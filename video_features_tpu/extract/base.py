"""Extractor runtime: the per-video loop every feature type shares.

This is the framework contract layer (SURVEY.md §1 L4). The reference
implements it as a ``torch.nn.Module`` per feature type with a uniform
shape — path list in ``__init__``, model built inside ``forward`` per
replica, per-video try/except, results routed to the output sink (e.g.
ref models/resnet/extract_resnet.py:25-71, models/CLIP/extract_clip.py:69-87).

The TPU-native equivalent: a plain class whose per-device state is a
lazily-built, cached bundle of jit-compiled functions + device-resident
params (``warmup``/``_build``); ``__call__(indices, device)`` runs the
video loop with the same error isolation and sink routing; the
``external_call`` mode returns feature dicts in-memory instead
(ref models/CLIP/extract_clip.py:22,73-77).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
from tqdm import tqdm

from video_features_tpu.config import as_config
from video_features_tpu.io.paths import form_list_from_user_input, video_path_of
from video_features_tpu.io.sink import action_on_extraction, expected_output_files
from video_features_tpu.runtime import faults
from video_features_tpu.runtime import telemetry as telemetry_mod
from video_features_tpu.runtime.faults import NULL_MANIFEST, RunManifest
from video_features_tpu.runtime.telemetry import Telemetry
from video_features_tpu.utils.profiling import device_trace


class BaseExtractor:
    """Subclasses set ``feature_type`` and implement ``_build`` + ``extract``."""

    feature_type: str = ""
    # True when _build accepts a jax.sharding.Mesh as ``device`` and runs
    # one GSPMD-sharded executable over it (--sharding mesh).
    mesh_capable: bool = False
    # True when the extractor additionally defines tensor-parallel param
    # specs, i.e. --mesh_model > 1 shards weights instead of replicating.
    mesh_tp_capable: bool = False
    # True when the extractor can run --mesh_context: its model has a
    # transformer token axis to shard, and its _build injects ring
    # attention (parallel/ring_attention.py) when the flag is set.
    mesh_context_capable: bool = False
    # what the preflight probe (io/probe.py) must find in an input:
    # 'video' for frame consumers, 'audio' for the VGGish path (a wav
    # is then legitimate and a RIFF/WAVE container is not a reject)
    media_need: str = "video"

    def __init__(self, config, external_call: bool = False) -> None:
        self.config = as_config(config)
        self.external_call = external_call
        if not self.feature_type:
            self.feature_type = self.config.feature_type
        self.path_list = form_list_from_user_input(self.config)
        self.progress = tqdm(total=len(self.path_list))
        # features land in <output_path>/<feature_type>/ unless output_direct
        # (ref models/CLIP/extract_clip.py:30-35)
        if self.config.output_direct:
            self.output_path = self.config.output_path
        else:
            self.output_path = os.path.join(self.config.output_path, self.feature_type)
        self.tmp_path = os.path.join(self.config.tmp_path, self.feature_type)
        self._device_state: Dict[Any, Any] = {}
        self._build_lock = threading.Lock()
        # --- fault tolerance (runtime/faults.py; docs/robustness.md) ---
        # The manifest roots at config.output_path (NOT the feature-
        # suffixed dir): one <output>/_manifest covers a multi-feature
        # output tree, and --resume merges across prior runs. Gated so a
        # casual print-mode/external run never litters ./output.
        wants_manifest = not external_call and (
            self.config.on_extraction in ("save_numpy", "save_pickle")
            or bool(getattr(self.config, "strict", False))
            or bool(getattr(self.config, "fault_inject", None))
        )
        self.manifest = (
            RunManifest(self.config.output_path) if wants_manifest else NULL_MANIFEST
        )
        # --- structured telemetry (runtime/telemetry.py; docs/observability.md)
        # Spans/metrics stream next to the manifest (<output>/_telemetry)
        # on save runs; external/print runs keep spans in memory so bench
        # passes can still compute overlap efficiency. '--telemetry off'
        # degrades span() to the bare StageTimer aggregate. self.timer
        # stays the span-backed StageTimer view, so --profile_dir's
        # summary print and existing tests are unchanged.
        wants_telemetry = getattr(self.config, "telemetry", "on") != "off"
        tele_root = self.config.output_path if (wants_manifest and wants_telemetry) else None
        self.telemetry = Telemetry(
            output_root=tele_root,
            enabled=wants_telemetry,
            heartbeat_s=(
                float(getattr(self.config, "heartbeat_s", 30.0) or 0.0)
                if tele_root is not None
                else 0.0
            ),
            total_videos=len(self.path_list),
        )
        self.timer = self.telemetry.timer
        telemetry_mod.set_current(self.telemetry)
        if (
            wants_telemetry
            and tele_root is not None
            and getattr(self.config, "preprocess", "host") == "device"
        ):
            # production recompile watch: jax_log_compiles -> compile
            # spans + ONE manifest warning per fn family exceeding its
            # committed per-bucket budget (analysis/compile_budget.json)
            self.telemetry.arm_recompile_watch(self.manifest)
        # --- device cost ledger (telemetry/ledger.py; docs/observability.md)
        # Save runs only (the same gate as the spans file): external/print
        # runs — the GC401 budget scenarios, parity tests — never pay the
        # analysis compile. warmup() wraps the built state dict so every
        # executable's memory_analysis/cost_analysis lands in the ledger
        # next to --compile_cache.
        self.ledger = None
        if wants_telemetry and tele_root is not None:
            from video_features_tpu.telemetry.ledger import (
                CostLedger,
                default_ledger_path,
            )

            self.ledger = CostLedger.shared(default_ledger_path(self.config))
        faults.install_injector(getattr(self.config, "fault_inject", None))
        from video_features_tpu.io.probe import ResourceCaps
        from video_features_tpu.io.video import set_decode_timeout, set_resource_caps

        set_decode_timeout(getattr(self.config, "decode_timeout", None))
        # --max_pixels/--max_duration_s/--max_decode_bytes: the running
        # decode budget every reader snapshots (io/video.py), plus the
        # declared-metadata caps the preflight probe checks
        self._resource_caps = ResourceCaps.from_config(self.config)
        set_resource_caps(self._resource_caps)
        self._t0: Dict[str, float] = {}  # video key -> attempt start
        # --preprocess device degradation: a thread-local force-host flag
        # lets ONE video's fallback re-prepare through the host chain
        # while other threads keep the device path
        self._force_host = threading.local()
        self._prior_failed: set = set()
        if (
            self.config.resume
            and not external_call
            and not getattr(self.config, "retry_failed", False)
        ):
            self._prior_failed = faults.permanently_failed_videos(
                self.config.output_path
            )
        # --- content-addressed feature cache (extract/cache.py; ISSUE 17)
        # Save runs only. Mesh sharding opts out: a per-process store
        # probe diverges on per-host filesystems exactly like
        # _already_done's local probe would, and every skip decision
        # there must be collective.
        self._feature_cache = None
        self._cache_digest: Optional[str] = None
        if (
            getattr(self.config, "cache_dir", None)
            and not external_call
            and self.config.on_extraction in ("save_numpy", "save_pickle")
            and getattr(self.config, "sharding", "queue") != "mesh"
        ):
            from video_features_tpu.extract.cache import (
                FeatureCache,
                config_digest,
            )

            self._feature_cache = FeatureCache(
                self.config.cache_dir,
                hash_mode=getattr(self.config, "cache_hash", "fast") or "fast",
            )
            self._cache_digest = config_digest(self.config)

    def feature_keys(self):
        """The keys a feats_dict will carry (used by --resume to probe for
        existing outputs). I3D overrides with its streams."""
        return [self.feature_type]

    def _fps_source(self, video_path: str):
        """(decode_path, selection_fps) under the --fps_retarget policy.

        nearest (default): decode the original and select frames on the
        native grid in-process (io/video._resample_indices) — no ffmpeg,
        no transcode. reencode: the reference's ffmpeg re-encode into
        --tmp_path (ref utils/utils.py:222-244) — the decode path becomes
        the re-encoded file, already on the target grid, so selection_fps
        is None. Used by the extractors whose reference path re-encodes
        (resnet*/raft/pwc; sanity_check restricts the flag to them)."""
        fps = self.config.extraction_fps
        if fps and getattr(self.config, "fps_retarget", "nearest") == "reencode":
            from video_features_tpu.io.ffmpeg import reencode_video_with_diff_fps

            with self.telemetry.span("reencode", video=str(video_path)):
                return (
                    reencode_video_with_diff_fps(
                        video_path,
                        self.tmp_path,
                        fps,
                        timeout_s=getattr(self.config, "decode_timeout", None),
                    ),
                    None,
                )
        return video_path, fps

    def _already_done(self, entry) -> bool:
        files = expected_output_files(
            self.feature_keys(),
            video_path_of(entry),
            self.output_path,
            self.config.on_extraction,
            self.config.output_direct,
        )
        done = bool(files) and all(os.path.exists(f) for f in files)
        # Multi-host MESH runs: only process 0 writes (see
        # _sink_or_collect), so a per-process local probe DIVERGES on
        # per-host filesystems — and every sharded dispatch is collective,
        # so one process skipping a video the others compute is a
        # deadlock. All processes take process 0's answer; this broadcast
        # is itself a collective, which is safe exactly because in mesh
        # mode every process probes every video in the same order. Queue
        # mode is the opposite: each process owns a DISJOINT video set in
        # its own order, so a collective here would hang/mismatch — the
        # local probe is the correct answer (advisor r4).
        from video_features_tpu.parallel.sharding import multihost

        if multihost() and self.config.sharding == "mesh":
            from jax.experimental import multihost_utils

            # the blocking collective IS the point: every process must
            # agree on the skip decision before any of them dispatches
            # (taint knows broadcast_one_to_all yields a HOST value)
            done = bool(
                multihost_utils.broadcast_one_to_all(np.int32(done))
            )
        return done

    # --- native host-preprocess decision (shared by the PIL-chain
    # extractors: ResNet's bilinear chain, CLIP's bicubic chain) ----------
    _use_native: Optional[bool] = None
    _native_threads: int = 1

    def _decide_native(self) -> None:
        if self.config.host_preprocess == "native":
            from video_features_tpu import native

            self._use_native = native.available()
            if not self._use_native:
                print(
                    f"native preprocess unavailable "
                    f"({native.build_error()}); using PIL"
                )
            else:
                # share the affinity-visible host cores across concurrent
                # device workers (native._resolve_threads re-clamps, so a
                # stale decision can never oversubscribe)
                from video_features_tpu.parallel.devices import resolve_devices

                n_workers = max(len(resolve_devices(self.config)), 1)
                self._native_threads = max(native.cpu_budget() // n_workers, 1)
        else:
            self._use_native = False

    def _native_decided(self) -> bool:
        """One-shot backend decision (and unavailability warning); the
        lock keeps it single-shot under concurrent decode workers."""
        with self._build_lock:
            if self._use_native is None:
                self._decide_native()
        return bool(self._use_native)

    def _device_preprocess_enabled(self) -> bool:
        """--preprocess device: the image-model extractors (CLIP, ResNet)
        ship raw uint8 frames and fuse resize/crop/normalize into the
        encoder dispatch (ops/preprocess.py::device_preprocess_frames).
        sanity_check restricts the flag to the extractors that honor it.

        False while this thread's ``_force_host`` flag is up: the
        compile-failure fallback re-prepares ONE video through the host
        chain (``_run_host_fallback``) without disturbing concurrent
        device-path prepares."""
        if getattr(self._force_host, "on", False):
            return False
        return getattr(self.config, "preprocess", "host") == "device"

    # --- per-device model state -------------------------------------------
    def _build(self, device) -> Any:
        """Build jitted fns + device-resident params for ``device``."""
        raise NotImplementedError

    def warmup(self, device) -> Any:
        """Build (once) and cache this device's model state. Thread-safe.
        On save runs the state dict's jitted callables are wrapped for
        the device cost ledger (telemetry/ledger.py): the first call per
        (fn family, signature) records the executable's flops/HBM facts
        via a one-time AOT analysis compile; every call still executes
        the original jit function."""
        key = device
        state = self._device_state.get(key)
        if state is None:
            with self._build_lock:
                state = self._device_state.get(key)
                if state is None:
                    state = self._build(device)
                    if self.ledger is not None:
                        from video_features_tpu.telemetry.ledger import (
                            instrument_state,
                        )

                        state = instrument_state(
                            state,
                            self.ledger,
                            model=self.feature_type,
                            sharding=getattr(self.config, "sharding", "queue"),
                            device=device,
                        )
                    self._device_state[key] = state
        return state

    # --- the video loop ----------------------------------------------------
    def _default_device(self):
        from video_features_tpu.parallel.devices import resolve_devices

        return resolve_devices(self.config)[0]

    def _supports_pipeline(self) -> bool:
        return type(self).prepare is not BaseExtractor.prepare

    def _sink_or_collect(self, feats_dict, entry, results, order: int = 0) -> None:
        """``order`` is the video's position in the caller's indices:
        external_call results are returned sorted by it, so aggregation's
        out-of-order completion (a full group can overtake an agg_key=None
        video, and vice versa) never reorders what the caller sees."""
        if self.external_call:
            results.append((order, feats_dict))
        else:
            # multi-host MESH runs: every process executes the same loop
            # on the same path list (the sharded dispatches are collective
            # — all hosts must participate), but exactly ONE writes the
            # output files. Features are replicated at graph exit
            # (parallel/sharding.py::multihost), so process 0 holds the
            # full arrays. Queue-mode multi-process runs are disjoint:
            # every process computed different videos and must sink its
            # own (advisor r4 — the old unconditional gate silently
            # dropped non-zero processes' outputs). Single-process runs:
            # process_index() == 0.
            import jax as _jax

            if self.config.sharding == "mesh" and _jax.process_index() != 0:
                return
            with self.telemetry.span("sink", video=self._video_key(entry)):
                warnings = action_on_extraction(
                    feats_dict,
                    video_path_of(entry),
                    self.output_path,
                    self.config.on_extraction,
                    self.config.output_direct,
                )
            for w in warnings or ():
                # empty-feature values etc.: recorded so --strict can
                # fail the run on them (ISSUE 3 satellite)
                self.manifest.record(
                    self._video_key(entry), "warning", stage="sink", message=w
                )
            self._cache_publish(entry)

    def _report_video_error(self, entry) -> None:
        """The per-video failure contract: print, continue, count the
        video as handled (shared by _isolate and the dispatch phase)."""
        print(f"An error occurred extracting {video_path_of(entry)}:")
        traceback.print_exc()
        print("Continuing...")
        self.progress.update()

    def _isolate(self, entry, fn, *args) -> None:
        """Per-video error isolation (ref extract_clip.py:78-84) with no
        manifest/retry semantics — the legacy contract, kept for callers
        outside the retrying loops."""
        try:
            fn(*args)
        except KeyboardInterrupt:
            raise
        except Exception:  # noqa: BLE001
            self._report_video_error(entry)
            return
        self.progress.update()

    # --- fault-tolerance bookkeeping (runtime/faults.py) -------------------
    def _video_key(self, entry) -> str:
        """Canonical manifest key for a path-list entry (flow entries are
        (rgb, flow-or-None) pairs; the rgb path identifies the video)."""
        vp = video_path_of(entry)
        if isinstance(vp, (list, tuple)):
            vp = vp[0]
        return str(vp)

    def _resume_skip_reason(self, entry) -> Optional[str]:
        """Why --resume would skip this video, or None to process it:
        outputs already on disk, or the manifest recorded a PERMANENT
        failure in a prior run (retrying bad bytes forever is the failure
        mode --retry_failed gates)."""
        if not self.config.resume or self.external_call:
            return None
        if self._video_key(entry) in self._prior_failed:
            return "prior permanent failure (pass --retry_failed to re-attempt)"
        if self._probe_done_safe(entry):
            return "outputs exist"
        return None

    def _skip(self, entry, reason: str) -> None:
        # Cross-host resume dedup (ISSUE 18): replicas resuming one
        # shared output root each probe the same finished videos; only
        # the winner of an O_EXCL claim file records the "skipped" line,
        # so fleet-level skip counts stay per-video, not per-replica.
        key = self._video_key(entry)
        if self.manifest is NULL_MANIFEST or faults.claim_skip_record(
            self.config.output_path, key
        ):
            self.manifest.record(key, "skipped", message=reason)
        self.progress.update()

    # --- content-addressed feature cache (extract/cache.py) ---------------
    def _cacheable_entry(self, entry) -> bool:
        """(rgb, flow-dir) pairs are uncacheable: the content hash covers
        only the rgb file, so a changed flow dir would serve stale
        features."""
        return not (
            isinstance(entry, (tuple, list)) and len(entry) > 1 and entry[1]
        )

    def _try_cache_hit(self, entry) -> bool:
        """Content-addressed short-circuit before any decode work: when
        the store holds this (content hash, config digest), materialize
        the payloads onto the expected output paths and count the video
        done (manifest note ``cache_hit``). Every cache-side failure —
        unreadable input, corrupt entry, vanished payload — is a miss;
        the real extraction path is always the fallback."""
        if self._feature_cache is None or not self._cacheable_entry(entry):
            return False
        video = self._video_key(entry)
        keys = self.feature_keys()
        try:
            chash = self._feature_cache.content_hash(video)
        except OSError:
            return False  # unreadable input: let the real path report it
        cached = self._feature_cache.lookup(chash, self._cache_digest, keys)
        if cached is not None:
            try:
                with self.telemetry.span("cache_hit", video=video):
                    self._feature_cache.materialize(
                        cached,
                        self._feature_cache.dest_files(
                            keys,
                            video,
                            self.output_path,
                            self.config.on_extraction,
                            self.config.output_direct,
                        ),
                    )
            except OSError:
                cached = None  # payload vanished mid-copy: treat as miss
        if cached is None:
            self.telemetry.metrics.inc(f"cache_miss.{self.feature_type}")
            return False
        self.telemetry.metrics.inc(f"cache_hit.{self.feature_type}")
        self._on_success(entry, 1, note="cache_hit")
        return True

    def _cache_publish(self, entry) -> None:
        """Populate the store from the files the sink just committed
        atomically. Claim-by-rename semantics: losing to a concurrent
        writer is a no-op, and any OSError leaves the store unchanged."""
        if self._feature_cache is None or not self._cacheable_entry(entry):
            return
        video = self._video_key(entry)
        try:
            chash = self._feature_cache.content_hash(video)
        except OSError:
            return
        dests = self._feature_cache.dest_files(
            self.feature_keys(),
            video,
            self.output_path,
            self.config.on_extraction,
            self.config.output_direct,
        )
        if not all(os.path.exists(p) for p in dests.values()):
            return
        self._feature_cache.publish(
            chash, self._cache_digest, dests, feature_type=self.feature_type
        )

    def _preflight_entry(self, entry) -> None:
        """The vouching stage before a video's FIRST attempt
        (``--preflight on``): probe the container, record caution
        warnings, and raise the probe's permanent taxonomy error on
        reject — so hostile media fails with a precise reason before a
        single retry (or any real decode work) is spent on it. Raises
        from inside prepare/extract try-blocks; ``_on_failure``
        classifies the error permanent and the stage 'preflight'."""
        if getattr(self.config, "preflight", "off") != "on":
            return
        from video_features_tpu.io import probe as probe_mod

        report = probe_mod.preflight(
            self._video_key(entry),
            need=self.media_need,
            caps=self._resource_caps,
        )
        for w in report.warnings:
            self.manifest.record(
                self._video_key(entry), "warning", stage="preflight", message=w
            )
        if report.verdict == "reject":
            raise report.to_error()

    def _drain_decode_warnings(self, entry) -> None:
        """Move this thread's accumulated decode notes (fps defaulted,
        partial decode — io/video.py) into the manifest as per-video
        warnings. Must run on the thread that decoded (the notes are
        thread-local), i.e. inside prep() / the serial loop."""
        from video_features_tpu.io.video import pop_decode_warnings

        for note in pop_decode_warnings():
            extra = {
                k: v for k, v in note.items() if k not in ("kind", "message")
            }
            self.manifest.record(
                self._video_key(entry),
                "warning",
                stage="decode",
                kind=note.get("kind"),
                message=note.get("message"),
                **extra,
            )

    def _mark_start(self, entry) -> None:
        self._t0[self._video_key(entry)] = time.monotonic()

    def _wall(self, entry) -> Optional[float]:
        t0 = self._t0.get(self._video_key(entry))
        return time.monotonic() - t0 if t0 is not None else None

    def _on_success(self, entry, attempt: int, note: Optional[str] = None) -> None:
        self.telemetry.metrics.inc("videos_done")
        extra = {"note": note} if note else {}
        self.manifest.record(
            self._video_key(entry),
            "done",
            attempts=attempt,
            wall_s=self._wall(entry),
            **extra,
        )
        self.progress.update()

    def _on_failure(
        self, entry, stage: str, attempt: int, requeue=None, fallback=None
    ) -> None:
        """The per-video failure policy, called from an ``except`` block
        (the live exception is read off sys.exc_info):

        - transient/oom AND attempts left AND the caller can requeue ->
          record ``retry`` and re-enter the work queue after
          :func:`faults.backoff_delay`;
        - compile AND the caller has a degradation path (device->host
          preprocess) -> record ``fallback`` and run it (the fallback
          records its own terminal outcome);
        - otherwise -> record ``failed`` and print the reference failure
          contract ("An error occurred ... Continuing...").

        An exception's own ``stage`` attribute (set by decode/injected
        errors) overrides the caller's coarser label — a DecodeTimeout
        surfacing from a prepare future is a decode failure."""
        exc = sys.exc_info()[1]
        stage = getattr(exc, "stage", None) or stage
        error_class = faults.classify_error(exc) if exc is not None else "permanent"
        video = self._video_key(entry)
        retries = int(getattr(self.config, "retries", 0) or 0)
        # the failing stage's span id (stamped by Telemetry.span on the
        # way out, innermost wins) links this manifest record to its
        # interval in _telemetry/spans-*.jsonl
        span_extra = {}
        span_id = getattr(exc, "telemetry_span", None)
        if span_id is not None:
            span_extra["span"] = span_id
        if (
            requeue is not None
            and faults.is_retryable(error_class)
            and attempt <= retries
        ):
            delay = faults.backoff_delay(
                attempt, float(getattr(self.config, "retry_backoff", 0.0)), video
            )
            self.telemetry.metrics.inc("retries")
            self.manifest.record(
                video,
                "retry",
                stage=stage,
                error_class=error_class,
                error_type=type(exc).__name__,
                message=str(exc),
                attempts=attempt,
                wall_s=self._wall(entry),
                **span_extra,
            )
            print(
                f"Transient {stage} failure for {video} (attempt "
                f"{attempt}/{retries + 1}): {type(exc).__name__}: {exc}; "
                f"retrying in {delay:.2f}s"
            )
            requeue(delay)
            return
        if error_class == "compile" and fallback is not None:
            self.manifest.record(
                video,
                "fallback",
                stage=stage,
                error_class=error_class,
                error_type=type(exc).__name__,
                message=str(exc),
                attempts=attempt,
                **span_extra,
            )
            fallback()
            return
        self.manifest.record(
            video,
            "failed",
            stage=stage,
            error_class=error_class,
            error_type=type(exc).__name__ if exc is not None else None,
            message=str(exc) if exc is not None else None,
            attempts=attempt,
            wall_s=self._wall(entry),
            **span_extra,
        )
        self._report_video_error(entry)

    def _fallback_closure(self, device, state, pos, attempt, entry, results):
        """The degradation path handed to ``_on_failure``: None unless
        this run uses --preprocess device (the only path with a second,
        differently-compiled program to fall back to)."""
        if not self._device_preprocess_enabled():
            return None

        def do() -> None:
            self._run_host_fallback(device, state, pos, attempt, entry, results)

        return do

    def _run_host_fallback(self, device, state, pos, attempt, entry, results) -> None:
        """Re-run ONE video through the host preprocess chain after its
        fused device-preprocess program failed to compile/lower. The
        extractors' state bundles always build both entry points (CLIP's
        encode_image + encode_raw, ResNet's forward + forward_raw), and
        prepare() branches on ``_device_preprocess_enabled()`` — so
        flipping the thread-local flag re-prepares a host payload that
        extract_prepared routes down the host branch."""
        video = self._video_key(entry)
        print(
            f"Device-preprocess compile failure for {video}; "
            f"falling back to the host chain"
        )
        self._force_host.on = True
        try:
            with self.telemetry.span("prepare", video=video, attempt=attempt):
                try:
                    payload = self.prepare(entry)
                finally:
                    self._drain_decode_warnings(entry)
            with self.telemetry.span("dispatch", video=video, attempt=attempt):
                self.telemetry.count_h2d(payload)
                feats_dict = self.extract_prepared(device, state, entry, payload)
            self._sink_or_collect(feats_dict, entry, results, pos)
        except KeyboardInterrupt:
            raise
        except Exception:  # noqa: BLE001 - fallback is terminal: no retry loop
            self._on_failure(entry, "dispatch", attempt)
            return
        finally:
            self._force_host.on = False
        self._on_success(entry, attempt, note="device->host preprocess fallback")

    def __call__(
        self,
        indices: Optional[Sequence[int]] = None,
        device=None,
    ) -> Optional[List[Dict[str, np.ndarray]]]:
        if indices is None:
            indices = range(len(self.path_list))
        if device is None:
            device = self._default_device()
        state = self.warmup(device)

        results: List = []  # external_call: (order, feats_dict) pairs
        indices = [int(i) for i in indices]
        pipelined = (
            self._supports_pipeline()
            and len(indices) > 1
            and int(self.config.decode_workers or 0) >= 1
        )
        with device_trace(self.config.profile_dir):
            if pipelined:
                self._run_pipelined(indices, device, state, results)
            else:
                self._run_serial(indices, device, state, results)
        # stage totals always land in summary.json via the telemetry
        # metrics snapshot (finalize_run merges them); the console print
        # stays opt-in behind --profile_dir
        self.telemetry.flush()
        if self.config.profile_dir:
            print(self.timer.summary())
        if self.external_call:
            return [d for _, d in sorted(results, key=lambda t: t[0])]
        return None

    def run_paths(
        self, entries: Sequence[Any], device=None
    ) -> Optional[List[Dict[str, np.ndarray]]]:
        """Run extraction over ``entries`` (paths, or (video, flow)
        tuples for disk-flow i3d) on an extractor that may already have
        processed other videos — the serve daemon's dispatch surface.

        Appends to ``path_list`` and runs the normal ``__call__`` loop
        over just the new indices, so the warm ``_device_state`` (loaded
        weights, per-bucket fused executables) is reused as-is: a group
        of same-bucket entries with ``--video_batch`` > 1 fuses exactly
        like a batch run's would, and retries/degradation/manifest all
        apply per entry. Extractors are built once per daemon lifetime
        and path_list grows monotonically; each entry is a fresh
        manifest identity even if the same path was run before."""
        entries = list(entries)
        if not entries:
            return [] if self.external_call else None
        start = len(self.path_list)
        self.path_list.extend(entries)
        self.progress.total = len(self.path_list)
        if self.telemetry.total_videos is not None:
            self.telemetry.total_videos = len(self.path_list)
        return self(range(start, len(self.path_list)), device)

    def _run_serial(self, indices, device, state, results) -> None:
        """The reference-shaped serial loop, now over a retry deque:
        transient failures re-enter the queue with their backoff deadline
        (``not_before``) instead of being dropped after one try."""
        from collections import deque

        wid = str(device)
        queue: deque = deque((pos, idx, 1, 0.0) for pos, idx in enumerate(indices))
        while queue:
            pos, idx, attempt, not_before = queue.popleft()
            entry = self.path_list[idx]
            if attempt == 1:
                reason = self._resume_skip_reason(entry)
                if reason is not None:
                    self._skip(entry, reason)
                    continue
                if self._try_cache_hit(entry):
                    continue
            wait = not_before - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            self._mark_start(entry)
            try:
                try:
                    if attempt == 1:
                        self._preflight_entry(entry)
                    with self.telemetry.span(
                        "extract", video=self._video_key(entry),
                        attempt=attempt, worker=wid,
                    ):
                        feats_dict = self.extract(device, state, entry)
                finally:
                    # serial mode decodes on this thread: the notes are here
                    self._drain_decode_warnings(entry)
                self._sink_or_collect(feats_dict, entry, results, pos)
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - classify, maybe retry

                def _requeue(delay, pos=pos, idx=idx, attempt=attempt):
                    queue.append((pos, idx, attempt + 1, time.monotonic() + delay))

                self._on_failure(entry, "extract", attempt, requeue=_requeue)
                continue
            self._on_success(entry, attempt)

    def _run_pipelined(self, indices, device, state, results) -> None:
        """Decode/preprocess on ``--decode_workers`` host threads, device
        compute on this thread, overlapped through a bounded window of
        in-flight ``prepare`` futures (SURVEY.md §7 hard part #5: the
        reference is decode-bound — ref extract_resnet.py:131-156 decodes
        inline between model calls, idling the accelerator).

        While video k's jitted forward runs (XLA dispatch is async; the
        blocking point is fetching its result), videos k+1..k+W are
        already decoding — the host/device double-buffer.

        With ``--video_batch N`` (and an agg-capable extractor), prepared
        videos whose batches share a static shape (``agg_key``) buffer up
        into groups of N and cross the device as ONE fused dispatch
        (``dispatch_group``/``fetch_group``) — N videos' frames fill one
        forward instead of N tiny ones. Up to N-1 prepared payloads per
        shape key stay host-resident while a group fills; extractors
        whose payloads can be large return ``agg_key=None`` above a size
        cap, which routes that video through the individual path.

        Dispatched work lands in a ``--inflight_groups``-deep
        CompletionQueue (extract/ingest.py): the drain blocks on the
        oldest entry only when the window is full, and opportunistically
        sinks any head whose device buffers already report ready — so
        group N+1's H2D (the dedicated ``transfer_group`` stage, timed
        under the ``h2d`` span) issues while group N computes, instead
        of the old lockstep dispatch-then-fetch turn-taking.

        Failure policy (runtime/faults.py; docs/robustness.md): every
        per-video failure goes through ``_on_failure`` — transient ones
        re-enter ``pending`` as a fresh prepare future after a
        timer-scheduled backoff (``requeue``; the timer, not a decode
        worker, owns the wait), compile failures under --preprocess
        device degrade to the host chain, fused-group failures fall
        back to per-video dispatch, and everything terminal lands in
        the run manifest."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        from video_features_tpu.extract import ingest

        workers = max(1, int(self.config.decode_workers))
        depth = workers + 1  # prepared-and-waiting beyond the one consumed
        wid = str(device)

        def prep(entry, attempt: int = 1):
            self._mark_start(entry)
            with self.telemetry.span(
                "prepare", video=self._video_key(entry),
                attempt=attempt, worker=wid,
            ):
                faults.fire("prepare")
                if attempt == 1:
                    # preflight on the decode worker, ahead of real
                    # decode: a reject surfaces from the future as a
                    # permanent 'preflight'-stage failure, zero retries
                    self._preflight_entry(entry)
                try:
                    return self.prepare(entry)
                finally:
                    # decode notes are thread-local to THIS worker
                    self._drain_decode_warnings(entry)

        pending: deque = deque()  # (pos, idx, attempt, fut)
        # device pipeline (extractors with the dispatch/fetch split): up
        # to --inflight_groups dispatched groups/videos stay in flight
        # while earlier results are fetched/sunk
        split = self._supports_device_pipeline()
        agg = self._aggregation_enabled()
        group_size = max(int(self.config.video_batch or 1), 1)
        groups: Dict[Any, list] = {}  # agg_key -> [(pos, idx, attempt, entry, payload)]
        # CompletionQueue entries: ([(pos, idx, attempt, entry), ...],
        # handle, grouped, payloads-or-None). Grouped entries keep their
        # HOST payloads resident until their drain succeeds, so a fused
        # failure can fall back to the solo path even when the staged
        # device copies were donated to the fused jit entry (at most
        # --inflight_groups groups' payloads stay pinned).
        inflight = ingest.CompletionQueue(
            max(int(getattr(self.config, "inflight_groups", 2) or 2), 1)
        )
        timers = ingest.RequeueTimers()

        def requeue(pos, idx, attempt):
            """Retry closure for _on_failure: resubmit a prepare future
            at attempt+1 once the backoff timer fires (the timer owns
            the wait — no decode worker sleeps). Retries during the
            final drain re-enter ``pending``, which the outer drain
            loop below keeps consuming; it also waits on
            ``timers.pending()`` so an armed retry cannot be stranded."""

            def do(delay: float) -> None:
                def fire() -> None:
                    pending.append(
                        (pos, idx, attempt + 1,
                         pool.submit(prep, self.path_list[idx], attempt + 1))
                    )

                timers.schedule(delay, fire)

            return do

        def sink_one(pos, idx, attempt, entry, feats_dict):
            try:
                self._sink_or_collect(feats_dict, entry, results, pos)
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - sink failed: this video only
                self._on_failure(
                    entry, "sink", attempt, requeue=requeue(pos, idx, attempt)
                )
                return
            self._on_success(entry, attempt)

        def run_solo(pos, idx, attempt, entry, payload, inject: bool = True):
            """The individual device path for one prepared video (shared
            by the non-split dispatch branch and the group fallback —
            which passes inject=False so the dispatch injection counter
            cannot re-fail the members it is recovering)."""
            try:
                if inject:
                    faults.fire("dispatch")
                with self.telemetry.span(
                    "dispatch", video=self._video_key(entry),
                    attempt=attempt, worker=wid,
                ):
                    self.telemetry.count_h2d(payload)
                    feats_dict = self.extract_prepared(device, state, entry, payload)
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - classify, maybe retry/degrade
                self._on_failure(
                    entry,
                    "dispatch",
                    attempt,
                    requeue=requeue(pos, idx, attempt),
                    fallback=self._fallback_closure(
                        device, state, pos, attempt, entry, results
                    ),
                )
                return
            sink_one(pos, idx, attempt, entry, feats_dict)

        def solo_fallback(items, phase, fused_err):
            """A fused dispatch/fetch died (OOM, one bad interaction):
            recover per-video isolation by re-running every member through
            the individual ``extract_prepared`` path, so at most the truly
            bad video is lost — matching the non-aggregated contract
            (advisor r03 medium). Callers format the traceback and exit
            their ``except`` block BEFORE calling this: a live exception
            would pin the failed group's device arrays via its traceback
            frames exactly while the re-runs contend for that HBM. The
            fused failure is still logged so a persistent group-path
            regression stays visible even when every member recovers."""
            print(
                f"Fused --video_batch {phase} failed for a group of "
                f"{len(items)}; falling back to per-video dispatch:"
            )
            print(fused_err, end="")
            self.manifest.event(
                "group_fallback",
                phase=phase,
                size=len(items),
                videos=[self._video_key(e) for _, _, _, e, _ in items],
                message=fused_err.strip().splitlines()[-1][:300] if fused_err else None,
            )
            for pos, idx, attempt, e, p in items:
                run_solo(pos, idx, attempt, e, p, inject=False)

        def drain_completed(only_ready: bool = False) -> bool:
            """Drain ONE entry from the completion queue: fetch its
            device results and sink them (the allowlisted GC10x/GC312
            host-sync boundary — this drain is where device values
            legitimately become host numpy). ``only_ready=True`` pops
            only if the head's device buffers already report complete
            (non-blocking probe), so the loop can sink finished work
            without stalling behind still-computing groups. Returns
            True when an entry was drained."""
            if only_ready and not inflight.head_ready():
                return False
            slots, handle, grouped, payloads = inflight.pop()
            self.telemetry.metrics.set_gauge("queue_depth.inflight", len(inflight))
            if grouped:
                fused_err = None
                try:
                    with self.telemetry.span(
                        "fetch", worker=wid, group_size=len(slots),
                    ):
                        dicts = self.fetch_group(handle)
                except KeyboardInterrupt:
                    raise
                except Exception:  # noqa: BLE001 - fused fetch fails together
                    fused_err = traceback.format_exc()
                if fused_err is not None:
                    # free the dead group's device buffers before the solo
                    # re-runs, or they contend for the HBM that may have
                    # caused the failure; the except block above has already
                    # exited, so no live traceback pins them either
                    del handle
                    solo_fallback(
                        [
                            (pos, idx, att, e, p)
                            for (pos, idx, att, e), p in zip(slots, payloads)
                        ],
                        "fetch",
                        fused_err,
                    )
                    return True
                for (pos, idx, att, e), d in zip(slots, dicts):
                    sink_one(pos, idx, att, e, d)
                return True
            pos, idx, attempt, entry = slots[0]
            try:
                with self.telemetry.span(
                    "fetch", video=self._video_key(entry),
                    attempt=attempt, worker=wid,
                ):
                    feats_dict = self.fetch_dispatched(handle)
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - classify, maybe retry/degrade
                self._on_failure(
                    entry,
                    "dispatch",
                    attempt,
                    requeue=requeue(pos, idx, attempt),
                    fallback=self._fallback_closure(
                        device, state, pos, attempt, entry, results
                    ),
                )
                return True
            sink_one(pos, idx, attempt, entry, feats_dict)
            return True

        def drain_to_capacity():
            """Post-dispatch drain policy: block on the oldest entry
            while the completion window is over capacity, then sink
            whatever else already finished without blocking."""
            while len(inflight) >= inflight.depth:
                drain_completed()
            while drain_completed(only_ready=True):
                pass

        def dispatch_group_now(items):  # items: [(pos, idx, attempt, entry, payload)]
            entries = [e for _, _, _, e, _ in items]
            payloads = [p for *_, p in items]
            fused_err = None
            try:
                # one dispatch-injection call per GROUP (the dispatch is
                # one device program); the OOM spec's split-then-recover
                # path is exactly this: fused raise -> solo_fallback
                faults.fire("dispatch")
                # dedicated transfer stage: assemble + device_put the
                # fused group under the h2d span (extractors without a
                # transfer_group return None and keep placement inside
                # dispatch_group, as before)
                with self.telemetry.span(
                    "h2d", worker=wid, group_size=len(items),
                ):
                    for p in payloads:
                        self.telemetry.count_h2d(p)
                    staged = self.transfer_group(device, state, entries, payloads)
                with self.telemetry.span(
                    "dispatch", worker=wid, group_size=len(items),
                ):
                    handle = self.dispatch_group(
                        device, state, entries,
                        staged if staged is not None else payloads,
                    )
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - fused transfer/dispatch fails together
                fused_err = traceback.format_exc()
            if fused_err is not None:
                solo_fallback(items, "dispatch", fused_err)
                return
            inflight.push(
                [(pos, idx, att, e) for pos, idx, att, e, _ in items],
                handle, True, payloads,
            )
            self.telemetry.metrics.set_gauge("queue_depth.inflight", len(inflight))
            drain_to_capacity()

        def dispatch_single(pos, idx, attempt, entry, payload):
            if split:
                try:
                    faults.fire("dispatch")
                    with self.telemetry.span(
                        "dispatch", video=self._video_key(entry),
                        attempt=attempt, worker=wid,
                    ):
                        self.telemetry.count_h2d(payload)
                        inflight.push(
                            [(pos, idx, attempt, entry)],
                            self.dispatch_prepared(device, state, entry, payload),
                            False,
                            None,
                        )
                        self.telemetry.metrics.set_gauge(
                            "queue_depth.inflight", len(inflight)
                        )
                except KeyboardInterrupt:
                    raise
                except Exception:  # noqa: BLE001 - classify, maybe retry/degrade
                    self._on_failure(
                        entry,
                        "dispatch",
                        attempt,
                        requeue=requeue(pos, idx, attempt),
                        fallback=self._fallback_closure(
                            device, state, pos, attempt, entry, results
                        ),
                    )
                drain_to_capacity()
                return

            run_solo(pos, idx, attempt, entry, payload)

        def consume_one():
            pos, idx, attempt, fut = pending.popleft()
            # queue-depth gauges: how full the host->device pipeline is
            # at each consume (pending prepare futures, buffered group
            # payloads, in-flight device dispatches)
            metrics = self.telemetry.metrics
            metrics.set_gauge("queue_depth.pending", len(pending))
            metrics.set_gauge("queue_depth.inflight", len(inflight))
            # 'prepared' = host-resident payloads waiting to dispatch
            # (the --video_batch group buffers); exposition renders it
            # as vft_queue_depth{queue="prepared"} and the heartbeat
            # line carries it next to 'inflight'
            metrics.set_gauge(
                "queue_depth.prepared",
                sum(len(b) for b in groups.values()) if agg else 0,
            )
            entry = self.path_list[idx]
            try:
                payload = fut.result()
                key = self.agg_key(payload) if agg else None
                if key is not None:
                    self.telemetry.note_bucket(key)
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - prepare/decode failed: classify
                # decode errors carry stage='decode' on the exception;
                # everything else surfacing from the future is 'prepare'
                self._on_failure(
                    entry, "prepare", attempt, requeue=requeue(pos, idx, attempt)
                )
                return
            if key is not None:
                buf = groups.setdefault(key, [])
                buf.append((pos, idx, attempt, entry, payload))
                if len(buf) >= group_size:
                    del groups[key]
                    dispatch_group_now(buf)
                return
            dispatch_single(pos, idx, attempt, entry, payload)

        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"decode-{device}"
        ) as pool:
            for pos, idx in enumerate(indices):
                entry = self.path_list[idx]
                reason = self._resume_skip_reason(entry)
                if reason is not None:
                    self._skip(entry, reason)
                    continue
                if self._try_cache_hit(entry):
                    continue
                pending.append((pos, idx, 1, pool.submit(prep, entry)))
                if len(pending) > depth:
                    consume_one()
            # retries re-enter `pending` from any of the drains below
            # (consume/dispatch/fetch/sink — possibly via a backoff
            # timer still armed), so the drain is ONE outer loop:
            # separate sequential drains would strand a video requeued
            # after its phase's drain had already passed, and ignoring
            # timers.pending() would exit with a retry still scheduled
            while pending or groups or inflight or timers.pending():
                while pending:
                    consume_one()
                for key in list(groups):  # flush partial groups (< N videos)
                    buf = groups.pop(key)
                    if buf:
                        dispatch_group_now(buf)
                while inflight and not pending:
                    drain_completed()
                if not (pending or groups or inflight):
                    # only armed backoff timers remain: park until one
                    # fires (bounded poll, not a busy spin)
                    timers.wait_any(0.05)

    def _probe_done_safe(self, entry) -> bool:
        try:
            return self._already_done(entry)
        except Exception:  # noqa: BLE001 - probe failure means "not done"
            return False

    # torch-API compatibility: the reference invokes extractors as modules
    forward = __call__

    def extract(self, device, state, path_entry) -> Dict[str, np.ndarray]:
        """Decode -> preprocess -> model -> {feature_type, fps, timestamps_ms}.

        Extractors that split into ``prepare`` + ``extract_prepared`` get
        this composition for free (and the pipelined path above)."""
        if self._supports_pipeline():
            return self.extract_prepared(
                device, state, path_entry, self.prepare(path_entry)
            )
        raise NotImplementedError

    def prepare(self, path_entry):
        """Host-side half: decode + preprocess into device-ready arrays.
        Override (with ``extract_prepared``) to enable the async host
        pipeline; must not touch jax/device state — it runs on decode
        worker threads."""
        raise NotImplementedError

    def extract_prepared(self, device, state, path_entry, payload):
        """Device-side half: consume ``prepare``'s payload. Extractors
        that split further into ``dispatch_prepared``+``fetch_dispatched``
        get this composition for free."""
        if self._supports_device_pipeline():
            return self.fetch_dispatched(
                self.dispatch_prepared(device, state, path_entry, payload)
            )
        raise NotImplementedError

    def _supports_device_pipeline(self) -> bool:
        return type(self).dispatch_prepared is not BaseExtractor.dispatch_prepared

    # --- cross-video aggregation (--video_batch) --------------------------
    def _supports_aggregation(self) -> bool:
        return type(self).dispatch_group is not BaseExtractor.dispatch_group

    def _aggregation_enabled(self) -> bool:
        return (
            self._supports_aggregation()
            and max(int(getattr(self.config, "video_batch", 1) or 1), 1) > 1
        )

    def agg_key(self, payload):
        """Hashable static-shape key for ``--video_batch`` grouping:
        payloads with equal keys may fuse into one dispatch. ``None``
        routes this video through the individual dispatch path (the
        extractor's opt-out for oversized payloads or show_pred)."""
        return None

    def dispatch_group(self, device, state, entries, payloads):
        """Fuse up to ``--video_batch`` same-key payloads into one
        transfer + jitted forward; return a handle without fetching.
        Implementations must pad the fused batch to the full-group shape
        so XLA compiles exactly one executable per agg_key."""
        raise NotImplementedError

    def fetch_group(self, handle):
        """Blocking half of ``dispatch_group``: fetch once, slice per
        video, return the feats_dicts in ``entries`` order."""
        raise NotImplementedError

    def transfer_group(self, device, state, entries, payloads):
        """Optional dedicated H2D stage for the fused --video_batch
        path: assemble the group's host arrays and issue the explicit
        device_put NOW (timed under the pipelined loop's ``h2d`` span),
        returning an ``ingest.StagedGroup`` that ``dispatch_group``
        consumes without touching host memory again — so the next
        group's transfer overlaps this group's compute, and fused jit
        entries may donate the staged buffers (``donate_argnums``:
        XLA reuses the uint8 ingest HBM in place). Return None (the
        default) to keep placement inside ``dispatch_group``. The host
        payloads stay resident in the completion queue either way, so
        the solo fallback survives donation."""
        return None

    def _note_windows_skipped(self, path_entry, skipped: int, total: int) -> None:
        """Frame-delta gating accounting (--frame_delta_threshold): the
        skip count rides the metrics registry (exposition renders it as
        ``vft_windows_skipped_total``) and the run manifest as a
        ``delta_gated`` note, so a gated run is auditable per video."""
        if skipped <= 0:
            return
        self.telemetry.metrics.inc("windows_skipped", skipped)
        self.manifest.event(
            "delta_gated",
            video=self._video_key(path_entry),
            skipped=skipped,
            total=total,
        )

    def _dispatch_rows_grouped(self, state, rows, chunk_rows):
        """Shared chunked re-dispatch for row-batched aggregation (ResNet
        frames, R21D stacks): concatenate the videos' valid rows, run one
        padded ``state['forward']`` per ``chunk_rows`` chunk (a single
        compiled shape per agg_key), return ``[(feats, n_valid)]``
        handles without fetching."""
        import numpy as _np

        from video_features_tpu.ops.window import pad_batch
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        all_rows = _np.concatenate(rows, axis=0)
        outs = []
        for i in range(0, all_rows.shape[0], chunk_rows):
            piece = all_rows[i : i + chunk_rows]
            n = piece.shape[0]
            x = pad_batch(piece, chunk_rows)
            x = pad_batch_for(state["device"], x)
            x = place_batch(x, state["device"])
            feats, _ = state["forward"](state["params"], x)
            outs.append((feats, n))
        return outs

    @staticmethod
    def _split_grouped_rows(outs, totals):
        """Fetch ``_dispatch_rows_grouped`` handles and split the row axis
        back into per-video arrays (``totals`` rows each, input order)."""
        import numpy as _np

        feats_cat = _np.concatenate([_np.asarray(f)[:n] for f, n in outs], axis=0)
        arrays, off = [], 0
        for total in totals:
            arrays.append(feats_cat[off : off + total])
            off += total
        return arrays

    def _prefetch_frame_cap(self, max_bytes: int, frame_bytes: int, floor: int) -> int:
        """Per-video prefetch cap in frames: the shared byte budget split
        over the decode_workers+2 resident prepared-video slots (advisor
        r02: flat frame caps scaled host RAM with the worker count)."""
        resident = max(int(self.config.decode_workers or 0), 1) + 2
        return max(max_bytes // resident // frame_bytes, floor)

    def dispatch_prepared(self, device, state, path_entry, payload):
        """Optional split of ``extract_prepared``: enqueue the host->HBM
        transfer and the jitted forward (XLA dispatch is async) and return
        a handle WITHOUT fetching results. The pipelined loop then starts
        video k+1's transfer+compute before blocking on video k's fetch —
        transfers and compute overlap the result fetch, which matters
        most when host<->device latency is high (tunnel, DCN)."""
        raise NotImplementedError

    def fetch_dispatched(self, handle):
        """Blocking half: fetch the dispatched results to host numpy and
        assemble the feats_dict."""
        raise NotImplementedError
