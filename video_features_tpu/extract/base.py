"""Extractor runtime: the per-video loop every feature type shares.

This is the framework contract layer (SURVEY.md §1 L4). The reference
implements it as a ``torch.nn.Module`` per feature type with a uniform
shape — path list in ``__init__``, model built inside ``forward`` per
replica, per-video try/except, results routed to the output sink (e.g.
ref models/resnet/extract_resnet.py:25-71, models/CLIP/extract_clip.py:69-87).

The TPU-native equivalent: a plain class whose per-device state is a
lazily-built, cached bundle of jit-compiled functions + device-resident
params (``warmup``/``_build``); ``__call__(indices, device)`` runs the
video loop with the same error isolation and sink routing; the
``external_call`` mode returns feature dicts in-memory instead
(ref models/CLIP/extract_clip.py:22,73-77).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
from tqdm import tqdm

from video_features_tpu.config import as_config
from video_features_tpu.io.paths import form_list_from_user_input, video_path_of
from video_features_tpu.io.sink import action_on_extraction, expected_output_files
from video_features_tpu.utils.profiling import StageTimer, device_trace


class BaseExtractor:
    """Subclasses set ``feature_type`` and implement ``_build`` + ``extract``."""

    feature_type: str = ""
    # True when _build accepts a jax.sharding.Mesh as ``device`` and runs
    # one GSPMD-sharded executable over it (--sharding mesh).
    mesh_capable: bool = False
    # True when the extractor additionally defines tensor-parallel param
    # specs, i.e. --mesh_model > 1 shards weights instead of replicating.
    mesh_tp_capable: bool = False
    # True when the extractor can run --mesh_context: its model has a
    # transformer token axis to shard, and its _build injects ring
    # attention (parallel/ring_attention.py) when the flag is set.
    mesh_context_capable: bool = False

    def __init__(self, config, external_call: bool = False) -> None:
        self.config = as_config(config)
        self.external_call = external_call
        if not self.feature_type:
            self.feature_type = self.config.feature_type
        self.path_list = form_list_from_user_input(self.config)
        self.progress = tqdm(total=len(self.path_list))
        # features land in <output_path>/<feature_type>/ unless output_direct
        # (ref models/CLIP/extract_clip.py:30-35)
        if self.config.output_direct:
            self.output_path = self.config.output_path
        else:
            self.output_path = os.path.join(self.config.output_path, self.feature_type)
        self.tmp_path = os.path.join(self.config.tmp_path, self.feature_type)
        self._device_state: Dict[Any, Any] = {}
        self._build_lock = threading.Lock()
        self.timer = StageTimer()

    def feature_keys(self):
        """The keys a feats_dict will carry (used by --resume to probe for
        existing outputs). I3D overrides with its streams."""
        return [self.feature_type]

    def _fps_source(self, video_path: str):
        """(decode_path, selection_fps) under the --fps_retarget policy.

        nearest (default): decode the original and select frames on the
        native grid in-process (io/video._resample_indices) — no ffmpeg,
        no transcode. reencode: the reference's ffmpeg re-encode into
        --tmp_path (ref utils/utils.py:222-244) — the decode path becomes
        the re-encoded file, already on the target grid, so selection_fps
        is None. Used by the extractors whose reference path re-encodes
        (resnet*/raft/pwc; sanity_check restricts the flag to them)."""
        fps = self.config.extraction_fps
        if fps and getattr(self.config, "fps_retarget", "nearest") == "reencode":
            from video_features_tpu.io.ffmpeg import reencode_video_with_diff_fps

            with self.timer.stage("reencode"):
                return (
                    reencode_video_with_diff_fps(video_path, self.tmp_path, fps),
                    None,
                )
        return video_path, fps

    def _already_done(self, entry) -> bool:
        files = expected_output_files(
            self.feature_keys(),
            video_path_of(entry),
            self.output_path,
            self.config.on_extraction,
            self.config.output_direct,
        )
        done = bool(files) and all(os.path.exists(f) for f in files)
        # Multi-host MESH runs: only process 0 writes (see
        # _sink_or_collect), so a per-process local probe DIVERGES on
        # per-host filesystems — and every sharded dispatch is collective,
        # so one process skipping a video the others compute is a
        # deadlock. All processes take process 0's answer; this broadcast
        # is itself a collective, which is safe exactly because in mesh
        # mode every process probes every video in the same order. Queue
        # mode is the opposite: each process owns a DISJOINT video set in
        # its own order, so a collective here would hang/mismatch — the
        # local probe is the correct answer (advisor r4).
        from video_features_tpu.parallel.sharding import multihost

        if multihost() and self.config.sharding == "mesh":
            from jax.experimental import multihost_utils

            done = bool(
                multihost_utils.broadcast_one_to_all(np.int32(done))
            )
        return done

    # --- native host-preprocess decision (shared by the PIL-chain
    # extractors: ResNet's bilinear chain, CLIP's bicubic chain) ----------
    _use_native: Optional[bool] = None
    _native_threads: int = 1

    def _decide_native(self) -> None:
        if self.config.host_preprocess == "native":
            from video_features_tpu import native

            self._use_native = native.available()
            if not self._use_native:
                print(
                    f"native preprocess unavailable "
                    f"({native.build_error()}); using PIL"
                )
            else:
                # share the affinity-visible host cores across concurrent
                # device workers (native._resolve_threads re-clamps, so a
                # stale decision can never oversubscribe)
                from video_features_tpu.parallel.devices import resolve_devices

                n_workers = max(len(resolve_devices(self.config)), 1)
                self._native_threads = max(native.cpu_budget() // n_workers, 1)
        else:
            self._use_native = False

    def _native_decided(self) -> bool:
        """One-shot backend decision (and unavailability warning); the
        lock keeps it single-shot under concurrent decode workers."""
        with self._build_lock:
            if self._use_native is None:
                self._decide_native()
        return bool(self._use_native)

    def _device_preprocess_enabled(self) -> bool:
        """--preprocess device: the image-model extractors (CLIP, ResNet)
        ship raw uint8 frames and fuse resize/crop/normalize into the
        encoder dispatch (ops/preprocess.py::device_preprocess_frames).
        sanity_check restricts the flag to the extractors that honor it."""
        return getattr(self.config, "preprocess", "host") == "device"

    # --- per-device model state -------------------------------------------
    def _build(self, device) -> Any:
        """Build jitted fns + device-resident params for ``device``."""
        raise NotImplementedError

    def warmup(self, device) -> Any:
        """Build (once) and cache this device's model state. Thread-safe."""
        key = device
        state = self._device_state.get(key)
        if state is None:
            with self._build_lock:
                state = self._device_state.get(key)
                if state is None:
                    state = self._build(device)
                    self._device_state[key] = state
        return state

    # --- the video loop ----------------------------------------------------
    def _default_device(self):
        from video_features_tpu.parallel.devices import resolve_devices

        return resolve_devices(self.config)[0]

    def _supports_pipeline(self) -> bool:
        return type(self).prepare is not BaseExtractor.prepare

    def _sink_or_collect(self, feats_dict, entry, results, order: int = 0) -> None:
        """``order`` is the video's position in the caller's indices:
        external_call results are returned sorted by it, so aggregation's
        out-of-order completion (a full group can overtake an agg_key=None
        video, and vice versa) never reorders what the caller sees."""
        if self.external_call:
            results.append((order, feats_dict))
        else:
            # multi-host MESH runs: every process executes the same loop
            # on the same path list (the sharded dispatches are collective
            # — all hosts must participate), but exactly ONE writes the
            # output files. Features are replicated at graph exit
            # (parallel/sharding.py::multihost), so process 0 holds the
            # full arrays. Queue-mode multi-process runs are disjoint:
            # every process computed different videos and must sink its
            # own (advisor r4 — the old unconditional gate silently
            # dropped non-zero processes' outputs). Single-process runs:
            # process_index() == 0.
            import jax as _jax

            if self.config.sharding == "mesh" and _jax.process_index() != 0:
                return
            with self.timer.stage("sink"):
                action_on_extraction(
                    feats_dict,
                    video_path_of(entry),
                    self.output_path,
                    self.config.on_extraction,
                    self.config.output_direct,
                )

    def _report_video_error(self, entry) -> None:
        """The per-video failure contract: print, continue, count the
        video as handled (shared by _isolate and the dispatch phase)."""
        print(f"An error occurred extracting {video_path_of(entry)}:")
        traceback.print_exc()
        print("Continuing...")
        self.progress.update()

    def _isolate(self, entry, fn, *args) -> None:
        """Per-video error isolation (ref extract_clip.py:78-84)."""
        try:
            fn(*args)
        except KeyboardInterrupt:
            raise
        except Exception:  # noqa: BLE001
            self._report_video_error(entry)
            return
        self.progress.update()

    def __call__(
        self,
        indices: Optional[Sequence[int]] = None,
        device=None,
    ) -> Optional[List[Dict[str, np.ndarray]]]:
        if indices is None:
            indices = range(len(self.path_list))
        if device is None:
            device = self._default_device()
        state = self.warmup(device)

        results: List = []  # external_call: (order, feats_dict) pairs
        indices = [int(i) for i in indices]
        pipelined = (
            self._supports_pipeline()
            and len(indices) > 1
            and int(self.config.decode_workers or 0) >= 1
        )
        with device_trace(self.config.profile_dir):
            if pipelined:
                self._run_pipelined(indices, device, state, results)
            else:
                for pos, idx in enumerate(indices):
                    entry = self.path_list[idx]

                    def one(entry=entry, pos=pos):
                        if (
                            self.config.resume
                            and not self.external_call
                            and self._already_done(entry)
                        ):
                            return
                        with self.timer.stage("extract"):
                            feats_dict = self.extract(device, state, entry)
                        self._sink_or_collect(feats_dict, entry, results, pos)

                    self._isolate(entry, one)
        if self.config.profile_dir:
            print(self.timer.summary())
        if self.external_call:
            return [d for _, d in sorted(results, key=lambda t: t[0])]
        return None

    def _run_pipelined(self, indices, device, state, results) -> None:
        """Decode/preprocess on ``--decode_workers`` host threads, device
        compute on this thread, overlapped through a bounded window of
        in-flight ``prepare`` futures (SURVEY.md §7 hard part #5: the
        reference is decode-bound — ref extract_resnet.py:131-156 decodes
        inline between model calls, idling the accelerator).

        While video k's jitted forward runs (XLA dispatch is async; the
        blocking point is fetching its result), videos k+1..k+W are
        already decoding — the host/device double-buffer.

        With ``--video_batch N`` (and an agg-capable extractor), prepared
        videos whose batches share a static shape (``agg_key``) buffer up
        into groups of N and cross the device as ONE fused dispatch
        (``dispatch_group``/``fetch_group``) — N videos' frames fill one
        forward instead of N tiny ones. Up to N-1 prepared payloads per
        shape key stay host-resident while a group fills; extractors
        whose payloads can be large return ``agg_key=None`` above a size
        cap, which routes that video through the individual path."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        workers = max(1, int(self.config.decode_workers))
        depth = workers + 1  # prepared-and-waiting beyond the one consumed

        def prep(entry):
            with self.timer.stage("prepare"):
                return self.prepare(entry)

        pending: deque = deque()
        # device pipeline (extractors with the dispatch/fetch split): one
        # video's transfer+compute stays in flight while the previous
        # video's results are fetched/sunk
        split = self._supports_device_pipeline()
        agg = self._aggregation_enabled()
        group_size = max(int(self.config.video_batch or 1), 1)
        groups: Dict[Any, list] = {}  # agg_key -> [(pos, entry, payload)]
        # ([(pos, entry), ...], handle, grouped, payloads-or-None); grouped
        # entries keep their payloads host-resident until fetch succeeds so
        # a fused failure can fall back to the solo path (inflight depth is
        # <=2, so at most two groups' payloads stay pinned)
        inflight: deque = deque()

        def run_solo(pos, entry, payload):
            """The individual device path for one prepared video (shared
            by the non-split dispatch branch and the group fallback)."""

            def one():
                with self.timer.stage("device"):
                    feats_dict = self.extract_prepared(device, state, entry, payload)
                self._sink_or_collect(feats_dict, entry, results, pos)

            self._isolate(entry, one)

        def solo_fallback(items, phase, fused_err):
            """A fused dispatch/fetch died (OOM, one bad interaction):
            recover per-video isolation by re-running every member through
            the individual ``extract_prepared`` path, so at most the truly
            bad video is lost — matching the non-aggregated contract
            (advisor r03 medium). Callers format the traceback and exit
            their ``except`` block BEFORE calling this: a live exception
            would pin the failed group's device arrays via its traceback
            frames exactly while the re-runs contend for that HBM. The
            fused failure is still logged so a persistent group-path
            regression stays visible even when every member recovers."""
            print(
                f"Fused --video_batch {phase} failed for a group of "
                f"{len(items)}; falling back to per-video dispatch:"
            )
            print(fused_err, end="")
            for pos, e, p in items:
                run_solo(pos, e, p)

        def fetch_one():
            slots, handle, grouped, payloads = inflight.popleft()
            if grouped:
                fused_err = None
                try:
                    with self.timer.stage("device"):
                        dicts = self.fetch_group(handle)
                except KeyboardInterrupt:
                    raise
                except Exception:  # noqa: BLE001 - fused fetch fails together
                    fused_err = traceback.format_exc()
                if fused_err is not None:
                    # free the dead group's device buffers before the solo
                    # re-runs, or they contend for the HBM that may have
                    # caused the failure; the except block above has already
                    # exited, so no live traceback pins them either
                    del handle
                    solo_fallback(
                        [(pos, e, p) for (pos, e), p in zip(slots, payloads)],
                        "fetch",
                        fused_err,
                    )
                    return
                for (pos, e), d in zip(slots, dicts):
                    self._isolate(e, self._sink_or_collect, d, e, results, pos)
                return
            pos, entry = slots[0]

            def one():
                with self.timer.stage("device"):
                    feats_dict = self.fetch_dispatched(handle)
                self._sink_or_collect(feats_dict, entry, results, pos)

            self._isolate(entry, one)

        def dispatch_group_now(items):  # items: [(pos, entry, payload)]
            entries = [e for _, e, _ in items]
            payloads = [p for _, _, p in items]
            fused_err = None
            try:
                with self.timer.stage("device"):
                    handle = self.dispatch_group(device, state, entries, payloads)
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - fused dispatch fails together
                fused_err = traceback.format_exc()
            if fused_err is not None:
                solo_fallback(items, "dispatch", fused_err)
                return
            inflight.append(
                ([(pos, e) for pos, e, _ in items], handle, True, payloads)
            )
            if len(inflight) > 1:
                fetch_one()

        def dispatch_single(pos, entry, payload):
            if split:
                try:
                    with self.timer.stage("device"):
                        inflight.append(
                            (
                                [(pos, entry)],
                                self.dispatch_prepared(device, state, entry, payload),
                                False,
                                None,
                            )
                        )
                except KeyboardInterrupt:
                    raise
                except Exception:  # noqa: BLE001 - same per-video isolation
                    self._report_video_error(entry)
                if len(inflight) > 1:
                    fetch_one()
                return

            run_solo(pos, entry, payload)

        def consume_one():
            pos, idx, fut = pending.popleft()
            entry = self.path_list[idx]
            try:
                payload = fut.result()
                key = self.agg_key(payload) if agg else None
            except KeyboardInterrupt:
                raise
            except Exception:  # noqa: BLE001 - prepare failed: this video only
                self._report_video_error(entry)
                return
            if key is not None:
                buf = groups.setdefault(key, [])
                buf.append((pos, entry, payload))
                if len(buf) >= group_size:
                    del groups[key]
                    dispatch_group_now(buf)
                return
            dispatch_single(pos, entry, payload)

        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"decode-{device}"
        ) as pool:
            for pos, idx in enumerate(indices):
                entry = self.path_list[idx]
                if (
                    self.config.resume
                    and not self.external_call
                    and self._probe_done_safe(entry)
                ):
                    self.progress.update()
                    continue
                pending.append((pos, idx, pool.submit(prep, entry)))
                if len(pending) > depth:
                    consume_one()
            while pending:
                consume_one()
            for buf in groups.values():  # flush partial groups (< N videos)
                if buf:
                    dispatch_group_now(buf)
            groups.clear()
            while inflight:
                fetch_one()

    def _probe_done_safe(self, entry) -> bool:
        try:
            return self._already_done(entry)
        except Exception:  # noqa: BLE001 - probe failure means "not done"
            return False

    # torch-API compatibility: the reference invokes extractors as modules
    forward = __call__

    def extract(self, device, state, path_entry) -> Dict[str, np.ndarray]:
        """Decode -> preprocess -> model -> {feature_type, fps, timestamps_ms}.

        Extractors that split into ``prepare`` + ``extract_prepared`` get
        this composition for free (and the pipelined path above)."""
        if self._supports_pipeline():
            return self.extract_prepared(
                device, state, path_entry, self.prepare(path_entry)
            )
        raise NotImplementedError

    def prepare(self, path_entry):
        """Host-side half: decode + preprocess into device-ready arrays.
        Override (with ``extract_prepared``) to enable the async host
        pipeline; must not touch jax/device state — it runs on decode
        worker threads."""
        raise NotImplementedError

    def extract_prepared(self, device, state, path_entry, payload):
        """Device-side half: consume ``prepare``'s payload. Extractors
        that split further into ``dispatch_prepared``+``fetch_dispatched``
        get this composition for free."""
        if self._supports_device_pipeline():
            return self.fetch_dispatched(
                self.dispatch_prepared(device, state, path_entry, payload)
            )
        raise NotImplementedError

    def _supports_device_pipeline(self) -> bool:
        return type(self).dispatch_prepared is not BaseExtractor.dispatch_prepared

    # --- cross-video aggregation (--video_batch) --------------------------
    def _supports_aggregation(self) -> bool:
        return type(self).dispatch_group is not BaseExtractor.dispatch_group

    def _aggregation_enabled(self) -> bool:
        return (
            self._supports_aggregation()
            and max(int(getattr(self.config, "video_batch", 1) or 1), 1) > 1
        )

    def agg_key(self, payload):
        """Hashable static-shape key for ``--video_batch`` grouping:
        payloads with equal keys may fuse into one dispatch. ``None``
        routes this video through the individual dispatch path (the
        extractor's opt-out for oversized payloads or show_pred)."""
        return None

    def dispatch_group(self, device, state, entries, payloads):
        """Fuse up to ``--video_batch`` same-key payloads into one
        transfer + jitted forward; return a handle without fetching.
        Implementations must pad the fused batch to the full-group shape
        so XLA compiles exactly one executable per agg_key."""
        raise NotImplementedError

    def fetch_group(self, handle):
        """Blocking half of ``dispatch_group``: fetch once, slice per
        video, return the feats_dicts in ``entries`` order."""
        raise NotImplementedError

    def _dispatch_rows_grouped(self, state, rows, chunk_rows):
        """Shared chunked re-dispatch for row-batched aggregation (ResNet
        frames, R21D stacks): concatenate the videos' valid rows, run one
        padded ``state['forward']`` per ``chunk_rows`` chunk (a single
        compiled shape per agg_key), return ``[(feats, n_valid)]``
        handles without fetching."""
        import numpy as _np

        from video_features_tpu.ops.window import pad_batch
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        all_rows = _np.concatenate(rows, axis=0)
        outs = []
        for i in range(0, all_rows.shape[0], chunk_rows):
            piece = all_rows[i : i + chunk_rows]
            n = piece.shape[0]
            x = pad_batch(piece, chunk_rows)
            x = pad_batch_for(state["device"], x)
            x = place_batch(x, state["device"])
            feats, _ = state["forward"](state["params"], x)
            outs.append((feats, n))
        return outs

    @staticmethod
    def _split_grouped_rows(outs, totals):
        """Fetch ``_dispatch_rows_grouped`` handles and split the row axis
        back into per-video arrays (``totals`` rows each, input order)."""
        import numpy as _np

        feats_cat = _np.concatenate([_np.asarray(f)[:n] for f, n in outs], axis=0)
        arrays, off = [], 0
        for total in totals:
            arrays.append(feats_cat[off : off + total])
            off += total
        return arrays

    def _prefetch_frame_cap(self, max_bytes: int, frame_bytes: int, floor: int) -> int:
        """Per-video prefetch cap in frames: the shared byte budget split
        over the decode_workers+2 resident prepared-video slots (advisor
        r02: flat frame caps scaled host RAM with the worker count)."""
        resident = max(int(self.config.decode_workers or 0), 1) + 2
        return max(max_bytes // resident // frame_bytes, floor)

    def dispatch_prepared(self, device, state, path_entry, payload):
        """Optional split of ``extract_prepared``: enqueue the host->HBM
        transfer and the jitted forward (XLA dispatch is async) and return
        a handle WITHOUT fetching results. The pipelined loop then starts
        video k+1's transfer+compute before blocking on video k's fetch —
        transfers and compute overlap the result fetch, which matters
        most when host<->device latency is high (tunnel, DCN)."""
        raise NotImplementedError

    def fetch_dispatched(self, handle):
        """Blocking half: fetch the dispatched results to host numpy and
        assemble the feats_dict."""
        raise NotImplementedError
