"""Extractor runtime: the per-video loop every feature type shares.

This is the framework contract layer (SURVEY.md §1 L4). The reference
implements it as a ``torch.nn.Module`` per feature type with a uniform
shape — path list in ``__init__``, model built inside ``forward`` per
replica, per-video try/except, results routed to the output sink (e.g.
ref models/resnet/extract_resnet.py:25-71, models/CLIP/extract_clip.py:69-87).

The TPU-native equivalent: a plain class whose per-device state is a
lazily-built, cached bundle of jit-compiled functions + device-resident
params (``warmup``/``_build``); ``__call__(indices, device)`` runs the
video loop with the same error isolation and sink routing; the
``external_call`` mode returns feature dicts in-memory instead
(ref models/CLIP/extract_clip.py:22,73-77).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
from tqdm import tqdm

from video_features_tpu.config import as_config
from video_features_tpu.io.paths import form_list_from_user_input, video_path_of
from video_features_tpu.io.sink import action_on_extraction, expected_output_files
from video_features_tpu.utils.profiling import StageTimer, device_trace


class BaseExtractor:
    """Subclasses set ``feature_type`` and implement ``_build`` + ``extract``."""

    feature_type: str = ""
    # True when _build accepts a jax.sharding.Mesh as ``device`` and runs
    # one GSPMD-sharded executable over it (--sharding mesh).
    mesh_capable: bool = False
    # True when the extractor additionally defines tensor-parallel param
    # specs, i.e. --mesh_model > 1 shards weights instead of replicating.
    mesh_tp_capable: bool = False
    # True when the extractor can run --mesh_context: its model has a
    # transformer token axis to shard, and its _build injects ring
    # attention (parallel/ring_attention.py) when the flag is set.
    mesh_context_capable: bool = False

    def __init__(self, config, external_call: bool = False) -> None:
        self.config = as_config(config)
        self.external_call = external_call
        if not self.feature_type:
            self.feature_type = self.config.feature_type
        self.path_list = form_list_from_user_input(self.config)
        self.progress = tqdm(total=len(self.path_list))
        # features land in <output_path>/<feature_type>/ unless output_direct
        # (ref models/CLIP/extract_clip.py:30-35)
        if self.config.output_direct:
            self.output_path = self.config.output_path
        else:
            self.output_path = os.path.join(self.config.output_path, self.feature_type)
        self.tmp_path = os.path.join(self.config.tmp_path, self.feature_type)
        self._device_state: Dict[Any, Any] = {}
        self._build_lock = threading.Lock()
        self.timer = StageTimer()

    def feature_keys(self):
        """The keys a feats_dict will carry (used by --resume to probe for
        existing outputs). I3D overrides with its streams."""
        return [self.feature_type]

    def _already_done(self, entry) -> bool:
        files = expected_output_files(
            self.feature_keys(),
            video_path_of(entry),
            self.output_path,
            self.config.on_extraction,
            self.config.output_direct,
        )
        return bool(files) and all(os.path.exists(f) for f in files)

    # --- native host-preprocess decision (shared by the PIL-chain
    # extractors: ResNet's bilinear chain, CLIP's bicubic chain) ----------
    _use_native: Optional[bool] = None
    _native_threads: int = 1

    def _decide_native(self) -> None:
        if self.config.host_preprocess == "native":
            from video_features_tpu import native

            self._use_native = native.available()
            if not self._use_native:
                print(
                    f"native preprocess unavailable "
                    f"({native.build_error()}); using PIL"
                )
            else:
                # share host cores across concurrent device workers
                from video_features_tpu.parallel.devices import resolve_devices

                n_workers = max(len(resolve_devices(self.config)), 1)
                self._native_threads = max((os.cpu_count() or 1) // n_workers, 1)
        else:
            self._use_native = False

    def _native_decided(self) -> bool:
        """One-shot backend decision (and unavailability warning); the
        lock keeps it single-shot under concurrent decode workers."""
        with self._build_lock:
            if self._use_native is None:
                self._decide_native()
        return bool(self._use_native)

    # --- per-device model state -------------------------------------------
    def _build(self, device) -> Any:
        """Build jitted fns + device-resident params for ``device``."""
        raise NotImplementedError

    def warmup(self, device) -> Any:
        """Build (once) and cache this device's model state. Thread-safe."""
        key = device
        state = self._device_state.get(key)
        if state is None:
            with self._build_lock:
                state = self._device_state.get(key)
                if state is None:
                    state = self._build(device)
                    self._device_state[key] = state
        return state

    # --- the video loop ----------------------------------------------------
    def _default_device(self):
        from video_features_tpu.parallel.devices import resolve_devices

        return resolve_devices(self.config)[0]

    def _supports_pipeline(self) -> bool:
        return type(self).prepare is not BaseExtractor.prepare

    def _sink_or_collect(self, feats_dict, entry, results) -> None:
        if self.external_call:
            results.append(feats_dict)
        else:
            with self.timer.stage("sink"):
                action_on_extraction(
                    feats_dict,
                    video_path_of(entry),
                    self.output_path,
                    self.config.on_extraction,
                    self.config.output_direct,
                )

    def _report_video_error(self, entry) -> None:
        """The per-video failure contract: print, continue, count the
        video as handled (shared by _isolate and the dispatch phase)."""
        print(f"An error occurred extracting {video_path_of(entry)}:")
        traceback.print_exc()
        print("Continuing...")
        self.progress.update()

    def _isolate(self, entry, fn, *args) -> None:
        """Per-video error isolation (ref extract_clip.py:78-84)."""
        try:
            fn(*args)
        except KeyboardInterrupt:
            raise
        except Exception:  # noqa: BLE001
            self._report_video_error(entry)
            return
        self.progress.update()

    def __call__(
        self,
        indices: Optional[Sequence[int]] = None,
        device=None,
    ) -> Optional[List[Dict[str, np.ndarray]]]:
        if indices is None:
            indices = range(len(self.path_list))
        if device is None:
            device = self._default_device()
        state = self.warmup(device)

        results: List[Dict[str, np.ndarray]] = []
        indices = [int(i) for i in indices]
        pipelined = (
            self._supports_pipeline()
            and len(indices) > 1
            and int(self.config.decode_workers or 0) >= 1
        )
        with device_trace(self.config.profile_dir):
            if pipelined:
                self._run_pipelined(indices, device, state, results)
            else:
                for idx in indices:
                    entry = self.path_list[idx]

                    def one(entry=entry):
                        if (
                            self.config.resume
                            and not self.external_call
                            and self._already_done(entry)
                        ):
                            return
                        with self.timer.stage("extract"):
                            feats_dict = self.extract(device, state, entry)
                        self._sink_or_collect(feats_dict, entry, results)

                    self._isolate(entry, one)
        if self.config.profile_dir:
            print(self.timer.summary())
        if self.external_call:
            return results
        return None

    def _run_pipelined(self, indices, device, state, results) -> None:
        """Decode/preprocess on ``--decode_workers`` host threads, device
        compute on this thread, overlapped through a bounded window of
        in-flight ``prepare`` futures (SURVEY.md §7 hard part #5: the
        reference is decode-bound — ref extract_resnet.py:131-156 decodes
        inline between model calls, idling the accelerator).

        While video k's jitted forward runs (XLA dispatch is async; the
        blocking point is fetching its result), videos k+1..k+W are
        already decoding — the host/device double-buffer."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        workers = max(1, int(self.config.decode_workers))
        depth = workers + 1  # prepared-and-waiting beyond the one consumed

        def prep(entry):
            with self.timer.stage("prepare"):
                return self.prepare(entry)

        pending: deque = deque()
        # device pipeline (extractors with the dispatch/fetch split): one
        # video's transfer+compute stays in flight while the previous
        # video's results are fetched/sunk
        split = self._supports_device_pipeline()
        inflight: deque = deque()  # (entry, handle)

        def fetch_one():
            entry, handle = inflight.popleft()

            def one():
                with self.timer.stage("device"):
                    feats_dict = self.fetch_dispatched(handle)
                self._sink_or_collect(feats_dict, entry, results)

            self._isolate(entry, one)

        def consume_one():
            idx, fut = pending.popleft()
            entry = self.path_list[idx]
            if split:
                try:
                    payload = fut.result()
                    with self.timer.stage("device"):
                        inflight.append(
                            (entry, self.dispatch_prepared(device, state, entry, payload))
                        )
                except KeyboardInterrupt:
                    raise
                except Exception:  # noqa: BLE001 - same per-video isolation
                    self._report_video_error(entry)
                if len(inflight) > 1:
                    fetch_one()
                return

            def one():
                payload = fut.result()
                with self.timer.stage("device"):
                    feats_dict = self.extract_prepared(device, state, entry, payload)
                self._sink_or_collect(feats_dict, entry, results)

            self._isolate(entry, one)

        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"decode-{device}"
        ) as pool:
            for idx in indices:
                entry = self.path_list[idx]
                if (
                    self.config.resume
                    and not self.external_call
                    and self._probe_done_safe(entry)
                ):
                    self.progress.update()
                    continue
                pending.append((idx, pool.submit(prep, entry)))
                if len(pending) > depth:
                    consume_one()
            while pending:
                consume_one()
            while inflight:
                fetch_one()

    def _probe_done_safe(self, entry) -> bool:
        try:
            return self._already_done(entry)
        except Exception:  # noqa: BLE001 - probe failure means "not done"
            return False

    # torch-API compatibility: the reference invokes extractors as modules
    forward = __call__

    def extract(self, device, state, path_entry) -> Dict[str, np.ndarray]:
        """Decode -> preprocess -> model -> {feature_type, fps, timestamps_ms}.

        Extractors that split into ``prepare`` + ``extract_prepared`` get
        this composition for free (and the pipelined path above)."""
        if self._supports_pipeline():
            return self.extract_prepared(
                device, state, path_entry, self.prepare(path_entry)
            )
        raise NotImplementedError

    def prepare(self, path_entry):
        """Host-side half: decode + preprocess into device-ready arrays.
        Override (with ``extract_prepared``) to enable the async host
        pipeline; must not touch jax/device state — it runs on decode
        worker threads."""
        raise NotImplementedError

    def extract_prepared(self, device, state, path_entry, payload):
        """Device-side half: consume ``prepare``'s payload. Extractors
        that split further into ``dispatch_prepared``+``fetch_dispatched``
        get this composition for free."""
        if self._supports_device_pipeline():
            return self.fetch_dispatched(
                self.dispatch_prepared(device, state, path_entry, payload)
            )
        raise NotImplementedError

    def _supports_device_pipeline(self) -> bool:
        return type(self).dispatch_prepared is not BaseExtractor.dispatch_prepared

    def dispatch_prepared(self, device, state, path_entry, payload):
        """Optional split of ``extract_prepared``: enqueue the host->HBM
        transfer and the jitted forward (XLA dispatch is async) and return
        a handle WITHOUT fetching results. The pipelined loop then starts
        video k+1's transfer+compute before blocking on video k's fetch —
        transfers and compute overlap the result fetch, which matters
        most when host<->device latency is high (tunnel, DCN)."""
        raise NotImplementedError

    def fetch_dispatched(self, handle):
        """Blocking half: fetch the dispatched results to host numpy and
        assemble the feats_dict."""
        raise NotImplementedError
