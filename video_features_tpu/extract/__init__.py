from video_features_tpu.extract.base import BaseExtractor  # noqa: F401
from video_features_tpu.extract.registry import build_extractor  # noqa: F401
