"""Shared-ingest planner: decode once, fan raw frames out to N models.

A CLIP+I3D+VGGish request for one video used to decode the file once
per model — the one-model-per-run architecture inherited from the
reference CLI. This module inverts it for the video extractors: a
byte-budgeted :class:`SharedFrameCache` holds each clip's full decoded
RGB frame list (plus the reader's fps/frame-count metadata), and
io/video.py's samplers consult it through the ``set_frame_cache`` hook
before opening a reader. The first toucher decodes ALL frames through
ONE reader (one ``decode`` telemetry span, the decode-once assertion
tests and bench pin); every later sampler — any model, any sampling
grid — replays the cached list with zero container opens.

Replay is bit-identical to direct decode by construction: a reader's
``retrieve()`` bytes do not depend on which frames a sampler keeps
(grab does the decode; retrieve only color-converts), so serving
``frames[target]`` from the cached list yields exactly the array the
sampler would have retrieved. tests/test_cache.py pins CLIP+ResNet
fan-out outputs bit-identical to their single-model runs.

The cache is installed around a scope — :func:`run_multi` for batch
fan-out, the serve daemon for its lifetime — and entries are LRU-
evicted under the ``--ingest_cache_mb`` byte budget. A clip too big
for the budget is decoded directly (never cached, never split).

Audio extractors (VGGish) read wav files through soundfile, not
io/video.py, so the frame cache never sees them; their repeat traffic
is served by the content-addressed feature cache instead
(extract/cache.py — the hash memo covers the wav bytes).
"""

from __future__ import annotations

import contextlib
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple


class CachedClip:
    """One fully-decoded clip: the frame list plus the reader metadata
    the samplers need (fps 0.0 when the container declared none — the
    consumer applies the same recorded 25.0 default as a live reader).
    Frames are marked read-only: N extractors share these arrays."""

    __slots__ = ("frames", "fps", "frame_count", "width", "height", "nbytes")

    def __init__(self, frames, fps, frame_count, width, height):
        for f in frames:
            f.setflags(write=False)
        self.frames: Tuple = tuple(frames)
        self.fps = float(fps)
        self.frame_count = int(frame_count)
        self.width = int(width)
        self.height = int(height)
        self.nbytes = sum(int(f.nbytes) for f in self.frames)


class SharedFrameCache:
    """Byte-budgeted LRU of :class:`CachedClip` keyed by
    (abspath, size, mtime_ns) — a re-encoded file under the same name
    can never serve stale frames.

    Thread contract (decode workers hit this concurrently): the map is
    lock-guarded; a per-key in-flight latch makes concurrent first
    touchers of the SAME clip decode it once (losers wait, timed, then
    re-check), while different clips decode in parallel. A builder
    that fails or exceeds the budget clears its latch and waiters fall
    back to direct decode — nobody blocks forever on a latch no one
    will set."""

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._clips: "OrderedDict[tuple, CachedClip]" = OrderedDict()
        self._inflight: Dict[tuple, threading.Event] = {}
        self._bytes = 0
        self._hits = 0
        self._populated = 0
        self._evicted = 0

    def _key(self, path: str) -> tuple:
        st = os.stat(path)
        return (os.path.abspath(path), st.st_size, st.st_mtime_ns)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "clips": len(self._clips),
                "bytes": self._bytes,
                "hits": self._hits,
                "populated": self._populated,
                "evicted": self._evicted,
            }

    def acquire(self, path: str, decoder: Optional[str] = None) -> Optional[CachedClip]:
        """The cached clip for ``path``, populating on first touch.
        None means "decode directly": unstatable path, over-budget
        clip, or a concurrent builder that hasn't finished in time.
        Decode errors (corrupt container, timeout, resource caps)
        propagate exactly as a direct open would raise them."""
        try:
            key = self._key(path)
        except OSError:
            return None
        with self._lock:
            clip = self._clips.get(key)
            if clip is not None:
                self._clips.move_to_end(key)
                self._hits += 1
                return clip
            latch = self._inflight.get(key)
            if latch is None:
                latch = self._inflight[key] = threading.Event()
                building = True
            else:
                building = False
        if not building:
            latch.wait(60.0)
            with self._lock:
                clip = self._clips.get(key)
                if clip is not None:
                    self._clips.move_to_end(key)
                    self._hits += 1
                return clip  # None -> caller decodes directly
        clip = None
        try:
            clip = self._decode_all(path, decoder)
        finally:
            with self._lock:
                self._inflight.pop(key, None)
                if clip is not None:
                    self._store(key, clip)
            latch.set()
        return clip

    def _store(self, key: tuple, clip: CachedClip) -> None:
        # caller holds self._lock
        if clip.nbytes > self.max_bytes:
            return
        self._clips[key] = clip
        self._bytes += clip.nbytes
        self._populated += 1
        while self._bytes > self.max_bytes and len(self._clips) > 1:
            _, old = self._clips.popitem(last=False)
            self._bytes -= old.nbytes
            self._evicted += 1

    def _decode_all(self, path: str, decoder: Optional[str]) -> Optional[CachedClip]:
        from video_features_tpu.io import video as vio

        frames: List = []
        total = 0
        with vio._Reader(path, decoder) as r:
            fps, declared = r.fps, r.frame_count
            width, height = r.width, r.height
            while r.grab():
                frame = r.retrieve()
                if frame is None:
                    break
                frames.append(frame)
                total += int(frame.nbytes)
                if total > self.max_bytes:
                    # too big to share: abandon (the partial prefix is
                    # useless — replay must cover the whole stream) and
                    # let every sampler decode this clip directly
                    return None
        return CachedClip(frames, fps, declared, width, height)


def cache_for(cfg, feature_types) -> Optional[SharedFrameCache]:
    """The shared-decode cache a run should install: only a multi-model
    scope can amortize a decode, and ``--ingest_cache_mb 0`` opts out."""
    budget_mb = int(getattr(cfg, "ingest_cache_mb", 0) or 0)
    if budget_mb <= 0 or len(list(feature_types)) < 2:
        return None
    return SharedFrameCache(budget_mb << 20)


@contextlib.contextmanager
def shared_frame_cache(cfg, feature_types):
    """Install the shared-decode cache into io/video.py for the scope
    of a fan-out run; always uninstalled on exit so a crashed run
    cannot leak frame memory into the next."""
    from video_features_tpu.io.video import set_frame_cache

    cache = cache_for(cfg, feature_types)
    set_frame_cache(cache)
    try:
        yield cache
    finally:
        set_frame_cache(None)


def run_multi(config, feature_types, external_call: bool = False, device=None):
    """Batch fan-out: run each feature type's extractor over the same
    input selection with ONE shared decode per clip.

    Extractor-major order — model A finishes every video before model B
    starts — so each resident model's weights/executables are built
    once; the frame cache (not interleaving) is what makes the second
    model's decode free. Returns {feature_type: extractor-call result}
    for ``external_call`` (the in-process API), else
    {feature_type: extractor} after each save run completes."""
    from video_features_tpu.config import as_config, sanity_check
    from video_features_tpu.extract.registry import build_extractor

    cfg = as_config(config)
    fts = list(dict.fromkeys(feature_types))
    results = {}
    with shared_frame_cache(cfg, fts):
        for ft in fts:
            fcfg = sanity_check(cfg.replace(feature_type=ft))
            ext = build_extractor(fcfg, external_call=external_call)
            if external_call:
                results[ft] = ext(range(len(ext.path_list)), device=device)
            else:
                from video_features_tpu.parallel.devices import resolve_devices
                from video_features_tpu.parallel.scheduler import (
                    mesh_feature_extraction,
                    parallel_feature_extraction,
                )

                devices = resolve_devices(fcfg)
                if fcfg.sharding == "mesh":
                    mesh_feature_extraction(ext, devices)
                else:
                    parallel_feature_extraction(ext, devices)
                ext.telemetry.close()
                results[ft] = ext
    return results
