"""Feature-type -> extractor dispatch (ref main.py:15-41).

Imports are lazy per feature type, mirroring the reference's
import-inside-branch pattern — here it keeps startup light rather than
dodging conda-env conflicts (the reference needed 3 incompatible envs;
this framework needs one).
"""

from __future__ import annotations

from video_features_tpu.config import CLIP_FEATURE_TYPES, RESNET_FEATURE_TYPES, as_config


def media_need_for(feature_type: str) -> str:
    """What the preflight probe must find in this feature type's input
    ('video' or 'audio') — derivable WITHOUT building the extractor, for
    the admission paths (serve preflight, cache lookup) that must stay
    build-free. Mirrors each extractor class's ``media_need``."""
    return "audio" if feature_type in ("vggish", "vggish_torch") else "video"


def build_extractor(config, external_call: bool = False):
    cfg = as_config(config)
    ft = cfg.feature_type
    if ft in CLIP_FEATURE_TYPES:
        from video_features_tpu.models.clip.extract_clip import ExtractCLIP

        return ExtractCLIP(cfg, external_call)
    if ft in RESNET_FEATURE_TYPES:
        from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

        return ExtractResNet(cfg, external_call)
    if ft == "r21d_rgb":
        from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

        return ExtractR21D(cfg, external_call)
    if ft == "raft":
        from video_features_tpu.models.raft.extract_raft import ExtractRAFT

        return ExtractRAFT(cfg, external_call)
    if ft == "pwc":
        from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

        return ExtractPWC(cfg, external_call)
    if ft == "i3d":
        from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

        return ExtractI3D(cfg, external_call)
    if ft in ("vggish", "vggish_torch"):
        from video_features_tpu.models.vggish.extract_vggish import ExtractVGGish

        return ExtractVGGish(cfg, external_call)
    raise ValueError(f"unknown feature_type: {ft}")
