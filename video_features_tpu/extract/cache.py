"""Content-addressed feature cache (ISSUE 17).

Completed features are keyed by (content hash, extraction-config digest):
the hash names the *bytes* of the input media, the digest names every
knob that can change the extracted values or their serialized form. A
repeat request for a video already extracted under the same config is a
store lookup + file copy instead of a decode + forward pass.

Layout on disk (shareable across hosts on a common filesystem)::

    <root>/<hh>/<content_hash>/<config_digest>/
        entry.json            # keys -> payload file names, provenance
        <key>.npy | <key>.pkl # one payload per feature key

Population is claim-by-rename: a writer stages the entry under
``<root>/.tmp/<uuid>/`` and ``os.rename``\\ s the whole directory onto the
entry path. Renaming onto an existing non-empty directory fails, so when
two replicas compute the same key concurrently exactly one wins and the
loser's work degrades to a no-op (its next lookup is a hit). A torn
entry can never be valid: payloads are copied from files the sink
already committed atomically (io/sink.py), the staged directory only
becomes visible via the single rename, and ``lookup`` re-validates
``entry.json`` plus each payload's magic bytes before trusting anything.

Hashing is ``fast`` by default — size + head + a few sampled chunks +
tail through sha256 — so admission never streams a multi-GB file;
``--cache_hash full`` streams every byte for collision-paranoid setups.
A (path, size, mtime_ns) memo makes the hash free for repeat lookups
and for multi-model fan-out requests that would otherwise hash the same
bytes once per model. Audio inputs (VGGish wav files) hash through the
same byte-level path — nothing here is video-specific.

No jax imports: admission-path code must stay importable without a
backend (same rule as serve/lifecycle.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

from video_features_tpu.io.sink import atomic_copy, output_file_name

# fast-hash geometry: 1 MiB head (container metadata + first GOPs), four
# 256 KiB chunks sampled at evenly spaced offsets, and a 256 KiB tail
# (mp4 moov atoms often live there) — plus the exact byte size, so two
# files must agree on size AND ~2 MiB of spread-out content to collide
_FAST_HEAD = 1 << 20
_FAST_CHUNK = 1 << 18
_FAST_SAMPLES = 4

HASH_MODES = ("fast", "full")

# (abspath, size, mtime_ns, mode) -> hex digest. Bounded LRU: a
# long-lived serve daemon must not grow this forever. Guarded — the
# daemon's admission thread and the extractor's decode workers both
# hash (GC301 scope).
_MEMO_CAP = 4096
_MEMO: "OrderedDict[tuple, str]" = OrderedDict()
_MEMO_LOCK = threading.Lock()


def content_hash(path: str, mode: str = "fast") -> str:
    """sha256 content hash of ``path`` (hex), memoized on
    (path, size, mtime_ns, mode) so repeat lookups and same-request
    fan-out never re-read the bytes. Raises OSError for unreadable
    paths — callers treat that as uncacheable, never as a hit."""
    if mode not in HASH_MODES:
        raise ValueError(f"unknown cache hash mode: {mode!r}")
    ap = os.path.abspath(path)
    st = os.stat(ap)
    memo_key = (ap, st.st_size, st.st_mtime_ns, mode)
    with _MEMO_LOCK:
        hit = _MEMO.get(memo_key)
        if hit is not None:
            _MEMO.move_to_end(memo_key)
            return hit
    digest = _hash_bytes(ap, st.st_size, mode)
    with _MEMO_LOCK:
        _MEMO[memo_key] = digest
        while len(_MEMO) > _MEMO_CAP:
            _MEMO.popitem(last=False)
    return digest


def _hash_bytes(path: str, size: int, mode: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        if mode == "full":
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
            return h.hexdigest()
        # fast: the size is part of the preimage — sampled chunks alone
        # would let a truncated copy collide with its original
        h.update(str(size).encode("ascii"))
        h.update(b"\x00")
        h.update(f.read(_FAST_HEAD))
        body = size - _FAST_HEAD - _FAST_CHUNK
        if body > 0:
            for i in range(1, _FAST_SAMPLES + 1):
                f.seek(_FAST_HEAD + body * i // (_FAST_SAMPLES + 1))
                h.update(f.read(_FAST_CHUNK))
            f.seek(size - _FAST_CHUNK)
            h.update(f.read(_FAST_CHUNK))
    return h.hexdigest()


# every knob that changes extracted values or their serialized form —
# the same family of knobs that keys fused executables (model identity,
# sampling grid, preprocess placement, numerics). Knobs that only move
# work around (decode_workers, video_batch, retries, telemetry) are
# deliberately absent: they must share cache entries. Missing a knob
# here would serve stale features; including a no-op knob only costs a
# spurious miss — when in doubt, include.
_DIGEST_FIELDS = (
    "feature_type",
    "extraction_fps",
    "fps_retarget",
    "extract_method",
    "stack_size",
    "step_size",
    "streams",
    "flow_type",
    "batch_size",
    "resize_to_smaller_edge",
    "side_size",
    "dtype",
    "weights_path",
    "allow_random_init",
    "host_preprocess",
    "preprocess",
    "spatial_bucket",
    "frame_delta_threshold",
    "attn",
    "conv3d_impl",
    "on_extraction",
)


def config_digest(cfg) -> str:
    """sha256 over the output-affecting knobs of an ExtractionConfig
    (hex, truncated to 16 chars — it is a directory name, and 64 bits
    of config space is plenty). Any change to a listed knob is a new
    cache namespace: invalidation IS the digest."""
    doc = {}
    for name in _DIGEST_FIELDS:
        value = getattr(cfg, name, None)
        if isinstance(value, (list, tuple)):
            value = list(value)
        doc[name] = value
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def feature_keys_for(cfg) -> List[str]:
    """The feature keys a config's extractor will produce, derivable
    without building the model (the serve admission path must not pay a
    build to answer a lookup). Mirrors BaseExtractor.feature_keys and
    the I3D override; a mismatch can only cause a miss, never a wrong
    hit — lookup requires every requested key to be present."""
    if cfg.feature_type == "i3d":
        return list(cfg.streams) if cfg.streams else ["rgb", "flow"]
    return [cfg.feature_type]


_PAYLOAD_MAGIC = {
    ".npy": b"\x93NUMPY",
    ".pkl": b"\x80",  # pickle protocol >= 2 opcode
}


def _payload_ok(path: str) -> bool:
    """Cheap torn-file detector: the payload must exist, be non-empty,
    and carry its format's magic bytes. A partially-copied or truncated
    entry fails here and the lookup degrades to a miss."""
    ext = os.path.splitext(path)[1]
    magic = _PAYLOAD_MAGIC.get(ext)
    if magic is None:
        return False
    try:
        with open(path, "rb") as f:
            return f.read(len(magic)) == magic
    except OSError:
        return False


class FeatureCache:
    """One content-addressed store rooted at a directory.

    Stateless beyond the root path + hash mode: every method re-reads
    the filesystem, so multiple processes (and hosts, on shared
    storage) can point at the same root with no coordination beyond
    the claim-by-rename publish protocol."""

    def __init__(self, root: str, hash_mode: str = "fast") -> None:
        if hash_mode not in HASH_MODES:
            raise ValueError(f"unknown cache hash mode: {hash_mode!r}")
        self.root = os.path.abspath(root)
        self.hash_mode = hash_mode

    def content_hash(self, path: str) -> str:
        return content_hash(path, self.hash_mode)

    def entry_dir(self, chash: str, digest: str) -> str:
        return os.path.join(self.root, chash[:2], chash, digest)

    def lookup(
        self, chash: str, digest: str, feature_keys
    ) -> Optional[Dict[str, str]]:
        """{key: payload path} when a VALID entry covers every requested
        key, else None. Corruption anywhere — unreadable/garbled
        entry.json, a missing key, a payload without its magic — is a
        miss; a wrong hit is the one failure mode this layer must not
        have."""
        d = self.entry_dir(chash, digest)
        try:
            with open(os.path.join(d, "entry.json"), "r", encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        names = meta.get("keys") if isinstance(meta, dict) else None
        if not isinstance(names, dict):
            return None
        out: Dict[str, str] = {}
        for key in feature_keys:
            fname = names.get(key)
            # payload names come from entry.json — refuse anything that
            # could escape the entry directory
            if not isinstance(fname, str) or fname != os.path.basename(fname):
                return None
            path = os.path.join(d, fname)
            if not _payload_ok(path):
                return None
            out[key] = path
        return out

    def publish(
        self, chash: str, digest: str, files: Dict[str, str], feature_type: str = ""
    ) -> bool:
        """Copy already-committed output files ({key: path}) into the
        store. Returns True when this call created the entry, False
        when another writer got there first (the claim-by-rename loss —
        a no-op, not an error) or a source file vanished."""
        entry = self.entry_dir(chash, digest)
        if os.path.isdir(entry):
            return False
        stage = os.path.join(self.root, ".tmp", uuid.uuid4().hex)
        try:
            os.makedirs(stage)
            names = {}
            for key, src in files.items():
                fname = key.replace("/", "-") + os.path.splitext(src)[1]
                shutil.copyfile(src, os.path.join(stage, fname))
                names[key] = fname
            meta = {
                "format_version": 1,
                "content_hash": chash,
                "config_digest": digest,
                "feature_type": feature_type,
                "hash_mode": self.hash_mode,
                "keys": names,
            }
            with open(os.path.join(stage, "entry.json"), "w", encoding="utf-8") as f:
                json.dump(meta, f, sort_keys=True)
            os.makedirs(os.path.dirname(entry), exist_ok=True)
            os.rename(stage, entry)  # the claim: fails if someone else won
            return True
        except OSError:
            shutil.rmtree(stage, ignore_errors=True)
            return False

    def materialize(
        self, cached: Dict[str, str], dests: Dict[str, str]
    ) -> List[str]:
        """Copy cached payloads to their expected output locations
        (tmp + rename, like the sink: a kill mid-copy must not leave a
        truncated file --resume would trust). Returns the dest paths in
        ``dests`` order; raises OSError if a payload disappears."""
        out = []
        for key, dest in dests.items():
            atomic_copy(cached[key], dest)
            out.append(dest)
        return out

    def dest_files(
        self, feature_keys, video_path: str, output_path: str,
        on_extraction: str, output_direct: bool = False,
    ) -> Dict[str, str]:
        """{key: expected output file} — the per-key companion of
        io/sink.py's expected_output_files (which flattens and dedups;
        materialize needs the key association)."""
        import pathlib

        stem = pathlib.Path(video_path).stem
        return {
            key: os.path.join(
                output_path,
                output_file_name(stem, key, on_extraction, output_direct),
            )
            for key in feature_keys
        }
