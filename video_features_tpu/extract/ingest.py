"""Async device-ingest plumbing for the pipelined extraction loop.

The restructured ``_run_pipelined`` (extract/base.py) composes three
pieces from here:

* ``CompletionQueue`` — the bounded window of dispatched-but-unfetched
  device work (``--inflight_groups`` deep). XLA dispatch is async, so a
  dispatched group is a *handle*; the loop pushes handles here and a
  single drain function pops them — blocking on the oldest only when
  the window is full, opportunistically sinking any head whose device
  buffers are already complete (``jax.Array.is_ready`` is a
  non-blocking readiness probe, not a sync).
* ``RequeueTimers`` — transient-retry backoff scheduled on
  ``threading.Timer`` instead of ``time.sleep`` on a decode worker, so
  a retrying video never steals decode throughput from the healthy
  ones. The outer drain loop waits on ``pending()`` so a run cannot
  exit while a delayed requeue is still armed.
* ``StagedGroup`` — the marker an extractor's ``transfer_group`` hook
  returns: the fused group's arrays already assembled and device_put
  (the dedicated H2D stage, timed under the ``h2d`` telemetry span),
  so ``dispatch_group`` only enqueues compute. Because the staged
  buffers are fresh per group, the fused jit entries can donate them
  (``donate_argnums``) and XLA reuses the uint8 ingest HBM in place.

Donation note: CPU (and some backends) cannot alias these buffers and
jax warns "Some donated buffers were not usable" on first execution;
``jit_donated`` filters exactly that message so CPU parity runs stay
clean while TPU gets the in-place reuse.
"""

from __future__ import annotations

import threading
import warnings
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

_DONATE_WARNING = "Some donated buffers were not usable"


def jit_donated(fun: Callable, donate_argnums: Tuple[int, ...], **jit_kwargs):
    """``jax.jit`` with ingest-buffer donation plus the CPU-backend
    warning filtered (see module docstring). Donate only arguments that
    are freshly placed per call — never arrays reused across calls
    (e.g. ResNet's per-video resize taps)."""
    import jax

    warnings.filterwarnings("ignore", message=_DONATE_WARNING)
    return jax.jit(fun, donate_argnums=donate_argnums, **jit_kwargs)


def handle_ready(handle: Any) -> bool:
    """Non-blocking completion probe for a dispatch handle: True when
    every jax array reachable in it reports ``is_ready()`` (host-side
    leaves — numpy arrays, floats, metadata tuples — are always ready).
    Never fetches and never blocks, so it is safe in the hot loop."""
    import jax

    for leaf in jax.tree_util.tree_leaves(handle):
        probe = getattr(leaf, "is_ready", None)
        if callable(probe):
            try:
                if not probe():
                    return False
            except Exception:  # noqa: BLE001 - a deleted/poisoned buffer: let
                # the drain path surface the real error at fetch time
                return True
    return True


class StagedGroup:
    """Output of an extractor's ``transfer_group``: the fused group's
    device-resident arrays plus the per-video metas ``fetch_group``
    needs to slice results apart. ``dispatch_group`` receives this in
    place of the host payload list and must consume ``arrays`` exactly
    once (they may be donated to the fused jit entry)."""

    __slots__ = ("arrays", "metas")

    def __init__(self, arrays: Tuple[Any, ...], metas: List[Any]):
        self.arrays = arrays
        self.metas = metas


class CompletionQueue:
    """FIFO of in-flight dispatched groups, ``depth`` entries deep.

    Entries are ``(slots, handle, grouped, payloads)`` exactly as the
    old inflight deque held them; ``payloads`` keeps the host arrays
    resident until the entry drains so a fused failure can fall back to
    the solo path even when the staged device copies were donated."""

    def __init__(self, depth: int):
        self.depth = max(int(depth), 1)
        self._q: deque = deque()

    def push(self, slots, handle, grouped, payloads) -> None:
        self._q.append((slots, handle, grouped, payloads))

    def pop(self):
        return self._q.popleft()

    def head_ready(self) -> bool:
        """True when the oldest entry's device work is already complete
        (drain order stays FIFO: only the head is probed)."""
        if not self._q:
            return False
        return handle_ready(self._q[0][1])

    @property
    def full(self) -> bool:
        return len(self._q) >= self.depth

    def __len__(self) -> int:
        return len(self._q)

    def __bool__(self) -> bool:
        return bool(self._q)


class RequeueTimers:
    """Backoff scheduler for transient-retry requeues.

    ``schedule(delay, fire)`` arms a daemon ``threading.Timer`` that
    invokes ``fire`` (which appends the retry's prepare future to the
    loop's ``pending`` deque) after ``delay`` seconds. ``pending()``
    counts armed timers; it is decremented only *after* ``fire`` has
    run, so the drain-loop exit condition ``pending() == 0`` implies
    every retry has already re-entered the queue. ``wait_any`` parks
    the drain loop until some timer fires (or the poll interval
    elapses) instead of spinning."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed = 0
        self._fired = threading.Event()
        self._timers: List[threading.Timer] = []

    def schedule(self, delay: float, fire: Callable[[], None]) -> None:
        if delay <= 0:
            fire()
            return
        with self._lock:
            self._armed += 1

        def _run() -> None:
            try:
                fire()
            finally:
                with self._lock:
                    self._armed -= 1
                self._fired.set()

        t = threading.Timer(delay, _run)
        t.daemon = True  # never blocks interpreter exit on a crashed run
        with self._lock:
            self._timers = [x for x in self._timers if x.is_alive()]
            self._timers.append(t)
        t.start()

    def pending(self) -> int:
        with self._lock:
            return self._armed

    def wait_any(self, timeout: float = 0.05) -> None:
        self._fired.wait(timeout)
        self._fired.clear()


def stack_group(payload_heads: Sequence[Any], pad_to: Optional[int] = None):
    """Host-side group assembly helper: stack per-video arrays along a
    new leading axis and (optionally) pad the group axis to the full
    ``--video_batch`` so partial flushes keep the compiled shape."""
    import numpy as np

    from video_features_tpu.ops.window import pad_batch

    arr = np.stack(payload_heads)
    if pad_to is not None and arr.shape[0] < pad_to:
        arr = pad_batch(arr, pad_to)
    return arr
