"""RAFT optical-flow extractor (ref models/raft/extract_raft.py).

Pair-streaming runtime shared with PWC (PairwiseFlowExtractor); RAFT adds
replicate padding to /8 multiples (InputPadder 'sintel' mode, ref
raft_src/raft.py:28-44) before the model and unpads the flow after.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from video_features_tpu.models.common.flow_extract import PairwiseFlowExtractor
from video_features_tpu.models.raft.convert import convert_state_dict
from video_features_tpu.models.raft.model import build, init_params, input_grid


class InputPadder:
    """Replicate-pad (H, W) to /8 multiples, 'sintel' mode: symmetric in
    both axes (ref raft_src/raft.py:28-44). Host-side numpy.

    Also enforces a 128-px floor per dim: the deepest of RAFT's 4
    correlation-pyramid levels lives at 1/64 resolution, and the
    pixel-coordinate sampler needs every level to be at least 2 wide
    (its x/(W-1) normalization). Below 128 px the reference silently
    produces NaN flow (division by zero on the 1x1 level) or crashes in
    the pyramid pooling; ``unpad`` still restores the original size."""

    def __init__(self, shape: Tuple[int, int], div: int = 8, min_size: int = 128):
        self.ht, self.wd = shape
        tgt_ht, tgt_wd = input_grid(self.ht, self.wd, div, min_size)
        pad_ht, pad_wd = tgt_ht - self.ht, tgt_wd - self.wd
        self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, pad_ht // 2, pad_ht - pad_ht // 2]

    def pad(self, x: np.ndarray) -> np.ndarray:
        """(..., H, W, C) -> replicate-padded."""
        l, r, t, b = self._pad
        width = [(0, 0)] * (x.ndim - 3) + [(t, b), (l, r), (0, 0)]
        return np.pad(x, width, mode="edge")

    def unpad(self, x: np.ndarray) -> np.ndarray:
        """(..., H, W, C) -> original size."""
        l, r, t, b = self._pad
        H, W = x.shape[-3], x.shape[-2]
        return x[..., t : H - b, l : W - r, :]


class ExtractRAFT(PairwiseFlowExtractor):
    _convert_state_dict = staticmethod(convert_state_dict)

    def _model(self):
        # --dtype bfloat16 selects RAFT's mixed-precision graph: convs on
        # the MXU in bf16, the refinement recurrence (corr volume, GRU
        # carry, coords accumulator, upsampling) pinned fp32 — see
        # models/raft/model.py docstring for the drift budget
        from video_features_tpu.models.common.weights import compute_dtype

        return build(dtype=compute_dtype(self.config))

    def _init_params(self):
        return init_params()

    def _make_padder(self, shape):
        return InputPadder(shape)

    def _device_grid(self, oh, ow):
        # the device-preprocess output contract IS InputPadder's target:
        # /8 multiples with the 128-px floor, image centered exactly
        # where the 'sintel'-mode pad puts it (pad_ht//2 == (tgt-oh)//2),
        # so the per-video padder's unpad slices the same valid region
        tgt_h, tgt_w = input_grid(oh, ow)
        return tgt_h, tgt_w, (tgt_h - oh) // 2, (tgt_w - ow) // 2
