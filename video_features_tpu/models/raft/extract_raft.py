"""RAFT optical-flow extractor (ref models/raft/extract_raft.py).

Per video: streaming decode (optionally on an ``--extraction_fps`` grid),
optional ``--side_size`` PIL resize (smaller or larger edge, ref
transforms ResizeImproved), frames kept as raw [0,255] floats, replicate-
padded to /8 multiples (InputPadder 'sintel' mode, ref
raft_src/raft.py:28-44), batched as B+1 frames sharing one boundary frame
between consecutive batches (ref extract_raft.py:139-146).

TPU-first: every batch runs at ONE static shape — the tail batch is
filled by repeating the last frame and the extra pair outputs are
discarded — so XLA compiles a single executable per video resolution.

Output contract: ``{raft: (T-1, 2, H, W), fps, timestamps_ms}``
(ref extract_raft.py:155-160), flow at unpadded input resolution.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import probe, stream_frames
from video_features_tpu.models.common.weights import load_params
from video_features_tpu.models.raft.convert import convert_state_dict
from video_features_tpu.models.raft.model import build, init_params
from video_features_tpu.ops.preprocess import pil_resize


class InputPadder:
    """Replicate-pad (H, W) to /8 multiples, 'sintel' mode: symmetric in
    both axes (ref raft_src/raft.py:28-44). Host-side numpy.

    Also enforces a 128-px floor per dim: the deepest of RAFT's 4
    correlation-pyramid levels lives at 1/64 resolution, and the
    pixel-coordinate sampler needs every level to be at least 2 wide
    (its x/(W-1) normalization). Below 128 px the reference silently
    produces NaN flow (division by zero on the 1x1 level) or crashes in
    the pyramid pooling; ``unpad`` still restores the original size."""

    def __init__(self, shape: Tuple[int, int], div: int = 8, min_size: int = 128):
        self.ht, self.wd = shape
        tgt_ht = max(-(-self.ht // div) * div, min_size)
        tgt_wd = max(-(-self.wd // div) * div, min_size)
        pad_ht, pad_wd = tgt_ht - self.ht, tgt_wd - self.wd
        self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, pad_ht // 2, pad_ht - pad_ht // 2]

    def pad(self, x: np.ndarray) -> np.ndarray:
        """(..., H, W, C) -> replicate-padded."""
        l, r, t, b = self._pad
        width = [(0, 0)] * (x.ndim - 3) + [(t, b), (l, r), (0, 0)]
        return np.pad(x, width, mode="edge")

    def unpad(self, x: np.ndarray) -> np.ndarray:
        """(..., H, W, C) -> original size."""
        l, r, t, b = self._pad
        H, W = x.shape[-3], x.shape[-2]
        return x[..., t : H - b, l : W - r, :]


class ExtractRAFT(BaseExtractor):
    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.batch_size = max(int(self.config.batch_size or 1), 1)
        self.side_size = self.config.side_size
        self.resize_to_smaller_edge = self.config.resize_to_smaller_edge
        self._host_params = None

    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path, convert_state_dict
                )
            else:
                self._host_params = init_params()
        return self._host_params

    def _build(self, device):
        model = build()
        params = jax.device_put(self._load_host_params(), device)

        @jax.jit
        def forward(p, frames):  # (B+1, H, W, 3) -> (B, H, W, 2)
            return model.apply({"params": p}, frames)

        return {"params": params, "forward": forward, "device": device}

    def _preprocess(self, frame: np.ndarray) -> np.ndarray:
        if self.side_size is not None:
            frame = pil_resize(frame, int(self.side_size), self.resize_to_smaller_edge)
        return frame.astype(np.float32)

    def _run_batch(
        self, state, batch: List[np.ndarray], padder: InputPadder, flows: List[np.ndarray]
    ) -> None:
        """Run flow on a B+1 frame window; tail windows are filled by
        repeating the last frame and the surplus pairs dropped."""
        n_pairs = len(batch) - 1
        if n_pairs < 1:
            return
        window = batch + [batch[-1]] * (self.batch_size + 1 - len(batch))
        x = padder.pad(np.stack(window))
        x = jax.device_put(jnp.asarray(x), state["device"])
        flow = np.asarray(state["forward"](state["params"], x))  # (B, Hp, Wp, 2)
        flow = padder.unpad(flow)[:n_pairs]
        flows.extend(np.transpose(flow, (0, 3, 1, 2)))  # saved as (2, H, W)
        if self.config.show_pred:
            from video_features_tpu.utils.flow_viz import show_flow_on_frame

            for i in range(n_pairs):
                show_flow_on_frame(flow[i], batch[i])

    def extract(self, device, state, path_entry) -> Dict[str, np.ndarray]:
        video_path = video_path_of(path_entry)
        fps = self.config.extraction_fps or probe(video_path).fps or 25.0

        flows: List[np.ndarray] = []
        timestamps_ms: List[float] = []
        batch: List[np.ndarray] = []
        padder = None
        for frame, ts in stream_frames(video_path, self.config.extraction_fps):
            timestamps_ms.append(ts)
            frame = self._preprocess(frame)
            if padder is None:
                padder = InputPadder(frame.shape[:2])
            batch.append(frame)
            # B+1 frames make B pairs; the boundary frame carries over
            if len(batch) - 1 == self.batch_size:
                self._run_batch(state, batch, padder, flows)
                batch = [batch[-1]]
        if len(batch) > 1:
            self._run_batch(state, batch, padder, flows)
        if padder is None:
            raise IOError(f"no frames decoded from {video_path}")

        return {
            self.feature_type: np.array(flows),
            "fps": np.array(fps),
            "timestamps_ms": np.array(timestamps_ms),
        }
