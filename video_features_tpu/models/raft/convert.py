"""princeton-vl RAFT checkpoint (raft-sintel.pth / raft-kitti.pth) ->
Flax param tree.

The reference loads these through a degenerate single-device
``torch.nn.DataParallel``, so every key carries a ``module.`` prefix
(ref models/raft/extract_raft.py:59-61); stripped here. InstanceNorm
layers (fnet, and every ``downsample.1``/``norm3`` of the fnet) carry no
parameters — only the cnet's BatchNorms contribute stats.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from video_features_tpu.models.common.weights import (
    bn_params,
    check_all_consumed,
    conv2d_kernel,
    strip_prefix,
)


def _conv(sd: Dict[str, np.ndarray], name: str, consumed) -> Dict[str, np.ndarray]:
    consumed.update((f"{name}.weight", f"{name}.bias"))
    return {"kernel": conv2d_kernel(sd[f"{name}.weight"]), "bias": sd[f"{name}.bias"]}


def _encoder(sd: Dict[str, np.ndarray], enc: str, batch_norm: bool, consumed):
    params = {
        "conv1": _conv(sd, f"{enc}.conv1", consumed),
        "conv2": _conv(sd, f"{enc}.conv2", consumed),
    }
    if batch_norm:
        params["norm1"] = bn_params(sd, f"{enc}.norm1", consumed)
    for layer in (1, 2, 3):
        for b in (0, 1):
            ref = f"{enc}.layer{layer}.{b}"
            blk = {
                "conv1": _conv(sd, f"{ref}.conv1", consumed),
                "conv2": _conv(sd, f"{ref}.conv2", consumed),
            }
            if batch_norm:
                blk["norm1"] = bn_params(sd, f"{ref}.norm1", consumed)
                blk["norm2"] = bn_params(sd, f"{ref}.norm2", consumed)
            if f"{ref}.downsample.0.weight" in sd:
                blk["downsample"] = _conv(sd, f"{ref}.downsample.0", consumed)
                if batch_norm:
                    blk["norm3"] = bn_params(sd, f"{ref}.downsample.1", consumed)
                    # the downsample norm is registered twice in the source
                    # module — as `downsample.1` AND as `norm3` (ref
                    # raft_src/extractor.py:26,44-45) — so a state_dict
                    # taken from the live model carries alias keys
                    for suffix in ("weight", "bias", "running_mean", "running_var"):
                        alias = f"{ref}.norm3.{suffix}"
                        if alias in sd:
                            consumed.add(alias)
            params[f"layer{layer}_{b}"] = blk
    return params


def convert_state_dict(sd: Dict[str, np.ndarray]):
    sd = strip_prefix(sd, "module.")
    consumed = set()
    update = {
        "encoder": {
            name: _conv(sd, f"update_block.encoder.{name}", consumed)
            for name in ("convc1", "convc2", "convf1", "convf2", "conv")
        },
        "gru": {
            name: _conv(sd, f"update_block.gru.{name}", consumed)
            for name in ("convz1", "convr1", "convq1", "convz2", "convr2", "convq2")
        },
        "flow_head": {
            name: _conv(sd, f"update_block.flow_head.{name}", consumed)
            for name in ("conv1", "conv2")
        },
        "mask_0": _conv(sd, "update_block.mask.0", consumed),
        "mask_2": _conv(sd, "update_block.mask.2", consumed),
    }
    params = {
        "fnet": _encoder(sd, "fnet", batch_norm=False, consumed=consumed),
        "cnet": _encoder(sd, "cnet", batch_norm=True, consumed=consumed),
        "update_block": update,
    }
    check_all_consumed(sd, consumed, "RAFT")
    return params
