"""RAFT optical flow in Flax (inference graph).

Reference: models/raft/raft_src/{raft,extractor,update,corr}.py — the
"basic" configuration (corr_levels=4, radius=4, hidden=context=128,
iters=20, ref raft_src/raft.py:56-68,115).

TPU-first redesign, numerically equivalent to the reference:

- NHWC layout end-to-end; convs tile onto the MXU without layout churn.
- The feature encoder runs ONCE over the T-frame sequence; consecutive
  pairs are views ``fmap[:-1]``/``fmap[1:]``. The reference encodes both
  pair stacks, touching every interior frame twice
  (ref raft_src/raft.py:129, extract_raft.py:101).
- The all-pairs correlation volume is one fp32 einsum on the MXU
  (ref raft_src/corr.py:52-60 does it as a batched matmul).
- The 20 refinement iterations run under ``flax.linen.scan`` — one
  compiled GRU body instead of a 20x unrolled graph; the carry holds
  (net, coords1, up_mask) so nothing is stacked across iterations
  (ref raft_src/raft.py:151-168 loops eagerly in Python).
- Convex upsampling is a shifted-window einsum (the reference's
  unfold+softmax, ref raft_src/raft.py:102-111).
- Mixed precision (``dtype=bfloat16``): every CONV — the encoders and the
  20x motion-encoder/GRU/flow-head/mask stacks, which is where the FLOPs
  are — computes in bf16 on the MXU, while everything the refinement
  recurrence ACCUMULATES through stays fp32: the correlation volume and
  its window lookup, the GRU gate math and hidden-state carry, the
  coords1 flow accumulator, and the convex-upsampling softmax. Params
  are always stored fp32. The budget: I3D's flow stream quantizes flow
  through ``flow_to_uint8`` (clamp ±20 -> 255 levels ~ 0.157 px/level),
  so conv-level drift far below half a level cannot change features
  (tests/test_raft.py::test_mixed_precision_flow_drift pins this).

Inputs are raw RGB floats in [0, 255]; scaling to [-1, 1] happens inside
(ref raft_src/raft.py:118-119).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from video_features_tpu.models.common.layers import EvalBatchNorm

CORR_LEVELS = 4
CORR_RADIUS = 4
HIDDEN_DIM = 128
CONTEXT_DIM = 128


class InstanceNorm(nn.Module):
    """torch InstanceNorm2d defaults: no affine params, eps=1e-5,
    always normalizes with the sample's own (H, W) statistics. Stats are
    fp32 even for a bf16 stream (a bf16 mean over H*W pixels loses ~2
    digits); the result returns in the incoming dtype."""

    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
        var = jnp.var(x32, axis=(1, 2), keepdims=True)
        return ((x32 - mean) * jax.lax.rsqrt(var + self.eps)).astype(x.dtype)


def _norm(kind: str, name: str):
    return EvalBatchNorm(name=name) if kind == "batch" else InstanceNorm(name=name)


def _conv(features: int, kernel, stride: int = 1, name: str = None,
          dtype=jnp.float32):
    kh, kw = kernel if isinstance(kernel, tuple) else (kernel, kernel)
    return nn.Conv(
        features,
        (kh, kw),
        strides=(stride, stride),
        padding=[(kh // 2, kh // 2), (kw // 2, kw // 2)],
        dtype=dtype,
        name=name,
    )


class ResidualBlock(nn.Module):
    planes: int
    norm: str
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        dt = self.dtype
        y = nn.relu(_norm(self.norm, "norm1")(_conv(self.planes, 3, self.stride, "conv1", dt)(x)))
        y = nn.relu(_norm(self.norm, "norm2")(_conv(self.planes, 3, 1, "conv2", dt)(y)))
        if self.stride != 1:
            x = nn.Conv(self.planes, (1, 1), strides=(self.stride,) * 2,
                        dtype=dt, name="downsample")(x)
            x = _norm(self.norm, "norm3")(x)
        return nn.relu(x.astype(dt) + y)


class BasicEncoder(nn.Module):
    """Conv encoder to 1/8 resolution (ref raft_src/extractor.py:118-196)."""

    output_dim: int
    norm: str
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        dt = self.dtype
        x = _conv(64, 7, 2, "conv1", dt)(x)
        x = nn.relu(_norm(self.norm, "norm1")(x))
        for i, (dim, stride) in enumerate(((64, 1), (96, 2), (128, 2)), start=1):
            x = ResidualBlock(dim, self.norm, stride, dtype=dt, name=f"layer{i}_0")(x)
            x = ResidualBlock(dim, self.norm, 1, dtype=dt, name=f"layer{i}_1")(x)
        return nn.Conv(self.output_dim, (1, 1), dtype=dt, name="conv2")(x)


# --- correlation pyramid ----------------------------------------------------

def build_corr_pyramid(
    fmap1: jnp.ndarray, fmap2: jnp.ndarray, num_levels: int = CORR_LEVELS
) -> Tuple[jnp.ndarray, ...]:
    """All-pairs correlation + avg-pool pyramid (ref raft_src/corr.py:12-27).

    fmaps are (N, H, W, C); returns ``num_levels`` arrays of shape
    (N*H*W, h_l, w_l, 1). fp32 HIGHEST-precision einsum: the volume feeds
    20 refinement iterations, so matmul drift compounds.
    """
    N, H, W, C = fmap1.shape
    corr = jnp.einsum(
        "nhwc,nijc->nhwij", fmap1, fmap2, precision=jax.lax.Precision.HIGHEST
    ) / jnp.sqrt(jnp.array(C, fmap1.dtype))
    corr = corr.reshape(N * H * W, H, W, 1)
    pyramid = [corr]
    for _ in range(num_levels - 1):
        corr = nn.avg_pool(corr, (2, 2), strides=(2, 2))
        pyramid.append(corr)
    return tuple(pyramid)


def _window_weights(c: jnp.ndarray, size: int, radius: int) -> jnp.ndarray:
    """Separable bilinear one-hot weights for a (2r+1) integer window at a
    fractional center ``c`` (B,) over an axis of ``size`` -> (B, 2r+1, size).

    ``out[b, k, p] = (1-frac)·[p == floor(c)-r+k] + frac·[p == floor(c)-r+k+1]``
    — row k of the matrix picks axis position ``c - r + k`` with exact
    bilinear weighting, and out-of-range positions simply match nothing,
    which IS the sampler's zero padding.
    """
    f = jnp.floor(c)
    frac = (c - f)[:, None, None]
    base = f[:, None] + jnp.arange(-radius, radius + 1, dtype=c.dtype)[None]  # (B, 2r+1)
    pos = jnp.arange(size, dtype=c.dtype)[None, None]  # (1, 1, size)
    lo = (pos == base[..., None]).astype(c.dtype)
    hi = (pos == base[..., None] + 1).astype(c.dtype)
    return (1.0 - frac) * lo + frac * hi


def lookup_corr(
    pyramid: Sequence[jnp.ndarray],
    coords: jnp.ndarray,
    radius: int = CORR_RADIUS,
) -> jnp.ndarray:
    """Sample each pyramid level in a (2r+1)^2 window around ``coords``
    (N, H, W, 2 as x,y pixels) -> (N, H, W, levels*(2r+1)^2).

    The window offset applied to x comes from the FIRST meshgrid axis and
    the offset to y from the second — the reference builds delta as
    ``stack(meshgrid(dy, dx))`` and adds it to (x, y) coords, so the
    window is transposed relative to the naive reading; the pretrained
    weights bake this in (ref raft_src/corr.py:35-42).

    TPU formulation: every window point shares the centroid's fractional
    offset, so bilinear sampling of the whole window separates into a row
    and a column one-hot-with-weights matmul per level —
    ``out[b, i, j] = Cx[b,i,:] · img[b] · Ry[b,j,:]^T`` — putting the hot
    lookup (4 levels x 20 GRU iterations, ref raft_src/corr.py:35-48) on
    the MXU instead of 81-point gathers on the VPU. Exact bilinear
    semantics incl. zero padding (out-of-range rows match nothing); fp32
    HIGHEST so the iterative refinement sees full-precision samples.
    """
    N, H, W, _ = coords.shape
    B = N * H * W
    r = radius
    hp = jax.lax.Precision.HIGHEST

    flat = coords.reshape(B, 2)
    out = []
    for lvl, corr in enumerate(pyramid):
        img = corr[..., 0]  # (B, h, w)
        h, w = img.shape[1:]
        cx = flat[:, 0] / (2 ** lvl)
        cy = flat[:, 1] / (2 ** lvl)
        Cx = _window_weights(cx, w, r)  # (B, 2r+1, w) — window axis i is x
        Ry = _window_weights(cy, h, r)  # (B, 2r+1, h) — window axis j is y
        tmp = jnp.einsum("byx,bix->biy", img, Cx, precision=hp)
        win = jnp.einsum("biy,bjy->bij", tmp, Ry, precision=hp)  # (B, i, j)
        out.append(win.reshape(N, H, W, (2 * r + 1) ** 2))
    return jnp.concatenate(out, axis=-1)


# --- update block -----------------------------------------------------------

class BasicMotionEncoder(nn.Module):
    """ref raft_src/update.py:85-103. Convs in ``dtype``; the fp32 corr
    samples and flow enter through the convs' own input cast, and the
    appended raw-flow channels are cast to match — conditioning inputs
    only, the fp32 flow ACCUMULATOR lives in UpdateCell's carry."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, flow: jnp.ndarray, corr: jnp.ndarray) -> jnp.ndarray:
        dt = self.dtype
        cor = nn.relu(nn.Conv(256, (1, 1), dtype=dt, name="convc1")(corr))
        cor = nn.relu(_conv(192, 3, 1, "convc2", dt)(cor))
        flo = nn.relu(_conv(128, 7, 1, "convf1", dt)(flow))
        flo = nn.relu(_conv(64, 3, 1, "convf2", dt)(flo))
        out = nn.relu(_conv(128 - 2, 3, 1, "conv", dt)(jnp.concatenate([cor, flo], -1)))
        return jnp.concatenate([out, flow.astype(dt)], -1)


class SepConvGRU(nn.Module):
    """Separable 1x5 + 5x1 ConvGRU (ref raft_src/update.py:37-65).

    Mixed precision: the six gate convs run in ``dtype``, but the gate
    nonlinearities and the convex hidden-state update run fp32 on an fp32
    carry — the recurrence is 20 steps deep and ``h`` is exactly what
    bf16 rounding would compound through."""

    hidden: int = HIDDEN_DIM
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
        dt = self.dtype
        x = x.astype(dt)
        for sfx, kernel in (("1", (1, 5)), ("2", (5, 1))):
            hx = jnp.concatenate([h.astype(dt), x], -1)
            z = nn.sigmoid(_conv(self.hidden, kernel, 1, f"convz{sfx}", dt)(hx).astype(jnp.float32))
            r = nn.sigmoid(_conv(self.hidden, kernel, 1, f"convr{sfx}", dt)(hx).astype(jnp.float32))
            q = jnp.tanh(
                _conv(self.hidden, kernel, 1, f"convq{sfx}", dt)(
                    jnp.concatenate([(r * h).astype(dt), x], -1)
                ).astype(jnp.float32)
            )
            h = (1 - z) * h + z * q
        return h


class FlowHead(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        dt = self.dtype
        return _conv(2, 3, 1, "conv2", dt)(nn.relu(_conv(256, 3, 1, "conv1", dt)(x)))


class UpdateCell(nn.Module):
    """One refinement iteration: corr lookup -> motion encoder -> GRU ->
    flow delta + upsampling mask (ref raft_src/update.py:121-139,
    raft.py:151-162). Written as a scan cell; ``consts`` are broadcast.
    The carry (net, coords1, mask) is pinned fp32; ``dtype`` governs only
    the conv compute inside the cell."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, carry, consts):
        dt = self.dtype
        net, coords1, _ = carry
        pyramid, inp, coords0 = consts
        corr = lookup_corr(pyramid, coords1)
        flow = coords1 - coords0
        motion = BasicMotionEncoder(dtype=dt, name="encoder")(flow, corr)
        net = SepConvGRU(dtype=dt, name="gru")(
            net, jnp.concatenate([inp.astype(dt), motion.astype(dt)], -1)
        )
        delta = FlowHead(dtype=dt, name="flow_head")(net).astype(jnp.float32)
        m = nn.relu(_conv(256, 3, 1, "mask_0", dt)(net))
        mask = 0.25 * nn.Conv(64 * 9, (1, 1), dtype=dt, name="mask_2")(m).astype(jnp.float32)
        return (net, coords1 + delta, mask), None


def coords_grid(n: int, h: int, w: int) -> jnp.ndarray:
    """(N, H, W, 2) pixel coordinate grid, channels (x, y)
    (ref raft_src/utils/utils.py:74-77)."""
    x = jnp.arange(w, dtype=jnp.float32)
    y = jnp.arange(h, dtype=jnp.float32)
    xx, yy = jnp.meshgrid(x, y)
    return jnp.broadcast_to(jnp.stack([xx, yy], -1)[None], (n, h, w, 2))


def upsample_flow(flow: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Convex-combination 8x upsampling (ref raft_src/raft.py:102-111):
    softmax over 9 neighbors, weights per output subpixel of each cell."""
    N, H, W, _ = flow.shape
    # fp32 pin (GC802): the convex weights are a 9-way softmax whose
    # renormalization cannot survive bf16; the GRU head keeps mask fp32
    # today and this cast makes that contract load-bearing.
    mask = jax.nn.softmax(
        mask.reshape(N, H, W, 9, 8, 8).astype(jnp.float32), axis=3
    )
    f = jnp.pad(8.0 * flow, ((0, 0), (1, 1), (1, 1), (0, 0)))
    patches = jnp.stack(
        [f[:, ky : ky + H, kx : kx + W, :] for ky in range(3) for kx in range(3)],
        axis=3,
    )  # (N, H, W, 9, 2)
    up = jnp.einsum("nhwkab,nhwkc->nhwcab", mask, patches)  # (N, H, W, 2, 8, 8)
    return up.transpose(0, 1, 4, 2, 5, 3).reshape(N, 8 * H, 8 * W, 2)


class RAFT(nn.Module):
    """(T, H, W, 3) RGB floats in [0,255], H and W divisible by 8 ->
    (T-1, H, W, 2) flow for each consecutive frame pair.

    ``dtype=bfloat16`` selects the mixed-precision graph (module
    docstring); the returned flow is always fp32."""

    iters: int = 20
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, frames: jnp.ndarray) -> jnp.ndarray:
        x = 2.0 * (frames / 255.0) - 1.0

        fmap = BasicEncoder(256, "instance", dtype=self.dtype, name="fnet")(x)
        # the volume feeds 20 lookup iterations: build and sample it fp32
        # even when the encoders computed in bf16
        pyramid = build_corr_pyramid(
            fmap[:-1].astype(jnp.float32), fmap[1:].astype(jnp.float32)
        )

        cnet = BasicEncoder(
            HIDDEN_DIM + CONTEXT_DIM, "batch", dtype=self.dtype, name="cnet"
        )(x[:-1])
        net, inp = jnp.split(cnet.astype(jnp.float32), 2, axis=-1)
        net = jnp.tanh(net)  # fp32: this is the GRU's fp32 initial carry
        inp = nn.relu(inp)

        N, H8, W8, _ = net.shape
        coords0 = coords_grid(N, H8, W8)
        mask0 = jnp.zeros((N, H8, W8, 64 * 9), jnp.float32)

        scan = nn.scan(
            UpdateCell,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=nn.broadcast,
            length=self.iters,
        )
        (net, coords1, mask), _ = scan(dtype=self.dtype, name="update_block")(
            (net, coords0, mask0), (pyramid, inp, coords0)
        )
        return upsample_flow(coords1 - coords0, mask)


def input_grid(
    h: int, w: int, div: int = 8, min_size: int = 128
) -> Tuple[int, int]:
    """The padded (H, W) grid RAFT actually runs at for an (h, w) input:
    /``div`` multiples (the encoder downsamples 1/8) with a ``min_size``
    floor per dim — the deepest of the 4 correlation-pyramid levels lives
    at 1/64 resolution and the pixel-coordinate sampler needs every level
    at least 2 wide. This is InputPadder's target geometry
    (extract_raft.py) and the output contract the shape-contracted
    ``--preprocess device`` taps resize onto directly."""
    return max(-(-h // div) * div, min_size), max(-(-w // div) * div, min_size)


def build(iters: int = 20, dtype=jnp.float32) -> RAFT:
    return RAFT(iters=iters, dtype=dtype)


def init_params(seed: int = 0, iters: int = 20):
    model = build(iters)
    dummy = jnp.zeros((2, 64, 64, 3), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)["params"]
