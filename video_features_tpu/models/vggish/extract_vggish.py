"""VGGish audio extractor (ref models/vggish/extract_vggish.py and
models/vggish_torch/extract_vggish.py — one extractor serves both
``vggish`` and ``vggish_torch``: the reference variants differ only in
runtime (TF1 session vs torch), not in contract).

Per input: ``.wav`` consumed directly; video containers ripped via
ffmpeg when available (ref utils/utils.py:247-276); waveform -> log-mel
(96, 64) examples on the host -> examples batched to a bucketed static
shape -> jit VGG -> raw (N, 128) float embeddings.

Output contract: ``{vggish: (Ta, 128)}``, Ta = duration/0.96 s; no
fps/timestamps meta (ref extract_vggish.py:105-108).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.audio import load_audio_for_model
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.models.common.weights import load_params, random_init_fallback
from video_features_tpu.models.vggish.convert import convert_state_dict
from video_features_tpu.models.vggish.mel import SAMPLE_RATE, waveform_to_examples
from video_features_tpu.models.vggish.model import (
    VGGISH_EMBEDDING_DIM,
    build,
    init_params,
)
from video_features_tpu.ops.window import bucket_size, pad_batch


class ExtractVGGish(BaseExtractor):
    # --sharding mesh: the 0.96 s example batch shards over 'data'
    # (pure DP; the VGG weights replicate — tiny next to activations)
    mesh_capable = True
    # preflight contract: this path consumes audio — a bare .wav is a
    # legitimate input here, and a video container is probed for
    # openability only (audio-stream presence resolves at rip time)
    media_need = "audio"

    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self._host_params = None

    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path, convert_state_dict
                )
            else:
                random_init_fallback(
                    self.config, self.feature_type,
                    "a torchvggish state dict (vggish-10086976.pth) or a "
                    "converted flax .msgpack",
                )
                self._host_params = init_params()
        return self._host_params

    def _build(self, device):
        from video_features_tpu.parallel.sharding import (
            jit_sharded_forward,
            place_params,
        )

        model = build()
        params = place_params(self._load_host_params(), device)
        forward = jit_sharded_forward(
            lambda p, x: model.apply({"params": p}, x), device  # (B, 96, 64, 1)
        )
        return {"params": params, "forward": forward, "device": device}

    # host half: wav rip + NumPy log-mel frontend (runs on
    # --decode_workers threads under the async pipeline)
    def prepare(self, path_entry):
        path = video_path_of(path_entry)
        samples = load_audio_for_model(
            path, SAMPLE_RATE, self.tmp_path, self.config.keep_tmp_files
        )
        examples = waveform_to_examples(samples, SAMPLE_RATE)  # (N, 96, 64)
        n = examples.shape[0]
        if n == 0:
            return None, 0
        x = pad_batch(
            examples[..., None], bucket_size(n, buckets=self.config.shape_buckets)
        )
        return x, n

    # device half, split for the device pipeline (extract/base.py):
    # transfer + async jitted VGG forward at dispatch, fetch later
    def dispatch_prepared(self, device, state, path_entry, payload):
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        x, n = payload
        if n == 0:
            return None, 0
        x = place_batch(pad_batch_for(state["device"], x), state["device"])
        return state["forward"](state["params"], x), n

    def fetch_dispatched(self, handle) -> Dict[str, np.ndarray]:
        out, n = handle
        if n == 0:
            return {
                self.feature_type: np.zeros((0, VGGISH_EMBEDDING_DIM), np.float32)
            }
        return {self.feature_type: np.asarray(out)[:n]}

    # --- cross-video aggregation (--video_batch): N clips' 0.96 s example
    # batches concatenate into ONE VGG forward at fixed per-key offsets
    # (the CLIP bucket-offset pattern; CLIP's own variant differs only in
    # its mesh_context placement and fps/timestamp metas). A short clip
    # yields 1-5 (96, 64) examples — far below what fills the MXU.
    AGG_MAX_EXAMPLES = 1024  # ~25 MB fp32 per payload; hour-long audio
    # dispatches alone rather than parking N-1 such buffers host-side

    def agg_key(self, payload):
        x, n = payload
        if n == 0 or x.shape[0] > self.AGG_MAX_EXAMPLES:
            return None
        return x.shape  # the bucketed (B, 96, 64, 1) shape

    def dispatch_group(self, device, state, entries, payloads):
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        group = max(int(self.config.video_batch or 1), 1)
        bucket = payloads[0][0].shape[0]
        x = np.concatenate([p[0] for p in payloads], axis=0)
        if len(payloads) < group:  # partial flush: keep the compiled shape
            x = pad_batch(x, group * bucket)
        x = place_batch(pad_batch_for(state["device"], x), state["device"])
        out = state["forward"](state["params"], x)
        return out, [(i * bucket, p[1]) for i, p in enumerate(payloads)]

    def fetch_group(self, handle):
        out, metas = handle
        arr = np.asarray(out)
        return [{self.feature_type: arr[off : off + n]} for off, n in metas]
