"""torchvggish checkpoint (vggish-10086976.pth) -> Flax param tree,
plus the PCA-params checkpoint for the optional postprocessor.

torch naming (ref models/vggish_torch/vggish_src/vggish.py:120-130):
``features.{0,3,6,8,11,13}.{weight,bias}`` convs and
``embeddings.{0,2,4}.{weight,bias}`` linears;
PCA file holds ``pca_eigen_vectors`` (128,128) / ``pca_means`` (128,).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from video_features_tpu.models.common.weights import (
    check_all_consumed,
    conv2d_kernel,
    strip_prefix,
    transpose_linear,
)
from video_features_tpu.models.vggish.model import _CONV_LAYOUT


def convert_state_dict(sd: Dict[str, np.ndarray]):
    sd = strip_prefix(sd, "module.")
    consumed = set()
    params = {}
    for idx, _ in _CONV_LAYOUT:
        consumed.update((f"features.{idx}.weight", f"features.{idx}.bias"))
        params[f"features_{idx}"] = {
            "kernel": conv2d_kernel(sd[f"features.{idx}.weight"]),
            "bias": sd[f"features.{idx}.bias"],
        }
    for idx in (0, 2, 4):
        consumed.update((f"embeddings.{idx}.weight", f"embeddings.{idx}.bias"))
        params[f"embeddings_{idx}"] = {
            "kernel": transpose_linear(sd[f"embeddings.{idx}.weight"]),
            "bias": sd[f"embeddings.{idx}.bias"],
        }
    check_all_consumed(sd, consumed, "VGGish")
    return params


def convert_pca_params(sd: Dict[str, np.ndarray]):
    return {
        "pca_eigen_vectors": np.asarray(sd["pca_eigen_vectors"], np.float32),
        "pca_means": np.asarray(sd["pca_means"], np.float32).reshape(-1),
    }
