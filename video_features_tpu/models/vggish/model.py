"""VGGish (AudioSet VGG) in Flax + the PCA/quantize postprocessor.

Reference: the torchvggish port the reference vendors
(ref models/vggish_torch/vggish_src/vggish.py:9-189): VGG-style conv
stack [64, M, 128, M, 256, 256, M, 512, 512, M] on (96, 64) log-mel
patches, then 4096-4096-128 fully-connected embeddings with a FINAL ReLU.
NHWC here; torch flattens (N, 512, 6, 4) as (H, W, C) before the first
Linear, which is exactly the natural NHWC flatten, so converted Linear
weights apply unchanged.

Both reference extractors emit the RAW 128-d floats — the TF variant
instantiates its PCA postprocessor but never applies it
(ref models/vggish/extract_vggish.py:56,100-104) and the torch variant
passes ``postprocess=False`` (ref models/vggish_torch/extract_vggish.py:
51-52). :func:`postprocess` is provided for library users wanting the
AudioSet-compatible 8-bit embeddings (ref vggish.py:34-105).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

VGGISH_EMBEDDING_DIM = 128
QUANTIZE_MIN_VAL = -2.0
QUANTIZE_MAX_VAL = 2.0

# torch Sequential indices of the convs in make_layers() (ref vggish.py:120-130)
_CONV_LAYOUT: Tuple[Tuple[int, int], ...] = (
    (0, 64), (3, 128), (6, 256), (8, 256), (11, 512), (13, 512),
)
_POOL_AFTER = {0, 3, 8, 13}  # a 2x2 max pool follows these convs


class VGGishNet(nn.Module):
    """(N, 96, 64, 1) log-mel examples -> (N, 128) embeddings."""

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for idx, ch in _CONV_LAYOUT:
            x = nn.relu(
                nn.Conv(ch, (3, 3), padding=[(1, 1), (1, 1)], name=f"features_{idx}")(x)
            )
            if idx in _POOL_AFTER:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)  # (N, 6*4*512), NHWC == torch's flatten
        x = nn.relu(nn.Dense(4096, name="embeddings_0")(x))
        x = nn.relu(nn.Dense(4096, name="embeddings_2")(x))
        return nn.relu(nn.Dense(VGGISH_EMBEDDING_DIM, name="embeddings_4")(x))


def postprocess(embeddings: jnp.ndarray, pca: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """AudioSet PCA-whiten + 8-bit quantize (ref vggish.py:47-105):
    clip((x - means) @ E^T, ±2) mapped to [0, 255] and rounded."""
    centered = embeddings - pca["pca_means"].reshape(1, -1)
    applied = centered @ pca["pca_eigen_vectors"].T
    clipped = jnp.clip(applied, QUANTIZE_MIN_VAL, QUANTIZE_MAX_VAL)
    quantized = jnp.round(
        (clipped - QUANTIZE_MIN_VAL) * (255.0 / (QUANTIZE_MAX_VAL - QUANTIZE_MIN_VAL))
    )
    # uint8, matching the reference's .astype(np.uint8) output contract
    # (ref vggish_src/vggish_postprocess.py:83-91)
    return quantized.astype(jnp.uint8)


def build() -> VGGishNet:
    return VGGishNet()


def init_params(seed: int = 0):
    model = build()
    dummy = jnp.zeros((1, 96, 64, 1), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)["params"]
