"""VGGish log-mel frontend (pure NumPy, host-side).

Semantics follow the AudioSet feature pipeline the reference vendors
(ref models/vggish/vggish_src/mel_features.py:195-223, vggish_input.py:
27-71, vggish_params.py:22-41): 25 ms periodic-Hann windows hopped 10 ms,
512-point rFFT magnitudes, HTK-formula 64-band mel filterbank over
125-7500 Hz with a zeroed DC bin, log with +0.01 offset, framed into
non-overlapping 0.96 s examples of shape (96, 64).

Resampling: io.audio implements the reference's resampy kaiser_best
windowed sinc natively (the r4-era scipy polyphase substitute measured
2.6e-3 relative L2 on final embeddings — past the 1e-3 budget; PARITY.md
"Known intentional divergences" has the numbers).
"""

from __future__ import annotations

import numpy as np

SAMPLE_RATE = 16000
STFT_WINDOW_SECONDS = 0.025
STFT_HOP_SECONDS = 0.010
NUM_MEL_BINS = 64
MEL_MIN_HZ = 125.0
MEL_MAX_HZ = 7500.0
LOG_OFFSET = 0.01
EXAMPLE_WINDOW_SECONDS = 0.96
EXAMPLE_HOP_SECONDS = 0.96

_MEL_BREAK_HZ = 700.0
_MEL_HIGH_Q = 1127.0


def frame(data: np.ndarray, window_length: int, hop_length: int) -> np.ndarray:
    """(num_samples, ...) -> (num_frames, window_length, ...); ragged tail
    dropped, no padding."""
    n = 1 + int(np.floor((data.shape[0] - window_length) / hop_length))
    if n < 1:
        return np.zeros((0, window_length) + data.shape[1:], data.dtype)
    idx = np.arange(window_length)[None, :] + hop_length * np.arange(n)[:, None]
    return data[idx]


def periodic_hann(window_length: int) -> np.ndarray:
    """Full-cycle raised cosine (matlab 'periodic'), not np.hanning's
    symmetric window."""
    return 0.5 - 0.5 * np.cos(2 * np.pi / window_length * np.arange(window_length))


def stft_magnitude(
    signal: np.ndarray, fft_length: int, hop_length: int, window_length: int
) -> np.ndarray:
    frames = frame(signal, window_length, hop_length)
    return np.abs(np.fft.rfft(frames * periodic_hann(window_length), int(fft_length)))


def hertz_to_mel(frequencies_hertz):
    """HTK mel scale."""
    return _MEL_HIGH_Q * np.log(1.0 + np.asarray(frequencies_hertz) / _MEL_BREAK_HZ)


def spectrogram_to_mel_matrix(
    num_mel_bins: int = NUM_MEL_BINS,
    num_spectrogram_bins: int = 257,
    audio_sample_rate: int = SAMPLE_RATE,
    lower_edge_hertz: float = MEL_MIN_HZ,
    upper_edge_hertz: float = MEL_MAX_HZ,
) -> np.ndarray:
    """(num_spectrogram_bins, num_mel_bins) triangular filterbank, linear
    in mel; DC bin zeroed."""
    nyquist = audio_sample_rate / 2.0
    if not 0.0 <= lower_edge_hertz < upper_edge_hertz <= nyquist:
        raise ValueError(
            f"bad mel range [{lower_edge_hertz}, {upper_edge_hertz}] for nyquist {nyquist}"
        )
    bins_mel = hertz_to_mel(np.linspace(0.0, nyquist, num_spectrogram_bins))
    edges_mel = np.linspace(
        hertz_to_mel(lower_edge_hertz), hertz_to_mel(upper_edge_hertz), num_mel_bins + 2
    )
    lower = edges_mel[:-2][None, :]
    center = edges_mel[1:-1][None, :]
    upper = edges_mel[2:][None, :]
    lower_slope = (bins_mel[:, None] - lower) / (center - lower)
    upper_slope = (upper - bins_mel[:, None]) / (upper - center)
    weights = np.maximum(0.0, np.minimum(lower_slope, upper_slope))
    weights[0, :] = 0.0
    return weights


def log_mel_spectrogram(data: np.ndarray, audio_sample_rate: int = SAMPLE_RATE) -> np.ndarray:
    """waveform -> (num_frames, 64) log mel magnitudes."""
    window_length = int(round(audio_sample_rate * STFT_WINDOW_SECONDS))
    hop_length = int(round(audio_sample_rate * STFT_HOP_SECONDS))
    fft_length = 2 ** int(np.ceil(np.log2(window_length)))
    spec = stft_magnitude(data, fft_length, hop_length, window_length)
    mel = spec @ spectrogram_to_mel_matrix(
        num_spectrogram_bins=spec.shape[1], audio_sample_rate=audio_sample_rate
    )
    return np.log(mel + LOG_OFFSET)


def waveform_to_examples(data: np.ndarray, sample_rate: int) -> np.ndarray:
    """mono/multichannel waveform -> (num_examples, 96, 64) float32."""
    from video_features_tpu.io.audio import resample, to_mono

    data = to_mono(np.asarray(data))
    data = resample(data, sample_rate, SAMPLE_RATE)
    log_mel = log_mel_spectrogram(data, SAMPLE_RATE)
    features_rate = 1.0 / STFT_HOP_SECONDS
    window = int(round(EXAMPLE_WINDOW_SECONDS * features_rate))
    hop = int(round(EXAMPLE_HOP_SECONDS * features_rate))
    return frame(log_mel, window, hop).astype(np.float32)
