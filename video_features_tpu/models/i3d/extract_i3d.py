"""I3D two-stream extractor (ref models/i3d/extract_i3d.py) — the
deepest pipeline: RGB + optical-flow Kinetics features over sliding
64-frame stacks, with flow computed on the fly by RAFT or PWC, or read
from pre-extracted flow JPEGs (``--flow_type flow`` + ``--flow_dir``).

Per video (ref extract_i3d.py:239-297): frames sampled on the reference's
grid — ``--extraction_fps`` linspace, or upsampling-to-65 for short
videos (against the DEFAULT stack of 64, a reference quirk kept even when
``--stack_size`` differs), or all frames — resized min-side 256, windowed
into stack_size+1 frame stacks sliding by step_size (ragged tail
dropped). Each stream runs as ONE jitted pipeline per video resolution:
flow model (RAFT on /8-replicate-padded stacks, flow kept at padded res
exactly like the reference, ref extract_i3d.py:170-173) -> center-crop
224 -> clamp[-20,20] -> uint8 quantize -> [-1,1] -> I3D; RGB ->
center-crop 224 -> [-1,1] -> I3D.

Weights: ``--weights_path`` points to a DIRECTORY holding any of
``i3d_rgb.pt``, ``i3d_flow.pt``, ``raft-sintel.pth``, ``pwc_net_sintel.pt``
(the reference hardcodes these names, ref extract_i3d.py:23-26); an
absent path or missing file is a hard error unless --allow_random_init.

Output contract: ``{rgb: (S, 1024), flow: (S, 1024), fps, timestamps_ms}``
(ref extract_i3d.py:299-303). Divergences (also in PARITY.md):

- timestamps: the reference computes ``0.001/fps`` (claiming ms, off by
  1e6, ref extract_i3d.py:242); here they are real milliseconds.
- channel order: the reference decodes via mmcv (BGR) and — unlike its
  resnet/raft/pwc extractors, which call cvtColor — feeds BGR frames to
  the I3D RGB stream and the flow nets (ref extract_i3d.py:239-259).
  Here frames are RGB, the convention the pretrained Kinetics weights
  were trained with, so rgb-stream features differ numerically from the
  reference's (which are subtly wrong).
- flow-from-disk JPEGs are treated as already-quantized uint8 flow (see
  ``flow_fn``); the reference re-clamps them into garbage.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List

import cv2
import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import form_slices, video_path_of
from video_features_tpu.io.video import probe, read_frames_at_indices
from video_features_tpu.models.common.weights import load_params, random_init_fallback
from video_features_tpu.models.i3d.convert import convert_state_dict as i3d_convert
from video_features_tpu.models.i3d.model import build as i3d_build
from video_features_tpu.models.i3d.model import init_params as i3d_init
from video_features_tpu.ops.preprocess import flow_to_uint8, pil_resize, scale_to_1_1
from video_features_tpu.utils.labels import show_predictions_on_dataset

MIN_SIDE_SIZE = 256
CENTRAL_CROP_SIZE = 224
DEFAULT_STACK_SIZE = 64
DEFAULT_STEP_SIZE = 64

# checkpoint file names searched under --weights_path (a directory),
# mirroring the reference's hardcoded paths (ref extract_i3d.py:23-26)
WEIGHT_FILES = {
    "rgb": "i3d_rgb.pt",
    "flow": "i3d_flow.pt",
    "raft": "raft-sintel.pth",
    "pwc": "pwc_net_sintel.pt",
}


@functools.lru_cache(maxsize=256)
def _device_geometry(h: int, w: int, bucket_multiple: int, flow_type: str):
    """Shape contracts for one raw source resolution under ``--preprocess
    device`` (both streams):

    - rgb: the min-edge-256 resize composes with the reference's FLOOR
      center crop into crop-fused taps — a fixed (224, 224) output, so
      the rgb stream needs no output bucket at all.
    - flow: min-edge-256 taps resize onto an OUTPUT BUCKET — the RAFT
      InputPadder /8 grid of the resized shape rounded up to
      ``bucket_multiple`` (ops/window.py::flow_output_bucket) so a
      variable-resolution corpus compiles O(buckets) flow executables —
      with the image edge-replicated at the centered InputPadder
      placement (the validity contract: input-bucket pad columns carry
      zero tap weight, output pad rows repeat the image edge exactly as
      host ``np.pad(mode="edge")`` would). PWC instead stretches to /64
      in-model, so its contract is the EXACT resized shape (bucketing
      would squash the geometry). The 224-crop offsets into the flow
      grid are returned as int32 scalars and ship as jit INPUTS
      (ops/preprocess.py::dynamic_center_crop), so the crop position can
      vary per source while the executable stays per-bucket.
    """
    from video_features_tpu.models.raft.model import input_grid
    from video_features_tpu.ops.resize import (
        fused_resize_crop_banded,
        resized_hw,
        shape_contract_banded,
    )
    from video_features_tpu.ops.window import flow_output_bucket, spatial_bucket

    bh, bw = spatial_bucket(h, w, bucket_multiple)
    oh, ow = resized_hw(h, w, MIN_SIDE_SIZE)
    rgb_wy_t, rgb_wy_i, rgb_wx_t, rgb_wx_i = fused_resize_crop_banded(
        h, w, MIN_SIDE_SIZE, CENTRAL_CROP_SIZE, "bilinear",
        pad_h=bh, pad_w=bw, crop_offset="floor",
    )
    if flow_type == "raft":
        tgt_h, tgt_w = input_grid(oh, ow)
        out_h, out_w = flow_output_bucket(oh, ow, multiple=bucket_multiple)
        top, left = (out_h - oh) // 2, (out_w - ow) // 2
        # host crops the /8-PADDED flow with floor offsets; replay that
        # region relative to where the bucket places the image
        fh = top + (tgt_h - CENTRAL_CROP_SIZE) // 2 - (tgt_h - oh) // 2
        fw = left + (tgt_w - CENTRAL_CROP_SIZE) // 2 - (tgt_w - ow) // 2
    else:  # pwc: exact resized grid, host-identical floor crop
        out_h, out_w, top, left = oh, ow, 0, 0
        fh = (oh - CENTRAL_CROP_SIZE) // 2
        fw = (ow - CENTRAL_CROP_SIZE) // 2
    if not (0 <= fh <= out_h - CENTRAL_CROP_SIZE
            and 0 <= fw <= out_w - CENTRAL_CROP_SIZE):
        raise AssertionError(
            f"flow crop {(fh, fw)} escapes the {(out_h, out_w)} grid "
            f"for source {(h, w)}"
        )
    f_wy_t, f_wy_i, f_wx_t, f_wx_i = shape_contract_banded(
        h, w, MIN_SIDE_SIZE, out_h, out_w, top, left, "bilinear",
        pad_h=bh, pad_w=bw, pad_mode="edge",
    )
    return {
        "bucket": (bh, bw),
        "grid": (out_h, out_w),
        "rgb_wy": (rgb_wy_t, rgb_wy_i),
        "rgb_wx": (rgb_wx_t, rgb_wx_i),
        "flow_wy": (f_wy_t, f_wy_i),
        "flow_wx": (f_wx_t, f_wx_i),
        "crop": (np.int32(fh), np.int32(fw)),
    }


def center_crop(x: jnp.ndarray, crop: int = CENTRAL_CROP_SIZE) -> jnp.ndarray:
    """(..., H, W, C) tensor-space center crop (ref transforms.py:7-18)."""
    H, W = x.shape[-3], x.shape[-2]
    fh = (H - crop) // 2
    fw = (W - crop) // 2
    return x[..., fh : fh + crop, fw : fw + crop, :]


# The parity-critical transform chains, defined ONCE on trailing axes so
# the per-stack (mesh) and stack-batched (single-device) pipelines share
# them exactly — a fix here reaches both execution modes.
def rgb_chain(stack_tail: jnp.ndarray) -> jnp.ndarray:
    """RGB frames -> I3D input (ref extract_i3d.py:178-184)."""
    return scale_to_1_1(center_crop(stack_tail))


def flow_chain(flow: jnp.ndarray) -> jnp.ndarray:
    """Raw flow -> I3D input: crop the PADDED flow like the reference
    (ref extract_i3d.py:170-184), clamp/quantize to uint8 levels, scale."""
    return scale_to_1_1(flow_to_uint8(center_crop(flow)))


def disk_flow_chain(flow_imgs: jnp.ndarray) -> jnp.ndarray:
    """Flow JPEGs already hold the uint8-QUANTIZED flow (the
    128 + 255/40*f map; what sink save_jpg and denseflow-style tools
    write), so only the [-1,1] scaling remains. Intentional divergence,
    documented in PARITY.md: the reference re-applies
    Clamp(-20,20)+ToUInt8 to the 0..255 pixels (extract_i3d.py:204-220),
    collapsing nearly every value to 255 — its flow-from-disk features
    are garbage, and no round-trip with its own flow extractors can
    work."""
    return scale_to_1_1(center_crop(flow_imgs))


class ExtractI3D(BaseExtractor):
    # --sharding mesh: each stack's FRAME axis shards over 'data' inside
    # the jitted per-stream pipelines (sequence parallelism: GSPMD halo
    # exchanges for RAFT/PWC pair views and I3D's temporal convs);
    # weights replicate
    mesh_capable = True

    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.streams = list(self.config.streams or ["rgb", "flow"])
        self.flow_type = self.config.flow_type or "pwc"
        self.stack_size = int(self.config.stack_size or DEFAULT_STACK_SIZE)
        self.step_size = int(self.config.step_size or DEFAULT_STEP_SIZE)
        # --batch_size B: window stacks per fused device call (the
        # reference's i3d path ignores the flag; here it batches stacks
        # the way its 2D nets batch frames). The last group is zero-padded
        # up to B (ops/window.pad_batch) so XLA keeps one compiled shape;
        # surplus outputs are sliced off. Mesh runs pin B=1 — there the stack's
        # FRAME axis is what shards (sequence parallelism).
        self.stack_batch = max(int(self.config.batch_size or 1), 1)
        # --conv3d_impl: threads into THIS extractor's model only — never
        # written to the process env, so two extractors with different
        # configs in one process can't clobber each other's lowering
        from video_features_tpu.models.common.layers import explicit_conv3d_impl

        self.conv_impl = explicit_conv3d_impl(self.config)
        self._host_params: Dict[str, object] = {}

    def feature_keys(self):
        return list(self.streams)  # i3d saves <stem>_rgb.npy / <stem>_flow.npy

    # --- weights -----------------------------------------------------------
    def _weights_file(self, kind: str):
        root = self.config.weights_path
        if root is None:
            return None
        if not os.path.isdir(root):
            raise ValueError(
                "i3d needs several checkpoints; --weights_path must be a "
                f"DIRECTORY containing any of {sorted(WEIGHT_FILES.values())} "
                f"(got file: {root})"
            )
        path = os.path.join(root, WEIGHT_FILES[kind])
        return path if os.path.exists(path) else None

    def _params(self, kind: str):
        if kind not in self._host_params:
            path = self._weights_file(kind)
            if path is None:
                # loud on BOTH an absent --weights_path and a directory
                # missing this stream/flow-model's file
                root = self.config.weights_path
                expected = (
                    f"{os.path.join(root, WEIGHT_FILES[kind])}"
                    if root
                    else f"a directory containing {WEIGHT_FILES[kind]}"
                )
                random_init_fallback(self.config, f"i3d[{kind}]", expected)
            if kind in ("rgb", "flow"):
                self._host_params[kind] = (
                    load_params(path, i3d_convert) if path else i3d_init(kind)
                )
            elif kind == "raft":
                from video_features_tpu.models.raft.convert import (
                    convert_state_dict as raft_convert,
                )
                from video_features_tpu.models.raft.model import (
                    init_params as raft_init,
                )

                self._host_params[kind] = (
                    load_params(path, raft_convert) if path else raft_init()
                )
            else:  # pwc
                from video_features_tpu.models.pwc.convert import (
                    convert_state_dict as pwc_convert,
                )
                from video_features_tpu.models.pwc.model import (
                    init_params as pwc_init,
                )

                self._host_params[kind] = (
                    load_params(path, pwc_convert) if path else pwc_init()
                )
        return self._host_params[kind]

    # --- per-device state --------------------------------------------------
    def _build(self, device):
        from video_features_tpu.models.common.weights import (
            cast_floats_for_compute,
            compute_dtype,
        )

        from video_features_tpu.parallel.sharding import place_params

        dt = compute_dtype(self.config)
        state = {"device": device, "params": {}, "fns": {}, "dtype": dt}
        for stream in self.streams:
            p = self._params(stream)
            if dt != jnp.float32:
                # I3D streams run bf16 (logits head stays fp32). RAFT and
                # PWC run their MIXED-precision graphs (convs bf16; flow
                # estimates / corr / warp-or-lookup recurrence pinned fp32
                # — models/{raft,pwc}/model.py docstrings)
                p = cast_floats_for_compute(p, dt, exclude=("conv3d_0c_1x1",))
            state["params"][stream] = place_params(p, device)
        if "flow" in self.streams and self.flow_type in ("raft", "pwc"):
            state["params"][self.flow_type] = place_params(
                self._params(self.flow_type), device
            )
        return state

    def _fns_for_shape(self, state, shape):
        """Jitted per-stream pipelines for one (H, W) frame shape.

        On a Mesh, the stack's FRAME axis shards over 'data' (the same
        sequence parallelism as the standalone flow extractors): GSPMD
        inserts the pair-view halo exchange for RAFT/PWC and the
        temporal-conv halos for I3D itself; weights replicate. The
        constraint is applied inside jit, so uneven stack lengths (11..65
        frames) need no host-side padding."""
        from video_features_tpu.parallel.sharding import is_mesh

        key = ("dev",) if self._device_preprocess_enabled() else tuple(shape)
        if key in state["fns"]:
            return state["fns"][key]
        i3d = i3d_build(
            dtype=state.get("dtype", jnp.float32), conv_impl=self.conv_impl
        )
        fns = {}

        if key == ("dev",) and not is_mesh(state["device"]):
            # shape-contracted device preprocess: ONE set of jitted fns
            # regardless of source resolution — the taps, raw uint8
            # stacks, and crop offsets are all INPUTS, so jax.jit's own
            # shape cache compiles one executable per (input bucket,
            # output grid) contract rather than per source shape.
            # sanity_check guarantees flow_type raft/pwc for I3D device
            # preprocess; the `not is_mesh` conjunct makes the
            # single-device claim visible to GC50x (the fused MESH
            # variants live in their own branch below with the full
            # payload sharding contract declared).
            from video_features_tpu.ops.preprocess import (
                device_resize_frames,
                dynamic_center_crop,
            )

            if "rgb" in self.streams:

                @jax.jit
                def rgb_fn(p, stacks, wy, wx):
                    # (B, S+1, bh, bw, 3) uint8; crop-fused taps land the
                    # min-edge-256 resize + floor 224-crop in one pass
                    x = device_resize_frames(stacks[:, :-1], wy, wx)
                    return i3d.apply({"params": p}, scale_to_1_1(x))

                fns["rgb"] = rgb_fn

            if "flow" in self.streams and self.flow_type == "raft":
                from video_features_tpu.models.raft.model import build as raft_build

                raft = raft_build(dtype=state.get("dtype", jnp.float32))

                @jax.jit
                def flow_fn(p_flow, p_i3d, stacks, wy, wx, fh, fw):
                    # taps place the resized image on the /8 output
                    # bucket with edge replication — InputPadder's pad is
                    # already inside the resize
                    x = device_resize_frames(stacks, wy, wx)
                    flow = jax.vmap(
                        lambda s: raft.apply({"params": p_flow}, s)
                    )(x)
                    f = dynamic_center_crop(flow, fh, fw, CENTRAL_CROP_SIZE)
                    f = scale_to_1_1(flow_to_uint8(f))
                    return i3d.apply({"params": p_i3d}, f)

                fns["flow"] = flow_fn
            elif "flow" in self.streams and self.flow_type == "pwc":
                from video_features_tpu.models.pwc.model import build as pwc_build

                pwc = pwc_build(dtype=state.get("dtype", jnp.float32))

                @jax.jit
                def flow_fn(p_flow, p_i3d, stacks, wy, wx, fh, fw):
                    # exact (oh, ow) contract — PWC's in-model /64
                    # stretch must see the true resized geometry
                    x = device_resize_frames(stacks, wy, wx)
                    flow = jax.vmap(
                        lambda s: pwc.apply({"params": p_flow}, s)
                    )(x)
                    f = dynamic_center_crop(flow, fh, fw, CENTRAL_CROP_SIZE)
                    f = scale_to_1_1(flow_to_uint8(f))
                    return i3d.apply({"params": p_i3d}, f)

                fns["flow"] = flow_fn

            state["fns"][key] = fns
            return fns

        if key == ("dev",) and is_mesh(state["device"]):
            # fused device preprocess ON the mesh: per-stack fns like the
            # host-mesh branch below, but consuming raw uint8 stacks plus
            # the shape-contract payload (taps, crop offsets). The full
            # payload declares its sharding (GC502/GC504): every input
            # replicates in — the taps and offsets are per-shape
            # metadata, and the raw stack re-shards over 'data' via the
            # in-body constraint, which tolerates the uneven S+1 frame
            # axis — and outputs pin replicated so the single-stack
            # feature row fetches whole.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from video_features_tpu.ops.preprocess import (
                device_resize_frames,
                dynamic_center_crop,
            )

            seq = NamedSharding(state["device"], P("data"))
            rep = NamedSharding(state["device"], P())

            if "rgb" in self.streams:

                def rgb_fn(p, stack, wy, wx):
                    # (S+1, bh, bw, 3) uint8 per stack; crop-fused taps
                    # land the min-edge-256 resize + floor 224-crop in
                    # one pass, sharded over the frame axis
                    stack = jax.lax.with_sharding_constraint(stack, seq)
                    x = device_resize_frames(stack[:-1], wy, wx)
                    return i3d.apply({"params": p}, scale_to_1_1(x)[None])

                fns["rgb"] = jax.jit(
                    rgb_fn,
                    in_shardings=(None, rep, (rep, rep), (rep, rep)),
                    out_shardings=rep,
                )

            if "flow" in self.streams and self.flow_type == "raft":
                from video_features_tpu.models.raft.model import build as raft_build

                raft = raft_build(dtype=state.get("dtype", jnp.float32))

                def flow_fn(p_flow, p_i3d, stack, wy, wx, fh, fw):
                    # taps place the resized image on the /8 output
                    # bucket with edge replication (InputPadder's pad is
                    # inside the resize); the sharded frame axis gives
                    # RAFT's pair views their GSPMD halo exchange
                    stack = jax.lax.with_sharding_constraint(stack, seq)
                    x = device_resize_frames(stack, wy, wx)
                    flow = raft.apply({"params": p_flow}, x)  # (S, Hb, Wb, 2)
                    f = dynamic_center_crop(flow, fh, fw, CENTRAL_CROP_SIZE)
                    f = scale_to_1_1(flow_to_uint8(f))
                    return i3d.apply({"params": p_i3d}, f[None])

                fns["flow"] = jax.jit(
                    flow_fn,
                    in_shardings=(None, None, rep, (rep, rep), (rep, rep),
                                  rep, rep),
                    out_shardings=rep,
                )
            elif "flow" in self.streams and self.flow_type == "pwc":
                from video_features_tpu.models.pwc.model import build as pwc_build

                pwc = pwc_build(dtype=state.get("dtype", jnp.float32))

                def flow_fn(p_flow, p_i3d, stack, wy, wx, fh, fw):
                    # exact (oh, ow) contract — PWC's in-model /64
                    # stretch must see the true resized geometry
                    stack = jax.lax.with_sharding_constraint(stack, seq)
                    x = device_resize_frames(stack, wy, wx)
                    flow = pwc.apply({"params": p_flow}, x)
                    f = dynamic_center_crop(flow, fh, fw, CENTRAL_CROP_SIZE)
                    f = scale_to_1_1(flow_to_uint8(f))
                    return i3d.apply({"params": p_i3d}, f[None])

                fns["flow"] = jax.jit(
                    flow_fn,
                    in_shardings=(None, None, rep, (rep, rep), (rep, rep),
                                  rep, rep),
                    out_shardings=rep,
                )

            state["fns"][key] = fns
            return fns

        if is_mesh(state["device"]):
            # mesh: per-stack fns, the FRAME axis shards (untouched by
            # --batch_size stack batching, which is the single-device
            # throughput knob)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from video_features_tpu.parallel.sharding import (
                multihost_out_kwargs,
            )

            seq = NamedSharding(state["device"], P("data"))
            # multi-host: outputs pin replicated so every process can
            # fetch (sharding.py::multihost_out_kwargs); single-host
            # keeps propagation
            mh = multihost_out_kwargs(state["device"])

            def shard_seq(stack):
                return jax.lax.with_sharding_constraint(stack, seq)

            if "rgb" in self.streams:

                @functools.partial(jax.jit, **mh)
                def rgb_fn(p, stack):  # (S+1, H, W, 3) raw [0,255] floats
                    # stack[:-1] in EVERY mode: with pre-extracted flow
                    # the window is stack_size, so rgb runs on
                    # stack_size-1 frames — exactly the reference
                    # (extract_i3d.py:178-179,221-222)
                    x = rgb_chain(shard_seq(stack)[:-1])
                    return i3d.apply({"params": p}, x[None])

                fns["rgb"] = rgb_fn

            if "flow" in self.streams and self.flow_type == "raft":
                raft, (l, r, t, b) = self._raft_and_pad(
                    shape, state.get("dtype", jnp.float32)
                )

                @functools.partial(jax.jit, **mh)
                def flow_fn(p_flow, p_i3d, stack):
                    padded = jnp.pad(
                        shard_seq(stack), ((0, 0), (t, b), (l, r), (0, 0)),
                        mode="edge",
                    )
                    flow = raft.apply({"params": p_flow}, padded)  # (S, Hp, Wp, 2)
                    return i3d.apply({"params": p_i3d}, flow_chain(flow)[None])

                fns["flow"] = flow_fn
            elif "flow" in self.streams and self.flow_type == "pwc":
                from video_features_tpu.models.pwc.model import build as pwc_build

                pwc = pwc_build(dtype=state.get("dtype", jnp.float32))

                @functools.partial(jax.jit, **mh)
                def flow_fn(p_flow, p_i3d, stack):
                    flow = pwc.apply({"params": p_flow}, shard_seq(stack))
                    return i3d.apply({"params": p_i3d}, flow_chain(flow)[None])

                fns["flow"] = flow_fn
            elif "flow" in self.streams and self.flow_type == "flow":

                @functools.partial(jax.jit, **mh)
                def flow_fn(p_i3d, flow_imgs):  # (S, H', W', 2) as floats
                    f = disk_flow_chain(shard_seq(flow_imgs))
                    return i3d.apply({"params": p_i3d}, f[None])

                fns["flow"] = flow_fn

            state["fns"][key] = fns
            return fns

        # single device: STACK-BATCHED fns — every input carries a leading
        # (B,) group axis (--batch_size; B=1 keeps the reference's
        # one-stack-at-a-time math, just with a batch dim). I3D takes the
        # batch natively; the flow nets consume one SEQUENCE each, so they
        # vmap over the group. Transform chains are the same module-level
        # functions the mesh fns use.
        if "rgb" in self.streams:

            @jax.jit
            def rgb_fn(p, stacks):  # (B, S+1, H, W, 3) raw [0,255] floats
                # [:, :-1] in EVERY mode — see the mesh variant's note
                return i3d.apply({"params": p}, rgb_chain(stacks[:, :-1]))

            fns["rgb"] = rgb_fn

        if "flow" in self.streams and self.flow_type == "raft":
            raft, (l, r, t, b) = self._raft_and_pad(
                shape, state.get("dtype", jnp.float32)
            )

            @jax.jit
            def flow_fn(p_flow, p_i3d, stacks):  # (B, S+1, H, W, 3)
                padded = jnp.pad(
                    stacks, ((0, 0), (0, 0), (t, b), (l, r), (0, 0)),
                    mode="edge",
                )
                flow = jax.vmap(lambda s: raft.apply({"params": p_flow}, s))(
                    padded
                )  # (B, S, Hp, Wp, 2)
                return i3d.apply({"params": p_i3d}, flow_chain(flow))

            fns["flow"] = flow_fn
        elif "flow" in self.streams and self.flow_type == "pwc":
            from video_features_tpu.models.pwc.model import build as pwc_build

            pwc = pwc_build(dtype=state.get("dtype", jnp.float32))

            @jax.jit
            def flow_fn(p_flow, p_i3d, stacks):  # (B, S+1, H, W, 3)
                flow = jax.vmap(lambda s: pwc.apply({"params": p_flow}, s))(
                    stacks
                )  # (B, S, H, W, 2)
                return i3d.apply({"params": p_i3d}, flow_chain(flow))

            fns["flow"] = flow_fn
        elif "flow" in self.streams and self.flow_type == "flow":

            @jax.jit
            def flow_fn(p_i3d, flow_imgs):  # (B, S, H', W', 2) as floats
                return i3d.apply({"params": p_i3d}, disk_flow_chain(flow_imgs))

            fns["flow"] = flow_fn

        state["fns"][key] = fns
        return fns

    @staticmethod
    def _raft_and_pad(shape, dtype=jnp.float32):
        from video_features_tpu.models.raft.extract_raft import InputPadder
        from video_features_tpu.models.raft.model import build as raft_build

        return raft_build(dtype=dtype), InputPadder(shape)._pad

    # --- decode ------------------------------------------------------------
    def _sampled_count(self, meta) -> int:
        """How many frames the I3D grid will sample — the prefetch guard's
        resident-cost estimate (NOT the container frame count: a long
        video at low --extraction_fps samples few frames)."""
        fps = meta.fps or 25.0
        if self.config.extraction_fps is not None:
            return max(int(meta.frame_count / fps * self.config.extraction_fps), 1)
        if meta.frame_count < DEFAULT_STACK_SIZE + 1:
            return DEFAULT_STACK_SIZE + 1
        return meta.frame_count

    def _sample_frames(self, video_path: str, meta=None):
        """The reference's I3D-specific sampling grid
        (ref extract_i3d.py:239-259): fps-linspace / short-video
        upsample-to-65 / all frames. Returns (frames, fps, timestamps_ms)."""
        meta = meta or probe(video_path, self.config.decoder)
        fps = meta.fps or 25.0
        frame_cnt = meta.frame_count
        mspf = 1000.0 / fps
        samples_num = self._sampled_count(meta)
        if self.config.extraction_fps is None and frame_cnt >= DEFAULT_STACK_SIZE + 1:
            samples_ix = np.arange(frame_cnt)
        else:
            samples_ix = np.linspace(1, max(frame_cnt - 1, 1), samples_num).astype(int)

        # allow_seek=False: same reasoning as the fix/uni samplers
        # (io/video.py extract_frames) — CAP_PROP_POS_FRAMES seeks can
        # land off-by-frames on open-GOP/B-frame streams while passing the
        # position-readback guard, and the sampled-feature contract must
        # not ride on that. Sequential decode up to max(index) is exact.
        wanted = read_frames_at_indices(
            video_path, samples_ix, self.config.decoder, allow_seek=False
        )
        # undecodable sampled indices are dropped, exactly like the
        # reference's `if i is not None` filter (ref extract_i3d.py:245-257)
        frames = [wanted[i] for i in samples_ix if i in wanted]
        stamps = [i * mspf for i in samples_ix if i in wanted]
        return frames, fps, stamps

    def _load_flow_pairs(self, flow_dir: str):
        """Sorted, stem-verified flow_x_*/flow_y_* JPEG pairs
        (ref extract_i3d.py:231-237; hardened: numeric suffixes sort
        numerically and x/y suffixes must match pairwise, so one missing
        file fails loudly instead of silently desyncing every later pair)."""
        import pathlib

        def key(p):
            sfx = p.stem[7:]
            return (0, int(sfx)) if sfx.isdigit() else (1, sfx)

        xs = sorted(pathlib.Path(flow_dir).glob("flow_x*.jpg"), key=key)
        ys = sorted(pathlib.Path(flow_dir).glob("flow_y*.jpg"), key=key)
        if len(xs) != len(ys):
            raise ValueError(
                f"{flow_dir}: {len(xs)} flow_x vs {len(ys)} flow_y images"
            )
        for x, y in zip(xs, ys):
            if x.stem[7:] != y.stem[7:]:
                raise ValueError(f"flow pair mismatch: {x.name} vs {y.name}")
        return list(zip(xs, ys))

    # graftcheck: fp32-island — precomputed-flow ingest: grayscale JPEGs
    # already encode clamped TV-L1 flow, decoded float here for the
    # [-20, 20] un-mapping; this input mode never takes the uint8 wire
    def _read_flow_images(self, flow_dir: str, pairs=None) -> np.ndarray:
        """Decode every flow JPEG pair ONCE -> (N, H, W, 2) float32 (the
        windows may overlap when step < stack; re-decoding per window
        would repeat the disk reads). ``pairs`` reuses a prior
        ``_load_flow_pairs`` scan."""
        if pairs is None:
            pairs = self._load_flow_pairs(flow_dir)
        imgs = np.stack(
            [
                np.stack(
                    [
                        cv2.imread(str(fx), cv2.IMREAD_GRAYSCALE),
                        cv2.imread(str(fy), cv2.IMREAD_GRAYSCALE),
                    ],
                    axis=-1,
                )
                for fx, fy in pairs
            ]
        ).astype(np.float32) if pairs else np.zeros((0, 1, 1, 2), np.float32)
        if len(pairs) and min(imgs.shape[1:3]) < CENTRAL_CROP_SIZE:
            raise ValueError(
                f"flow images {imgs.shape[1:3]} are smaller than the "
                f"{CENTRAL_CROP_SIZE}px center crop"
            )
        return imgs

    # --- main --------------------------------------------------------------
    # split as prepare (host decode/resize, runs on --decode_workers
    # threads) + dispatch/fetch (extract/base.py device pipeline). Inside
    # dispatch, stack k's results fetch only after stack k+1 is enqueued
    # (lag-1): the fetch overlaps the next stack's RAFT/PWC+I3D compute,
    # and at most ~2 stacks' inputs are ever resident in HBM regardless
    # of video length (the fetch is the backpressure).
    # host-RAM guard: a prepared video is T x 256 x W x 3 float32, and the
    # pipeline keeps decode_workers+2 of them resident — so the guard is a
    # BYTE budget across all resident slots, divided down to a per-video
    # frame cap (advisor r02: a flat 4096-frame cap let ~17 GB accumulate
    # at the default worker count). Over-cap videos move their decode into
    # the dispatch phase (one resident at a time — the serial memory
    # profile), same pattern as ResNet's streaming fallback.
    PIPELINE_MAX_BYTES = 4 << 30
    # bytes one resized frame costs — the budget unit the cap counts in
    # (min-side 256, ~4:3; disk-flow images are converted to this unit
    # because they prefetch at ORIGINAL resolution)
    _FRAME_BYTES = 256 * 342 * 3 * 4

    @property
    def PIPELINE_MAX_FRAMES(self) -> int:
        """Per-video prefetch cap in resized-frame units (floor: one
        65-frame stack, the smallest unit prepare can hand over)."""
        return self._prefetch_frame_cap(
            self.PIPELINE_MAX_BYTES, self._FRAME_BYTES, floor=65
        )

    def _flow_prefetch_cost(self, pairs) -> int:
        """Disk-flow resident cost in resized-frame equivalents: flow
        JPEGs stay full-resolution until the device transform, so a 1080p
        flow dir can dwarf the frames the cap was sized for. ``pairs`` is
        the caller's already-scanned ``_load_flow_pairs`` result; PIL
        reads only the first image's header for the size."""
        if not pairs:
            return 0
        from PIL import Image

        try:
            with Image.open(pairs[0][0]) as im:
                w, h = im.size
        except OSError:  # unreadable: let _read_flow_images raise later
            return 0
        return len(pairs) * (h * w * 2 * 4) // self._FRAME_BYTES

    # graftcheck: fp32-island — host PIL-parity decode (--preprocess host):
    # pil_resize wants float pixels; the production path is _decode_raw,
    # which ships uint8 and resizes on device (4x fewer wire bytes)
    def _decode_resized(self, video_path, meta=None):
        frames, fps, timestamps_ms = self._sample_frames(video_path, meta)
        if not frames:
            raise IOError(f"no frames decoded from {video_path}")
        frames = [
            pil_resize(f, MIN_SIDE_SIZE).astype(np.float32) for f in frames
        ]
        return frames, fps, timestamps_ms

    def _decode_raw(self, video_path, meta=None):
        """--preprocess device: the min-edge-256 resize moves on-chip
        (``_device_geometry`` taps), so prepare hands over RAW uint8
        frames — a quarter of the float32 bytes per pixel the
        host-resized path prefetches and ships over PCIe."""
        frames, fps, timestamps_ms = self._sample_frames(video_path, meta)
        if not frames:
            raise IOError(f"no frames decoded from {video_path}")
        return frames, fps, timestamps_ms

    def prepare(self, path_entry):
        from_disk = self.flow_type == "flow"
        if from_disk and (
            not isinstance(path_entry, (tuple, list)) or len(path_entry) != 2
        ):
            raise ValueError(
                "--flow_type flow needs (video, flow_dir) pairs; provide "
                "--flow_paths / --flow_dir alongside the videos"
            )
        video_path = video_path_of(path_entry)
        meta = probe(video_path, self.config.decoder)
        cost = self._sampled_count(meta)
        device_pre = self._device_preprocess_enabled()
        if device_pre:
            # raw uint8 frames prefetch at SOURCE resolution — restate
            # the cap's resized-float32 frame unit in those bytes
            cost = max(
                cost * (meta.height * meta.width * 3) // self._FRAME_BYTES, 1
            )
        pairs = self._load_flow_pairs(path_entry[1]) if from_disk else None
        if from_disk:
            cost += self._flow_prefetch_cost(pairs)
        if cost > self.PIPELINE_MAX_FRAMES:
            # too big to prefetch whole: frames AND disk flow defer to the
            # dispatch phase (one over-cap video resident at a time)
            return None, None, from_disk, meta
        flow_imgs = (
            self._read_flow_images(path_entry[1], pairs) if from_disk else None
        )
        decode = self._decode_raw if device_pre else self._decode_resized
        return decode(video_path, meta), flow_imgs, from_disk, meta

    def dispatch_prepared(self, device, state, path_entry, payload):
        from jax.sharding import PartitionSpec as P

        from video_features_tpu.parallel.sharding import is_mesh, place_batch

        decoded, flow_imgs, from_disk, meta = payload
        device_pre = self._device_preprocess_enabled()
        if decoded is None:  # over the prefetch cap: load here, held once
            if from_disk:
                flow_imgs = self._read_flow_images(path_entry[1])
            decode = self._decode_raw if device_pre else self._decode_resized
            decoded = decode(video_path_of(path_entry), meta)
        frames, fps, timestamps_ms = decoded
        fns = self._fns_for_shape(state, frames[0].shape[:2])
        geom = (
            _device_geometry(
                *frames[0].shape[:2], self.config.spatial_bucket, self.flow_type
            )
            if device_pre
            else None
        )

        feats: Dict[str, List[np.ndarray]] = {s: [] for s in self.streams}
        preds: List[tuple] = []  # (stack_idx, stream, logits) if show_pred
        window = self.stack_size + (0 if from_disk else 1)
        # with disk flow the reference zips frames with flow pairs, so the
        # windowed extent truncates to the shorter (ref extract_i3d.py:266)
        extent = min(len(frames), len(flow_imgs)) if from_disk else len(frames)
        mesh = is_mesh(state["device"])
        group = 1 if mesh else self.stack_batch
        slices = form_slices(extent, window, self.step_size)
        pending = None
        for g0 in range(0, len(slices), group):
            chunk = slices[g0 : g0 + group]
            n_valid = len(chunk)
            if mesh:  # per-stack, frame axis shards (sequence parallel)
                start, end = chunk[0]
                stack = np.stack(frames[start:end])
                if device_pre:
                    # raw uint8 onto the input bucket — the fused mesh
                    # fns' taps target the padded (bh, bw) grid
                    from video_features_tpu.ops.window import pad_hw

                    stack = pad_hw(stack, *geom["bucket"])
                x = place_batch(stack, state["device"], spec=P())
                fl = (
                    place_batch(flow_imgs[start:end], state["device"], spec=P())
                    if from_disk
                    else None
                )
            else:  # stack-batched: the last group zero-pads to the full
                # shape (ops/window.py pad_batch, the shared static-shape
                # idiom); surplus outputs are sliced off at fetch
                from video_features_tpu.ops.window import pad_batch, pad_hw

                stacked = pad_batch(
                    np.stack([np.stack(frames[s:e]) for s, e in chunk]), group
                )
                if device_pre:
                    # raw uint8 onto the input bucket; pad columns carry
                    # zero tap weight, so they never reach the models
                    stacked = pad_hw(stacked, *geom["bucket"])
                x = place_batch(stacked, state["device"])
                fl = (
                    place_batch(
                        pad_batch(
                            np.stack([flow_imgs[s:e] for s, e in chunk]), group
                        ),
                        state["device"],
                    )
                    if from_disk
                    else None
                )
            outs = []
            for stream in self.streams:
                if stream == "rgb" and device_pre:
                    f, logits = fns["rgb"](
                        state["params"]["rgb"], x, geom["rgb_wy"], geom["rgb_wx"]
                    )
                elif stream == "rgb":
                    f, logits = fns["rgb"](state["params"]["rgb"], x)
                elif from_disk:
                    f, logits = fns["flow"](state["params"]["flow"], fl)
                elif device_pre:
                    f, logits = fns["flow"](
                        state["params"][self.flow_type],
                        state["params"]["flow"],
                        x,
                        geom["flow_wy"],
                        geom["flow_wx"],
                        *geom["crop"],
                    )
                else:
                    f, logits = fns["flow"](
                        state["params"][self.flow_type], state["params"]["flow"], x
                    )
                outs.append(
                    (stream, f, logits if self.config.show_pred else None)
                )
            if pending is not None:
                self._fetch_stack(pending, feats, preds)  # overlaps this group
            pending = (g0, n_valid, outs)
        return feats, preds, pending, video_path_of(path_entry), fps, timestamps_ms

    def _fetch_stack(self, pending, feats, preds) -> None:
        base_idx, n_valid, outs = pending
        for stream, f, logits in outs:
            feats[stream].append(np.asarray(f)[:n_valid])
            if logits is not None:
                arr = np.asarray(logits)[:n_valid]
                for j in range(n_valid):
                    preds.append((base_idx + j, stream, arr[j]))

    # --- cross-video aggregation (--video_batch) ---------------------------
    # A corpus of short clips (one 65-frame stack each) dispatches one
    # stack per video on the deepest pipeline in the framework — RAFT x 64
    # pairs + two I3D towers (VERDICT r03 weak #4). Same-resolution stacks
    # are shape-identical, so cross-video stacks FILL the --batch_size
    # stack groups (the same compiled executable as within-video
    # batching) instead of zero-padding them. Mesh runs keep the solo path
    # (there the stack's frame axis shards — sequence parallelism).

    AGG_MAX_FRAMES = 256

    def agg_key(self, payload):
        decoded, _, from_disk, _ = payload
        if (
            decoded is None  # over the prefetch cap: one resident at a time
            or from_disk  # zipped frame+flow-image payloads don't fuse
            or self.config.show_pred  # per-video print interleaving
            or self.config.sharding == "mesh"
        ):
            return None
        frames = decoded[0]
        if len(frames) > self.AGG_MAX_FRAMES:
            return None
        # a video too short for even one stack_size+1 window yields zero
        # slices — nothing to fuse; decline so the solo path handles it
        # (mirrors flow_extract's empty-windows check; advisor r4: an
        # all-short group used to IndexError in dispatch_group and ride
        # the spurious solo_fallback traceback to the right answer)
        if len(frames) < self.stack_size + 1:
            return None
        key = (
            frames[0].shape[:2],
            self.stack_size,
            self.step_size,
            tuple(self.streams),
            self.flow_type,
        )
        if self._device_preprocess_enabled():
            # frames are RAW here, so shape[:2] is the source resolution:
            # same (h, w) -> the same _device_geometry taps serve the
            # whole group on one padded-bucket executable
            key = key + ("dev",)
        return key

    def dispatch_group(self, device, state, entries, payloads):
        from video_features_tpu.ops.window import pad_batch, pad_hw
        from video_features_tpu.parallel.sharding import place_batch

        group = self.stack_batch
        window = self.stack_size + 1
        device_pre = self._device_preprocess_enabled()
        stacks: List[np.ndarray] = []
        counts: List[int] = []
        metas = []
        for decoded, _, _, _ in payloads:
            frames, fps, timestamps_ms = decoded
            slices = form_slices(len(frames), window, self.step_size)
            stacks.extend(np.stack(frames[s:e]) for s, e in slices)
            counts.append(len(slices))
            metas.append((fps, timestamps_ms))
        fns = self._fns_for_shape(state, stacks[0].shape[1:3])
        geom = (
            _device_geometry(
                *stacks[0].shape[1:3], self.config.spatial_bucket, self.flow_type
            )
            if device_pre
            else None
        )
        outs = []
        for i in range(0, len(stacks), group):
            chunk = stacks[i : i + group]
            n_valid = len(chunk)
            stacked = pad_batch(np.stack(chunk), group)
            if device_pre:
                stacked = pad_hw(stacked, *geom["bucket"])
            x = place_batch(stacked, state["device"])
            souts = []
            for stream in self.streams:
                if stream == "rgb" and device_pre:
                    f, _ = fns["rgb"](
                        state["params"]["rgb"], x, geom["rgb_wy"], geom["rgb_wx"]
                    )
                elif stream == "rgb":
                    f, _ = fns["rgb"](state["params"]["rgb"], x)
                elif device_pre:
                    f, _ = fns["flow"](
                        state["params"][self.flow_type],
                        state["params"]["flow"],
                        x,
                        geom["flow_wy"],
                        geom["flow_wx"],
                        *geom["crop"],
                    )
                else:
                    f, _ = fns["flow"](
                        state["params"][self.flow_type],
                        state["params"]["flow"],
                        x,
                    )
                souts.append((stream, f))
            outs.append((n_valid, souts))
        return outs, counts, metas

    def fetch_group(self, handle):
        outs, counts, metas = handle
        per_stream: Dict[str, List[np.ndarray]] = {s: [] for s in self.streams}
        for n_valid, souts in outs:
            for stream, f in souts:
                per_stream[stream].append(np.asarray(f)[:n_valid])
        cat = {
            s: (
                np.concatenate(v, axis=0).astype(np.float32)
                if v
                else np.zeros((0, 1024), np.float32)
            )
            for s, v in per_stream.items()
        }
        dicts, off = [], 0
        for count, (fps, timestamps_ms) in zip(counts, metas):
            d: Dict[str, np.ndarray] = {
                s: cat[s][off : off + count] for s in self.streams
            }
            d["fps"] = np.array(fps)
            d["timestamps_ms"] = np.array(timestamps_ms)
            dicts.append(d)
            off += count
        return dicts

    def fetch_dispatched(self, handle) -> Dict[str, np.ndarray]:
        feats, preds, pending, video_path, fps, timestamps_ms = handle
        if pending is not None:
            self._fetch_stack(pending, feats, preds)
        for stack_idx, stream, logits in preds:
            print(f"{video_path} @ stack {stack_idx} ({stream} stream)")
            show_predictions_on_dataset(logits, "kinetics")
        out: Dict[str, np.ndarray] = {
            s: (
                np.concatenate(feats[s], axis=0).astype(np.float32)
                if feats[s]
                else np.zeros((0, 1024), np.float32)
            )
            for s in self.streams
        }
        out["fps"] = np.array(fps)
        out["timestamps_ms"] = np.array(timestamps_ms)
        return out
