"""I3D checkpoint (i3d_rgb.pt / i3d_flow.pt) -> Flax param tree.

torch naming (ref i3d_src/i3d_net.py): ``conv3d_*.conv3d.weight`` +
``conv3d_*.batch3d.*``, ``mixed_*.branch_0.*``, ``mixed_*.branch_{1,2}.
{0,1}.*`` (Sequential), ``mixed_*.branch_3.1.*`` (index 0 is the pool),
``conv3d_0c_1x1.conv3d.{weight,bias}``.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from video_features_tpu.models.common.weights import (
    bn_params,
    check_all_consumed,
    conv3d_kernel,
    strip_prefix,
)

_MIXED = (
    "mixed_3b", "mixed_3c",
    "mixed_4b", "mixed_4c", "mixed_4d", "mixed_4e", "mixed_4f",
    "mixed_5b", "mixed_5c",
)
_STEM = ("conv3d_1a_7x7", "conv3d_2b_1x1", "conv3d_2c_3x3")
# flax branch name -> torch branch prefix
_BRANCHES = {
    "branch_0": "branch_0",
    "branch_1_0": "branch_1.0",
    "branch_1_1": "branch_1.1",
    "branch_2_0": "branch_2.0",
    "branch_2_1": "branch_2.1",
    "branch_3_1": "branch_3.1",
}


def _unit(sd: Dict[str, np.ndarray], prefix: str, consumed, bias: bool = False):
    consumed.add(f"{prefix}.conv3d.weight")
    conv = {"kernel": conv3d_kernel(sd[f"{prefix}.conv3d.weight"])}
    if bias:
        consumed.add(f"{prefix}.conv3d.bias")
        conv["bias"] = sd[f"{prefix}.conv3d.bias"]
    unit = {"conv3d": conv}
    if f"{prefix}.batch3d.weight" in sd:
        unit["batch3d"] = bn_params(sd, f"{prefix}.batch3d", consumed)
    return unit


def convert_state_dict(sd: Dict[str, np.ndarray]):
    sd = strip_prefix(sd, "module.")
    consumed = set()
    params = {name: _unit(sd, name, consumed) for name in _STEM}
    for mixed in _MIXED:
        for flax_name, torch_name in _BRANCHES.items():
            params.setdefault(mixed, {})[flax_name] = _unit(
                sd, f"{mixed}.{torch_name}", consumed
            )
    params["conv3d_0c_1x1"] = _unit(sd, "conv3d_0c_1x1", consumed, bias=True)
    check_all_consumed(sd, consumed, "I3D")
    return params
