"""I3D (Inception-3D) in Flax (inference graph).

Reference: models/i3d/i3d_src/i3d_net.py — the Kinetics-400 two-stream
I3D with TF-style SAME padding. The padding is the subtle part
(SURVEY.md §7 hard part #4): every conv/pool pads asymmetrically with
``pad_along = max(kernel - stride, 0)``, low side ``pad_along // 2``
(ref i3d_net.py:8-25), which differs from both torch's symmetric padding
and XLA's input-size-aware 'SAME'. Max pools zero-pad explicitly and run
ceil-mode (ref i3d_net.py:108-120) — after ReLU everything is >= 0, so
reduce_window's -inf fill with an extra (stride-1) high-side pad
reproduces both the zero fill and the ceil semantics.

NDHWC layout end-to-end; inference BatchNorm folded to multiply-add;
forward returns (features (B, 1024), logits (B, num_classes)) in one
pass — the pre-logit time-averaged features of ``features=True`` plus
the classifier head used by ``--show_pred`` (ref i3d_net.py:238-274).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from video_features_tpu.models.common.layers import Conv3DCompat, EvalBatchNorm

I3D_FEATURE_DIM = 1024
I3D_NUM_CLASSES = 400


def tf_same_pads(kernel: Sequence[int], stride: Sequence[int]):
    """(lo, hi) per spatial dim: ``pad_along = max(k - s, 0)`` split with
    the smaller half first (ref i3d_net.py:8-25)."""
    pads = []
    for k, s in zip(kernel, stride):
        along = max(k - s, 0)
        pads.append((along // 2, along - along // 2))
    return pads


class Unit3D(nn.Module):
    """Conv3d + BN + ReLU with TF SAME padding (ref i3d_net.py:37-105)."""

    features: int
    kernel: Tuple[int, int, int] = (1, 1, 1)
    stride: Tuple[int, int, int] = (1, 1, 1)
    use_bn: bool = True
    use_bias: bool = False
    activation: bool = True
    dtype: jnp.dtype = jnp.float32
    conv_impl: str | None = None  # None = VFT_CONV3D_IMPL env, else direct

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # Conv3DCompat: param-tree-identical to nn.Conv, but the lowering
        # is selectable (--conv3d_impl / VFT_CONV3D_IMPL) — the direct
        # XLA 3D conv crashed the TPU compile helper three rounds running
        # (BASELINE.md round-4 chip log), so a decomposed sum-of-2D-convs
        # escape hatch is load-bearing for the north-star config
        x = Conv3DCompat(
            self.features,
            self.kernel,
            self.stride,
            tf_same_pads(self.kernel, self.stride),
            use_bias=self.use_bias,
            dtype=self.dtype,
            impl=self.conv_impl,
            name="conv3d",
        )(x)
        if self.use_bn:
            x = EvalBatchNorm(name="batch3d")(x)
        if self.activation:
            x = nn.relu(x)
        return x


def max_pool_tf(x: jnp.ndarray, kernel, stride) -> jnp.ndarray:
    """TF-SAME zero-padded, ceil-mode 3D max pool (ref i3d_net.py:108-120).

    reduce_window fills with -inf; valid since inputs are post-ReLU, and
    the extra (stride-1) high-side pad turns floor sizing into ceil."""
    pads = [
        (lo, hi + s - 1)
        for (lo, hi), s in zip(tf_same_pads(kernel, stride), stride)
    ]
    return nn.max_pool(
        x, tuple(kernel), strides=tuple(stride), padding=pads
    )


class Mixed(nn.Module):
    """Inception block: 1x1 / 1x1->3x3 / 1x1->3x3 / pool->1x1 branches
    (ref i3d_net.py:123-157)."""

    out: Sequence[int]
    dtype: jnp.dtype = jnp.float32
    conv_impl: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        o = self.out
        u = lambda *a, **kw: Unit3D(
            *a, dtype=self.dtype, conv_impl=self.conv_impl, **kw
        )
        b0 = u(o[0], name="branch_0")(x)
        b1 = u(o[2], (3, 3, 3), name="branch_1_1")(u(o[1], name="branch_1_0")(x))
        b2 = u(o[4], (3, 3, 3), name="branch_2_1")(u(o[3], name="branch_2_0")(x))
        b3 = u(o[5], name="branch_3_1")(max_pool_tf(x, (3, 3, 3), (1, 1, 1)))
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class I3D(nn.Module):
    """(B, T, H, W, C) in [-1, 1] (C=3 rgb / 2 flow) ->
    (features (B, 1024), logits (B, num_classes))."""

    num_classes: int = I3D_NUM_CLASSES
    dtype: jnp.dtype = jnp.float32
    conv_impl: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        ci = self.conv_impl
        u = lambda *a, **kw: Unit3D(*a, dtype=self.dtype, conv_impl=ci, **kw)
        m = lambda out, name: Mixed(out, self.dtype, ci, name=name)
        x = x.astype(self.dtype)
        x = u(64, (7, 7, 7), (2, 2, 2), name="conv3d_1a_7x7")(x)
        x = max_pool_tf(x, (1, 3, 3), (1, 2, 2))
        x = u(64, name="conv3d_2b_1x1")(x)
        x = u(192, (3, 3, 3), name="conv3d_2c_3x3")(x)
        x = max_pool_tf(x, (1, 3, 3), (1, 2, 2))
        x = m([64, 96, 128, 16, 32, 32], "mixed_3b")(x)
        x = m([128, 128, 192, 32, 96, 64], "mixed_3c")(x)
        x = max_pool_tf(x, (3, 3, 3), (2, 2, 2))
        x = m([192, 96, 208, 16, 48, 64], "mixed_4b")(x)
        x = m([160, 112, 224, 24, 64, 64], "mixed_4c")(x)
        x = m([128, 128, 256, 24, 64, 64], "mixed_4d")(x)
        x = m([112, 144, 288, 32, 64, 64], "mixed_4e")(x)
        x = m([256, 160, 320, 32, 128, 128], "mixed_4f")(x)
        x = max_pool_tf(x, (2, 2, 2), (2, 2, 2))
        x = m([256, 160, 320, 32, 128, 128], "mixed_5b")(x)
        x = m([384, 192, 384, 48, 128, 128], "mixed_5c")(x)

        # AvgPool3d((2, 7, 7), stride 1), VALID (ref i3d_net.py:227);
        # fp32 pooling + heads: features are the user-facing contract
        x = nn.avg_pool(x.astype(jnp.float32), (2, 7, 7), strides=(1, 1, 1))
        feats = jnp.mean(x, axis=(1, 2, 3))  # time-avg -> (B, 1024)

        logits = Unit3D(
            self.num_classes,
            use_bn=False,
            use_bias=True,
            activation=False,
            conv_impl=ci,
            name="conv3d_0c_1x1",
        )(x)
        logits = jnp.mean(logits, axis=(1, 2, 3))  # (B, num_classes)
        return feats, logits


def build(
    num_classes: int = I3D_NUM_CLASSES, dtype=jnp.float32,
    conv_impl: str | None = None,
) -> I3D:
    return I3D(num_classes=num_classes, dtype=dtype, conv_impl=conv_impl)


def init_params(modality: str, seed: int = 0, num_classes: int = I3D_NUM_CLASSES):
    model = build(num_classes)
    in_ch = {"rgb": 3, "flow": 2}[modality]
    dummy = jnp.zeros((1, 10, 224, 224, in_ch), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)["params"]
