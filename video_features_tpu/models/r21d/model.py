"""R(2+1)D-18 in Flax (inference graph).

The reference uses torchvision's ``r2plus1d_18`` pretrained on
Kinetics-400 (ref models/r21d/extract_r21d.py:9,58-62). The graph is
rebuilt TPU-first: NTHWC layout end-to-end (channels-last 3D convs tile
straight onto the MXU), inference BatchNorm folded to one multiply-add,
and forward returning ``(features, logits)`` in a single pass so
``--show_pred`` costs one extra matmul.

Architecture (torchvision VideoResNet): R(2+1)D stem — 1x7x7/1,2,2
spatial conv to 45 ch + BN + ReLU, then 3x1x1 temporal conv to 64 +
BN + ReLU — followed by four stages of 2 BasicBlocks whose 3D convs are
factorized into spatial (1x3x3) + BN + ReLU + temporal (3x1x1) pairs
with the midplane count chosen to match the parameter budget of the full
3x3x3 conv; global average pool; 400-way fc.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from video_features_tpu.models.common.layers import Conv3DCompat, EvalBatchNorm

R21D_FEATURE_DIM = 512


def midplanes(in_ch: int, out_ch: int) -> int:
    """Parameter-matching width of the factorized conv's intermediate
    (torchvision Conv2Plus1D): ``(in*out*3^3) // (in*3^2 + 3*out)``."""
    return (in_ch * out_ch * 3 * 3 * 3) // (in_ch * 3 * 3 + 3 * out_ch)


class Conv2Plus1D(nn.Module):
    """Factorized 3D conv: spatial 1x3x3 -> BN -> ReLU -> temporal 3x1x1."""

    mid: int
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    conv_impl: str | None = None  # Conv3DCompat lowering (VFT_CONV3D_IMPL)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = Conv3DCompat(
            self.mid,
            (1, 3, 3),
            (1, self.stride, self.stride),
            [(0, 0), (1, 1), (1, 1)],
            dtype=self.dtype,
            impl=self.conv_impl,
            name="spatial",
        )(x)
        x = nn.relu(EvalBatchNorm(name="bn_mid")(x))
        x = Conv3DCompat(
            self.features,
            (3, 1, 1),
            (self.stride, 1, 1),
            [(1, 1), (0, 0), (0, 0)],
            dtype=self.dtype,
            impl=self.conv_impl,
            name="temporal",
        )(x)
        return x


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    downsample: bool = False
    dtype: jnp.dtype = jnp.float32
    conv_impl: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        in_ch = x.shape[-1]
        # torchvision computes the midplane width once from (inplanes, planes)
        # and reuses it for BOTH factorized convs of the block
        mid = midplanes(in_ch, self.planes)
        identity = x
        out = Conv2Plus1D(mid, self.planes, self.stride, self.dtype,
                          self.conv_impl, name="conv1")(x)
        out = nn.relu(EvalBatchNorm(name="bn1")(out))
        out = Conv2Plus1D(mid, self.planes, 1, self.dtype,
                          self.conv_impl, name="conv2")(out)
        out = EvalBatchNorm(name="bn2")(out)
        if self.downsample:
            identity = Conv3DCompat(
                self.planes,
                (1, 1, 1),
                (self.stride,) * 3,
                [(0, 0)] * 3,
                dtype=self.dtype,
                impl=self.conv_impl,
                name="downsample_conv",
            )(x)
            identity = EvalBatchNorm(name="downsample_bn")(identity)
        return nn.relu(out + identity)


class R2Plus1D(nn.Module):
    """(N, T, H, W, 3) normalized fp32 -> (features (N, 512), logits (N, classes))."""

    layers: Sequence[int] = (2, 2, 2, 2)
    num_classes: int = 400
    dtype: jnp.dtype = jnp.float32
    conv_impl: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = Conv3DCompat(
            45,
            (1, 7, 7),
            (1, 2, 2),
            [(0, 0), (3, 3), (3, 3)],
            dtype=self.dtype,
            impl=self.conv_impl,
            name="stem_conv1",
        )(x)
        x = nn.relu(EvalBatchNorm(name="stem_bn1")(x))
        x = Conv3DCompat(
            64,
            (3, 1, 1),
            (1, 1, 1),
            [(1, 1), (0, 0), (0, 0)],
            dtype=self.dtype,
            impl=self.conv_impl,
            name="stem_conv2",
        )(x)
        x = nn.relu(EvalBatchNorm(name="stem_bn2")(x))

        in_planes = 64
        for stage, n_blocks in enumerate(self.layers):
            planes = 64 * (2 ** stage)
            stride = 1 if stage == 0 else 2
            for b in range(n_blocks):
                s = stride if b == 0 else 1
                need_ds = s != 1 or in_planes != planes
                x = BasicBlock(planes, s, need_ds, self.dtype, self.conv_impl,
                               name=f"layer{stage + 1}_{b}")(x)
                in_planes = planes

        # fp32 pool + head: features are the user-facing contract
        feats = jnp.mean(x.astype(jnp.float32), axis=(1, 2, 3))
        logits = nn.Dense(self.num_classes, name="fc")(feats)
        return feats, logits


def build(
    num_classes: int = 400, dtype=jnp.float32, conv_impl: str | None = None
) -> R2Plus1D:
    return R2Plus1D(num_classes=num_classes, dtype=dtype, conv_impl=conv_impl)


def init_params(seed: int = 0, num_classes: int = 400):
    model = build(num_classes)
    dummy = jnp.zeros((1, 4, 112, 112, 3), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)["params"]
