"""R(2+1)D clip-level feature extractor (ref models/r21d/extract_r21d.py).

Per video: whole-clip decode (optionally on an ``--extraction_fps`` grid —
done in-process, no ffmpeg re-encode subprocess), then ``form_slices``
windowing (stack/step default 16/16, ref extract_r21d.py:19-20,108) over
the raw uint8 frames, then batches of ``--batch_size`` stacks through ONE
jitted function that fuses the reference's tensor-space transform chain —
/255, bilinear resize to (128, 171) half-pixel convention, Kinetics
normalize, center crop 112 (ref extract_r21d.py:15-21,37-42) — with the
model forward. Windows cross host->device as uint8 (4x less PCIe/DMA
traffic than fp32) and there is exactly one compiled executable per
(video resolution, batch) shape; the tail batch is zero-padded.

The reference loops one fp32 stack at a time through the model
(ref extract_r21d.py:110-121); batching stacks is free here because the
weights are frozen.

Output contract: ``{r21d_rgb: (S, 512)}`` — the reference omits
fps/timestamps for this extractor (ref extract_r21d.py:118-121).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import jax.numpy as jnp

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import form_slices, video_path_of
from video_features_tpu.io.video import read_all_frames_with_meta, require_window
from video_features_tpu.models.common.weights import load_params, random_init_fallback
from video_features_tpu.models.r21d.convert import convert_state_dict
from video_features_tpu.models.r21d.model import R21D_FEATURE_DIM, build, init_params
from video_features_tpu.ops.preprocess import KINETICS_MEAN, KINETICS_STD
from video_features_tpu.ops.resize import resize_bilinear
from video_features_tpu.ops.window import pad_batch
from video_features_tpu.utils.labels import show_predictions_on_dataset

PRE_CENTRAL_CROP_SIZE = (128, 171)
CENTRAL_CROP_SIZE = 112
DEFAULT_STACK_SIZE = 16
DEFAULT_STEP_SIZE = 16


def kinetics_preprocess(frames: jnp.ndarray) -> jnp.ndarray:
    """(..., H, W, 3) uint8 -> (..., 112, 112, 3) fp32, matching the
    reference chain ToFloatTensorInZeroOne -> Resize(128,171) ->
    Normalize -> CenterCrop(112) (ref r21d/transforms/rgb_transforms.py:
    47-108). Jit-friendly: runs on-device, fused into the model forward."""
    x = jnp.asarray(frames, jnp.float32) / 255.0
    x = jnp.moveaxis(x, -1, -3)  # (..., C, H, W) for the trailing-axes resize
    x = resize_bilinear(x, PRE_CENTRAL_CROP_SIZE, align_corners=False)
    shape = (3, 1, 1)
    mean = jnp.asarray(KINETICS_MEAN, jnp.float32).reshape(shape)
    std = jnp.asarray(KINETICS_STD, jnp.float32).reshape(shape)
    x = (x - mean) / std
    h, w = PRE_CENTRAL_CROP_SIZE
    top = int(round((h - CENTRAL_CROP_SIZE) / 2.0))
    left = int(round((w - CENTRAL_CROP_SIZE) / 2.0))
    x = x[..., top : top + CENTRAL_CROP_SIZE, left : left + CENTRAL_CROP_SIZE]
    return jnp.moveaxis(x, -3, -1)  # back to channels-last


class ExtractR21D(BaseExtractor):
    # --sharding mesh: pure data parallelism — conv weights replicate,
    # the window-batch axis shards over 'data' (parallel/sharding.py)
    mesh_capable = True

    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.stack_size = int(self.config.stack_size or DEFAULT_STACK_SIZE)
        self.step_size = int(self.config.step_size or DEFAULT_STEP_SIZE)
        # stacks per device call; the reference's --batch_size batches
        # frames for 2D nets, here it batches windows
        self.batch_size = max(int(self.config.batch_size or 1), 1)
        # --conv3d_impl threads into this extractor's model only (shared
        # contract with i3d — common/layers.py::explicit_conv3d_impl)
        from video_features_tpu.models.common.layers import explicit_conv3d_impl

        self.conv_impl = explicit_conv3d_impl(self.config)
        self._host_params = None

    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path, convert_state_dict
                )
            else:
                random_init_fallback(
                    self.config, self.feature_type,
                    "a torchvision r2plus1d_18 (Kinetics-400) state dict "
                    "(.pt/.pth) or a converted flax .msgpack",
                )
                self._host_params = init_params()
        return self._host_params

    def _build(self, device):
        from video_features_tpu.models.common.weights import (
            cast_floats_for_compute,
            compute_dtype,
        )

        from video_features_tpu.parallel.sharding import (
            jit_sharded_forward,
            place_params,
        )

        dt = compute_dtype(self.config)
        model = build(dtype=dt, conv_impl=self.conv_impl)
        params = self._load_host_params()
        if dt != jnp.float32:
            params = cast_floats_for_compute(params, dt, exclude=("fc",))
        params = place_params(params, device)  # mesh: replicated (DP)

        def forward(p, stacks_uint8):  # (B, stack, H, W, 3) uint8
            return model.apply({"params": p}, kinetics_preprocess(stacks_uint8))

        forward = jit_sharded_forward(forward, device, n_out=2)
        return {"params": params, "forward": forward, "device": device}

    # host half: whole-clip decode + uint8 window batching (runs on
    # --decode_workers threads under the async pipeline; frames cross to
    # the device half as uint8, so prefetching holds 4x less memory than
    # it would after float conversion)
    def prepare(self, path_entry):
        video_path = video_path_of(path_entry)
        frames, _, _, declared = read_all_frames_with_meta(
            video_path, self.config.extraction_fps, self.config.decoder
        )
        # salvage contract: a truncated prefix proceeds (with its
        # partial_decode warning) as long as anything decoded; zero
        # frames is a permanent input failure with counts in the message
        require_window(frames, 1, video_path, declared=declared)
        clip = np.stack(frames)  # (T, H, W, 3) uint8, stays on host
        slices = form_slices(clip.shape[0], self.stack_size, self.step_size)
        batches = []
        for i in range(0, len(slices), self.batch_size):
            chunk = slices[i : i + self.batch_size]
            stacks = np.stack([clip[s:e] for s, e in chunk])
            batches.append((pad_batch(stacks, self.batch_size), stacks.shape[0]))
        return batches, slices

    # device half, split for the device pipeline (extract/base.py): every
    # window batch's transfer + fused preprocess/forward is dispatched
    # (async under XLA), results stay on device until fetch — the next
    # video's dispatches overlap this video's fetch
    # graftcheck: fp32-island — the documented --uint8_transfer=off escape
    # hatch: it exists to trade the 4x wire bytes for a slow-uint8-DMA
    # transport, so the host cast here is the feature, not a leak
    def _maybe_widen(self, frames: np.ndarray) -> np.ndarray:
        """--uint8_transfer off: pre-cast windows to fp32 host-side — the
        escape hatch for transports with a slow uint8 DMA path
        (config.py). kinetics_preprocess starts with an fp32 cast, so
        numerics are identical either way."""
        if self.config.uint8_transfer == "off":
            return frames.astype(np.float32)
        return frames

    def dispatch_prepared(self, device, state, path_entry, payload):
        batches, slices = payload
        if not slices:
            return path_entry, [], slices
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        outs = []
        for padded, n in batches:
            padded = pad_batch_for(state["device"], self._maybe_widen(padded))
            x = place_batch(padded, state["device"])
            feats, logits = state["forward"](state["params"], x)
            # drop logits unless show_pred needs them — the handle pins
            # its buffers until fetch
            outs.append((feats, logits if self.config.show_pred else None, n))
        return path_entry, outs, slices

    # --- cross-video aggregation (--video_batch): valid uint8 window
    # stacks of N same-resolution videos re-chunk into (N*batch_size)-stack
    # fused preprocess+forward calls. A typical short video yields 1-4
    # 16-frame stacks — alone they idle the MXU; fused they fill it. The
    # agg_key carries (H, W): only same-resolution videos share a compiled
    # shape. Oversized videos and show_pred keep the individual path.
    # The cap is BYTES, not stack count: R21D stacks stay at ORIGINAL
    # resolution until the on-device resize, so a stack count that is
    # harmless at 240p is gigabytes at 1080p — and up to N-1 payloads per
    # key park host-side while a group fills (code-review r03).
    AGG_MAX_BYTES = 256 << 20

    def agg_key(self, payload):
        if self.config.show_pred:
            return None
        batches, slices = payload
        if not slices:
            return None
        shape = batches[0][0].shape  # (batch_size, stack, H, W, 3)
        # budget in TRANSFER bytes: --uint8_transfer off widens rows to
        # fp32 before the fused dispatch, 4x the uint8 element count
        elem = 4 if self.config.uint8_transfer == "off" else 1
        if len(slices) * int(np.prod(shape[1:])) * elem > self.AGG_MAX_BYTES:
            return None
        return shape

    def dispatch_group(self, device, state, entries, payloads):
        group = max(int(self.config.video_batch or 1), 1)
        stacks, totals = [], []  # rows = uint8 window stacks here
        for batches, slices in payloads:
            stacks.extend(self._maybe_widen(x[:n]) for x, n in batches)
            totals.append(len(slices))
        outs = self._dispatch_rows_grouped(state, stacks, self.batch_size * group)
        return outs, totals

    def fetch_group(self, handle):
        outs, totals = handle
        return [
            {self.feature_type: feats}
            for feats in self._split_grouped_rows(outs, totals)
        ]

    def fetch_dispatched(self, handle) -> Dict[str, np.ndarray]:
        path_entry, outs, slices = handle
        if not slices:
            return {self.feature_type: np.zeros((0, R21D_FEATURE_DIM), np.float32)}
        feats_out, logits_out = [], []
        for feats, logits, n in outs:
            feats_out.append(np.asarray(feats)[:n])
            if logits is not None:
                logits_out.append(np.asarray(logits)[:n])
        if self.config.show_pred:
            video_path = video_path_of(path_entry)
            logits_all = np.concatenate(logits_out, axis=0)
            for i, (start, end) in enumerate(slices):
                print(f"{video_path} @ frames ({start}, {end})")
                show_predictions_on_dataset(logits_all[i], "kinetics")
        return {self.feature_type: np.concatenate(feats_out, axis=0)}
