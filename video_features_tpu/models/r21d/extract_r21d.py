"""R(2+1)D clip-level feature extractor (ref models/r21d/extract_r21d.py).

Per video: whole-clip decode (optionally on an ``--extraction_fps`` grid —
done in-process, no ffmpeg re-encode subprocess), then the reference's
tensor-space transform chain — /255, bilinear resize to (128, 171)
half-pixel convention, Kinetics normalize, center crop 112 (ref
extract_r21d.py:15-21,37-42) — followed by ``form_slices`` windowing
(stack/step default 16/16, ref extract_r21d.py:19-20,108).

TPU-first departure from the reference's one-stack-at-a-time loop: all
stacks of a video run as ONE padded batch (weights are frozen, so stacks
are independent), bucketed to a small set of static shapes for XLA.

Output contract: ``{r21d_rgb: (S, 512)}`` — the reference omits
fps/timestamps for this extractor (ref extract_r21d.py:118-121).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import form_slices, video_path_of
from video_features_tpu.io.video import read_all_frames
from video_features_tpu.models.common.weights import load_params
from video_features_tpu.models.r21d.convert import convert_state_dict
from video_features_tpu.models.r21d.model import R21D_FEATURE_DIM, build, init_params
from video_features_tpu.ops.preprocess import KINETICS_MEAN, KINETICS_STD
from video_features_tpu.ops.resize import resize_bilinear
from video_features_tpu.ops.window import bucket_size, pad_batch
from video_features_tpu.utils.labels import show_predictions_on_dataset

PRE_CENTRAL_CROP_SIZE = (128, 171)
CENTRAL_CROP_SIZE = 112
DEFAULT_STACK_SIZE = 16
DEFAULT_STEP_SIZE = 16


def kinetics_preprocess(frames: np.ndarray) -> jnp.ndarray:
    """(T, H, W, 3) uint8 -> (T, 112, 112, 3) fp32, matching the reference
    chain ToFloatTensorInZeroOne -> Resize(128,171) -> Normalize ->
    CenterCrop(112) (ref r21d/transforms/rgb_transforms.py:47-108)."""
    x = jnp.asarray(frames, jnp.float32) / 255.0
    x = jnp.transpose(x, (0, 3, 1, 2))  # THWC -> TCHW for the (..., H, W) resize
    x = resize_bilinear(x, PRE_CENTRAL_CROP_SIZE, align_corners=False)
    mean = jnp.asarray(KINETICS_MEAN, jnp.float32).reshape(1, 3, 1, 1)
    std = jnp.asarray(KINETICS_STD, jnp.float32).reshape(1, 3, 1, 1)
    x = (x - mean) / std
    h, w = PRE_CENTRAL_CROP_SIZE
    top = int(round((h - CENTRAL_CROP_SIZE) / 2.0))
    left = int(round((w - CENTRAL_CROP_SIZE) / 2.0))
    x = x[:, :, top : top + CENTRAL_CROP_SIZE, left : left + CENTRAL_CROP_SIZE]
    return jnp.transpose(x, (0, 2, 3, 1))  # back to THWC


class ExtractR21D(BaseExtractor):
    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.stack_size = int(self.config.stack_size or DEFAULT_STACK_SIZE)
        self.step_size = int(self.config.step_size or DEFAULT_STEP_SIZE)
        self._host_params = None

    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path, convert_state_dict
                )
            else:
                self._host_params = init_params()
        return self._host_params

    def _build(self, device):
        model = build()
        params = jax.device_put(self._load_host_params(), device)

        @jax.jit
        def forward(p, x):
            return model.apply({"params": p}, x)

        return {"params": params, "forward": forward, "device": device}

    def extract(self, device, state, path_entry) -> Dict[str, np.ndarray]:
        video_path = video_path_of(path_entry)
        frames, _, _ = read_all_frames(video_path, self.config.extraction_fps)
        if not frames:
            raise IOError(f"no frames decoded from {video_path}")
        with jax.default_device(device):
            clip = np.asarray(kinetics_preprocess(np.stack(frames)))
        slices = form_slices(clip.shape[0], self.stack_size, self.step_size)
        if not slices:
            return {self.feature_type: np.zeros((0, R21D_FEATURE_DIM), np.float32)}

        stacks = np.stack([clip[s:e] for s, e in slices])  # (S, stack, 112, 112, 3)
        n = stacks.shape[0]
        padded = pad_batch(stacks, bucket_size(n, multiple=4))
        x = jax.device_put(jnp.asarray(padded), state["device"])
        feats, logits = state["forward"](state["params"], x)
        feats = np.asarray(feats)[:n]
        if self.config.show_pred:
            logits = np.asarray(logits)[:n]
            for i, (start, end) in enumerate(slices):
                print(f"{video_path} @ frames ({start}, {end})")
                show_predictions_on_dataset(logits[i], "kinetics")
        return {self.feature_type: feats}
