"""torchvision VideoResNet (r2plus1d_18) checkpoint -> Flax param tree.

Consumes the standard torchvision naming the reference loads via
``r2plus1d_18(pretrained=True)`` (ref models/r21d/extract_r21d.py:58-62):
``stem.{0,1,3,4}``, ``layer{s}.{b}.conv{k}.0.{0,1,3}`` (spatial conv /
mid BN / temporal conv inside Conv2Plus1D), ``layer{s}.{b}.conv{k}.1``
(post-factorization BN), ``layer{s}.{b}.downsample.{0,1}``, ``fc``.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from video_features_tpu.models.common.weights import (
    bn_params as _bn,
    check_all_consumed,
    conv3d_kernel,
    strip_prefix,
    transpose_linear,
)


def _conv(sd: Dict[str, np.ndarray], name: str, consumed) -> Dict[str, np.ndarray]:
    consumed.add(f"{name}.weight")
    return {"kernel": conv3d_kernel(sd[f"{name}.weight"])}


def _conv2plus1d(sd: Dict[str, np.ndarray], prefix: str, consumed):
    return {
        "spatial": _conv(sd, f"{prefix}.0", consumed),
        "bn_mid": _bn(sd, f"{prefix}.1", consumed),
        "temporal": _conv(sd, f"{prefix}.3", consumed),
    }


def convert_state_dict(sd: Dict[str, np.ndarray], layers=(2, 2, 2, 2)):
    sd = strip_prefix(sd, "module.")
    consumed = set()
    params = {
        "stem_conv1": _conv(sd, "stem.0", consumed),
        "stem_bn1": _bn(sd, "stem.1", consumed),
        "stem_conv2": _conv(sd, "stem.3", consumed),
        "stem_bn2": _bn(sd, "stem.4", consumed),
        "fc": {
            "kernel": transpose_linear(sd["fc.weight"]),
            "bias": sd["fc.bias"],
        },
    }
    consumed.update(("fc.weight", "fc.bias"))
    for stage, n_blocks in enumerate(layers):
        for b in range(n_blocks):
            ref = f"layer{stage + 1}.{b}"
            blk = {
                "conv1": _conv2plus1d(sd, f"{ref}.conv1.0", consumed),
                "bn1": _bn(sd, f"{ref}.conv1.1", consumed),
                "conv2": _conv2plus1d(sd, f"{ref}.conv2.0", consumed),
                "bn2": _bn(sd, f"{ref}.conv2.1", consumed),
            }
            if f"{ref}.downsample.0.weight" in sd:
                blk["downsample_conv"] = _conv(sd, f"{ref}.downsample.0", consumed)
                blk["downsample_bn"] = _bn(sd, f"{ref}.downsample.1", consumed)
            params[f"layer{stage + 1}_{b}"] = blk
    check_all_consumed(sd, consumed, "R2Plus1D")
    return params
