"""PWC-Net optical flow in Flax (inference graph).

Reference: models/pwc/pwc_src/pwc_net.py (sniklaus pytorch-pwc wrapper):
6-level conv pyramid extractor, coarse-to-fine decoder cascade (levels
6->2) of correlation + backward-warp + DenseNet-style conv stacks, and a
dilated-conv refiner; input is BGR-swapped, /255-scaled and bilinearly
resized to /64 multiples inside forward (ref pwc_net.py:226-263).

TPU-first redesign, numerically equivalent:

- NHWC end-to-end; the 81-channel cost volume is the shared
  :func:`local_correlation` op (XLA fuses the 81 shifted multiply-reduces
  on the VPU) instead of the reference's four embedded CUDA-C kernels
  JIT-compiled through CuPy (ref pwc_src/correlation.py:17-242).
- The pyramid extractor runs ONCE over the T-frame sequence; pairs are
  views ``feat[:-1]``/``feat[1:]`` (the reference extracts per pair
  stack, touching interior frames twice, ref pwc_net.py:247-248).
- The backward warp rides the shared grid_sample gather (ref
  pwc_net.py:23-41), with the reference's partial-mask thresholding.

Inputs are raw RGB floats in [0, 255] at any resolution; the /64 resize
and the ``20 * flow`` rescale back to input resolution happen inside
(ref pwc_net.py:241-261).

Mixed precision (``dtype=bfloat16``, r4 — same split as RAFT's): the
extractor pyramid and the DenseNet decoder/refiner conv stacks (the
FLOPs) compute in bf16 on the MXU, while everything the coarse-to-fine
cascade STEERS by stays fp32: every flow estimate and the ``upflow``
deconv that upsamples it, the backward-warp sampling grid and its
partial mask, the correlation volumes, and the final resize/rescale.
Params always stored fp32; returned flow always fp32.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from video_features_tpu.ops.correlation import local_correlation
from video_features_tpu.ops.resize import resize_bilinear
from video_features_tpu.ops.sampler import grid_sample

# per-level feature channels of the extractor pyramid (levels 1..6)
LEVEL_DIMS = (16, 32, 64, 96, 128, 196)
# flow magnitude scale applied to the upsampled flow fed into the warp,
# per decoder level (ref pwc_net.py:119 dblBackward)
BACKWARD_SCALE = {5: 0.625, 4: 1.25, 3: 2.5, 2: 5.0}
# correlation(81) + first-image features + upsampled flow(2) + feat(2)
DECODER_IN = {6: 81, 5: 81 + 128 + 4, 4: 81 + 96 + 4, 3: 81 + 64 + 4, 2: 81 + 32 + 4}


def _lrelu(x):
    return nn.leaky_relu(x, negative_slope=0.1)


def _conv(features: int, stride: int = 1, dilation: int = 1, name: str = None,
          dtype=jnp.float32):
    p = dilation
    return nn.Conv(
        features,
        (3, 3),
        strides=(stride, stride),
        padding=[(p, p), (p, p)],
        kernel_dilation=(dilation, dilation),
        dtype=dtype,
        name=name,
    )


class TorchConvTranspose(nn.Module):
    """torch ConvTranspose2d(k=4, s=2, p=1) -> exact 2x upsampling conv.

    Implemented as an input-dilated regular conv; the converter stores the
    kernel pre-flipped/transposed into HWIO so this is a plain
    ``conv_general_dilated`` (ref pwc_net.py:125-126 moduleUpflow/Upfeat).
    """

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (4, 4, x.shape[-1], self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype),
            kernel.astype(self.dtype),
            window_strides=(1, 1),
            padding=[(2, 2), (2, 2)],  # k - 1 - p
            lhs_dilation=(2, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + bias.astype(self.dtype)


def backward_warp(feat: jnp.ndarray, flow: jnp.ndarray) -> jnp.ndarray:
    """Warp ``feat`` (N, H, W, C) by ``flow`` (N, H, W, 2 as x,y pixels),
    zeroing samples whose bilinear support leaves the image — the
    reference's ones-channel partial mask with the >0.999 threshold
    (ref pwc_net.py:23-41)."""
    N, H, W, C = feat.shape
    gx = jnp.linspace(-1.0, 1.0, W, dtype=flow.dtype)
    gy = jnp.linspace(-1.0, 1.0, H, dtype=flow.dtype)
    base = jnp.stack(jnp.meshgrid(gx, gy), axis=-1)  # (H, W, 2)
    norm = jnp.asarray([(W - 1.0) / 2.0, (H - 1.0) / 2.0], flow.dtype)
    grid = base[None] + flow / norm

    inp = jnp.concatenate([feat, jnp.ones((N, H, W, 1), feat.dtype)], axis=-1)
    out = grid_sample(
        jnp.transpose(inp, (0, 3, 1, 2)), grid, padding_mode="zeros", align_corners=False
    )
    out = jnp.transpose(out, (0, 2, 3, 1))
    mask = jnp.where(out[..., -1:] > 0.999, 1.0, 0.0).astype(feat.dtype)
    return out[..., :-1] * mask


class Decoder(nn.Module):
    """One pyramid level: correlation (+warp below level 6) -> dense conv
    stack -> 2-channel flow (ref pwc_net.py:112-187).

    Mixed precision: the dense conv stack runs in ``dtype``; the flow
    estimate, the ``upflow`` deconv that upsamples it, the warp (sampling
    coordinates + partial mask), and the correlation volume are pinned
    fp32 — they steer the next level's sampling positions."""

    level: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, feat1, feat2, prev: Tuple[jnp.ndarray, jnp.ndarray] = None):
        f32 = jnp.float32
        if prev is None:
            feat = _lrelu(local_correlation_nhwc(feat1.astype(f32), feat2.astype(f32)))
        else:
            flow_up = TorchConvTranspose(2, dtype=f32, name="upflow")(
                prev[0].astype(f32)
            )
            feat_up = TorchConvTranspose(2, dtype=self.dtype, name="upfeat")(prev[1])
            warped = backward_warp(
                feat2.astype(f32), flow_up * BACKWARD_SCALE[self.level]
            )
            volume = _lrelu(local_correlation_nhwc(feat1.astype(f32), warped))
            feat = jnp.concatenate(
                [volume, feat1.astype(f32), flow_up, feat_up.astype(f32)], axis=-1
            )

        assert feat.shape[-1] == DECODER_IN[self.level], (
            f"decoder level {self.level}: input width {feat.shape[-1]} != "
            f"{DECODER_IN[self.level]}"
        )
        feat = feat.astype(self.dtype)  # one cast into the dense stack
        for i, ch in enumerate((128, 128, 96, 64, 32)):
            feat = jnp.concatenate(
                [_lrelu(_conv(ch, name=f"conv{i}", dtype=self.dtype)(feat)), feat], -1
            )
        flow = _conv(2, name="flow", dtype=self.dtype)(feat).astype(f32)
        return flow, feat


def local_correlation_nhwc(f1: jnp.ndarray, f2: jnp.ndarray) -> jnp.ndarray:
    """NHWC wrapper over the shared NCHW cost-volume op."""
    out = local_correlation(
        jnp.transpose(f1, (0, 3, 1, 2)), jnp.transpose(f2, (0, 3, 1, 2))
    )
    return jnp.transpose(out, (0, 2, 3, 1))


class Extractor(nn.Module):
    """6-level strided conv pyramid (ref pwc_net.py:44-109)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray):
        feats = []
        for lvl, dim in enumerate(LEVEL_DIMS, start=1):
            x = _lrelu(_conv(dim, 2, name=f"lvl{lvl}_conv0", dtype=self.dtype)(x))
            x = _lrelu(_conv(dim, 1, name=f"lvl{lvl}_conv1", dtype=self.dtype)(x))
            x = _lrelu(_conv(dim, 1, name=f"lvl{lvl}_conv2", dtype=self.dtype)(x))
            feats.append(x)
        return feats


class Refiner(nn.Module):
    """Dilated-conv context network added to the level-2 flow
    (ref pwc_net.py:189-211). Convs in ``dtype``; the 2-channel flow
    delta it emits returns fp32 (it lands on the fp32 flow estimate)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> jnp.ndarray:
        dims = ((128, 1), (128, 2), (128, 4), (96, 8), (64, 16), (32, 1))
        for i, (ch, dil) in enumerate(dims):
            feat = _lrelu(
                _conv(ch, dilation=dil, name=f"conv{i}", dtype=self.dtype)(feat)
            )
        return _conv(2, name="conv6", dtype=self.dtype)(feat).astype(jnp.float32)


def internal_grid(h: int, w: int, div: int = 64) -> Tuple[int, int]:
    """The /``div`` (Hp, Wp) grid PWC stretches its input to inside the
    forward pass (ref pwc_net.py:234-238) — unlike RAFT's replicate pad
    this is an aspect-breaking bilinear stretch, so device-preprocess
    contracts for PWC must deliver the EXACT (h, w) the host path would
    (padding the input would squash the image); the helper exists so the
    bench bucket histogram and the docs matrix can name the grid PWC
    actually compiles at."""
    return int(math.ceil(h / div) * div), int(math.ceil(w / div) * div)


class PWCNet(nn.Module):
    """(T, H, W, 3) RGB floats in [0,255] -> (T-1, H, W, 2) flow for each
    consecutive frame pair, at input resolution.

    ``dtype=bfloat16`` selects the mixed-precision graph (module
    docstring); the returned flow is always fp32."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, frames: jnp.ndarray) -> jnp.ndarray:
        T, H, W, _ = frames.shape
        x = frames[..., ::-1] / 255.0  # RGB -> BGR, [0,1] (ref pwc_net.py:230-231)
        Hp, Wp = internal_grid(H, W)
        x = jnp.moveaxis(
            resize_bilinear(jnp.moveaxis(x, -1, -3), (Hp, Wp), align_corners=False),
            -3,
            -1,
        )

        pyramid = Extractor(dtype=self.dtype, name="extractor")(x)

        prev = None
        for level in (6, 5, 4, 3, 2):
            f = pyramid[level - 1]
            prev = Decoder(level, dtype=self.dtype, name=f"decoder{level}")(
                f[:-1], f[1:], prev
            )

        flow, feat = prev
        flow = flow + Refiner(dtype=self.dtype, name="refiner")(feat)

        flow = jnp.moveaxis(
            resize_bilinear(jnp.moveaxis(flow, -1, -3), (H, W), align_corners=False),
            -3,
            -1,
        )
        scale = jnp.asarray([W / Wp, H / Hp], flow.dtype)
        return 20.0 * flow * scale


def build(dtype=jnp.float32) -> PWCNet:
    return PWCNet(dtype=dtype)


def init_params(seed: int = 0):
    model = build()
    dummy = jnp.zeros((2, 64, 64, 3), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)["params"]
