"""sniklaus pytorch-pwc checkpoint (pwc_net_sintel.pt) -> Flax param tree.

torch naming (ref pwc_src/pwc_net.py): ``moduleExtractor.module{One..Six}``
Sequentials (conv indices 0/2/4), top-level ``module{Two..Six}`` decoders
with ``moduleUpflow``/``moduleUpfeat`` ConvTranspose2d + ``moduleOne.0``
.. ``moduleSix.0`` convs, and ``moduleRefiner.moduleMain`` (indices
0,2,...,12). ConvTranspose kernels are pre-flipped into HWIO so the model
applies them as input-dilated regular convs.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from video_features_tpu.models.common.weights import (
    check_all_consumed,
    conv2d_kernel,
    strip_prefix,
)

_ORDINAL = {1: "One", 2: "Two", 3: "Thr", 4: "Fou", 5: "Fiv", 6: "Six"}


def _conv(sd: Dict[str, np.ndarray], name: str, consumed) -> Dict[str, np.ndarray]:
    consumed.update((f"{name}.weight", f"{name}.bias"))
    return {"kernel": conv2d_kernel(sd[f"{name}.weight"]), "bias": sd[f"{name}.bias"]}


def _conv_transpose(sd: Dict[str, np.ndarray], name: str, consumed):
    """torch ConvTranspose2d weight (I, O, kH, kW) -> spatially flipped
    HWIO kernel for the equivalent input-dilated regular conv."""
    consumed.update((f"{name}.weight", f"{name}.bias"))
    w = np.transpose(sd[f"{name}.weight"], (2, 3, 0, 1))[::-1, ::-1]
    return {"kernel": np.ascontiguousarray(w), "bias": sd[f"{name}.bias"]}


def convert_state_dict(sd: Dict[str, np.ndarray]):
    sd = strip_prefix(sd, "module.")
    consumed = set()

    extractor = {}
    for lvl in range(1, 7):
        seq = f"moduleExtractor.module{_ORDINAL[lvl]}"
        for i, idx in enumerate((0, 2, 4)):
            extractor[f"lvl{lvl}_conv{i}"] = _conv(sd, f"{seq}.{idx}", consumed)

    params = {"extractor": extractor}
    for lvl in range(2, 7):
        dec = f"module{_ORDINAL[lvl]}"
        blk = {}
        if lvl < 6:
            blk["upflow"] = _conv_transpose(sd, f"{dec}.moduleUpflow", consumed)
            blk["upfeat"] = _conv_transpose(sd, f"{dec}.moduleUpfeat", consumed)
        for i in range(5):
            blk[f"conv{i}"] = _conv(sd, f"{dec}.module{_ORDINAL[i + 1]}.0", consumed)
        blk["flow"] = _conv(sd, f"{dec}.moduleSix.0", consumed)
        params[f"decoder{lvl}"] = blk

    params["refiner"] = {
        f"conv{i}": _conv(sd, f"moduleRefiner.moduleMain.{idx}", consumed)
        for i, idx in enumerate((0, 2, 4, 6, 8, 10, 12))
    }
    check_all_consumed(sd, consumed, "PWCNet")
    return params
