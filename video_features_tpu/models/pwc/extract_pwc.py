"""PWC-Net optical-flow extractor (ref models/pwc/extract_pwc.py).

Same pair-streaming runtime as RAFT (shared PairwiseFlowExtractor); no
host-side padding — the /64-multiple resize is part of the PWC forward
(ref pwc_src/pwc_net.py:241-245). Flow comes back at input resolution.
"""

from __future__ import annotations

from video_features_tpu.models.common.flow_extract import PairwiseFlowExtractor
from video_features_tpu.models.pwc.convert import convert_state_dict
from video_features_tpu.models.pwc.model import build, init_params


class ExtractPWC(PairwiseFlowExtractor):
    _convert_state_dict = staticmethod(convert_state_dict)

    def _model(self):
        # --dtype bfloat16 selects PWC's mixed-precision graph: conv
        # stacks bf16 on the MXU, every flow estimate / warp grid /
        # correlation volume pinned fp32 — models/pwc/model.py docstring
        from video_features_tpu.models.common.weights import compute_dtype

        return build(dtype=compute_dtype(self.config))

    def _init_params(self):
        return init_params()
