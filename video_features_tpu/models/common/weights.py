"""Checkpoint loading + conversion plumbing shared by every model family.

The reference hardcodes per-model checkpoint paths and pip/URL downloads
(SURVEY.md §2 #21) and keeps TF->PT weight porters in-tree (ref
i3d_src/i3d_net.py:277-321) — the precedent for the PT->Flax converters
that live in each ``models/<family>/convert.py`` here.

Checkpoints are consumed from local files only (this environment has no
egress): ``.pt``/``.pth`` torch pickles (weights_only load), ``.npz``
archives, or already-converted flax ``.msgpack``. When no weights are
given, models run with deterministic random init — feature *values* are
then meaningless but every pipeline contract (shapes, dtypes, windowing,
sinks) is exercised, and converters are oracle-tested against randomly
initialized torch models in tests/.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np


def load_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Load a torch/npz checkpoint into a flat {name: float32 ndarray}."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"weights not found: {path}")
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    if not path.endswith((".pt", ".pth", ".pytorch", ".bin")):
        raise ValueError(
            f"unsupported checkpoint format: {path} "
            "(expected .npz or a torch pickle .pt/.pth/.pytorch/.bin; "
            "already-converted flax .msgpack goes through load_params)"
        )
    # torch pickle
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    out = {}
    for k, v in obj.items():
        if hasattr(v, "numpy"):
            out[k] = v.detach().to(torch.float32).cpu().numpy()
    return out


def is_orbax_checkpoint(path: str) -> bool:
    """An orbax checkpoint directory (written by ``save_orbax`` /
    scripts/convert_weights.py) — distinguished from plain weight dirs
    (e.g. I3D's directory of reference-named .pt files) by its marker."""
    return os.path.isdir(path) and (
        os.path.exists(os.path.join(path, "_CHECKPOINT_METADATA"))
        or os.path.exists(os.path.join(path, "_METADATA"))
    )


def save_orbax(params: Any, path: str) -> None:
    """Write a converted param tree as an orbax checkpoint directory —
    the sharded-checkpoint format: each array is chunked on disk, so a
    mesh/multi-host run can restore every weight DIRECTLY onto its
    destination devices (``load_orbax`` with a mesh) without ever
    materializing the full tree in one host's memory. The TPU-native
    upgrade of the reference's whole-file torch pickles (SURVEY.md §2
    #21)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params)
    ckptr.wait_until_finished()


def load_orbax(path: str, mesh=None, specs_fn=None) -> Any:
    """Restore an orbax checkpoint.

    ``mesh=None``: host numpy tree (the ``load_params`` path).
    With a ``jax.sharding.Mesh``: build the abstract target from the
    checkpoint's own metadata and restore each leaf already placed under
    ``specs_fn(meta_tree) -> PartitionSpec tree`` (None = replicate) —
    no full-tree host copy, shards stream to their devices.
    """
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    path = os.path.abspath(path)
    if mesh is None:
        return ckptr.restore(path)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    meta = ckptr.metadata(path).item_metadata
    specs = specs_fn(meta) if specs_fn else jax.tree.map(lambda _: P(), meta)
    target = jax.tree.map(
        lambda m, s: jax.ShapeDtypeStruct(
            m.shape, m.dtype, sharding=NamedSharding(mesh, s)
        ),
        meta,
        specs,
    )
    return ckptr.restore(path, target)


def load_params(path: str, convert) -> Any:
    """Load model params for an extractor.

    ``.msgpack`` holds an already-converted flax param tree (saved with
    ``flax.serialization.msgpack_serialize``) and an orbax checkpoint
    directory an already-converted sharded tree — both are returned
    as-is; anything else is a source-framework state dict that goes
    through ``load_state_dict`` + the family's ``convert`` function.
    """
    if is_orbax_checkpoint(path):
        return load_orbax(path)
    if path.endswith(".msgpack"):
        if not os.path.exists(path):
            raise FileNotFoundError(f"weights not found: {path}")
        from flax import serialization

        with open(path, "rb") as f:
            tree = serialization.msgpack_restore(f.read())
        if isinstance(tree, dict) and set(tree) == {"params"}:
            tree = tree["params"]
        return tree
    return convert(load_state_dict(path))


def strip_prefix(sd: Dict[str, np.ndarray], prefix: str) -> Dict[str, np.ndarray]:
    """Drop a leading module prefix (e.g. the 'module.' that the reference's
    degenerate DataParallel wrapper bakes into RAFT/I3D checkpoints —
    ref models/raft/extract_raft.py:59)."""
    if any(k.startswith(prefix) for k in sd):
        return {k[len(prefix):] if k.startswith(prefix) else k: v for k, v in sd.items()}
    return sd


def transpose_linear(w: np.ndarray) -> np.ndarray:
    """torch Linear weight (out, in) -> flax Dense kernel (in, out)."""
    return np.ascontiguousarray(w.T)


def conv2d_kernel(w: np.ndarray) -> np.ndarray:
    """torch Conv2d weight (O, I, kH, kW) -> flax (kH, kW, I, O)."""
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def conv3d_kernel(w: np.ndarray) -> np.ndarray:
    """torch Conv3d weight (O, I, kT, kH, kW) -> flax (kT, kH, kW, I, O)."""
    return np.ascontiguousarray(np.transpose(w, (2, 3, 4, 1, 0)))


def bn_params(sd: Dict[str, np.ndarray], prefix: str, consumed) -> Dict[str, np.ndarray]:
    """torch BatchNorm state (weight/bias/running_mean/running_var) ->
    EvalBatchNorm params (scale/bias/mean/var), marking keys consumed."""
    consumed.update(
        f"{prefix}.{s}" for s in ("weight", "bias", "running_mean", "running_var")
    )
    return {
        "scale": sd[f"{prefix}.weight"],
        "bias": sd[f"{prefix}.bias"],
        "mean": sd[f"{prefix}.running_mean"],
        "var": sd[f"{prefix}.running_var"],
    }


def check_all_consumed(sd: Dict[str, np.ndarray], consumed, model_name: str) -> None:
    """Converters must account for every checkpoint tensor — silent drops are
    how weight-porting bugs hide (SURVEY.md §7 hard part #6)."""
    left = set(sd) - set(consumed)
    # num_batches_tracked counters carry no information
    left = {k for k in left if not k.endswith("num_batches_tracked")}
    if left:
        raise ValueError(
            f"{model_name} converter left {len(left)} tensors unconsumed, e.g. "
            f"{sorted(left)[:5]}"
        )


def cast_floats_for_compute(params: Any, dtype, exclude=()):
    """Cast float kernels (ndim >= 2) to the compute dtype for
    ``--dtype bfloat16``; 1-d leaves (biases, norm scales/stats) stay fp32
    — their math is pinned fp32 in the models. ``exclude`` lists param
    path-name substrings kept fp32 (e.g. CLIP's final 'proj')."""
    import jax
    import jax.numpy as jnp

    def cast(path, x):
        names = [str(getattr(p, "key", "")) for p in path]
        if any(e in names for e in exclude):
            return x
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) and x.ndim >= 2:
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map_with_path(cast, params)


def compute_dtype(config):
    """The jnp dtype for --dtype (config.py)."""
    import jax.numpy as jnp

    return jnp.bfloat16 if getattr(config, "dtype", "float32") == "bfloat16" else jnp.float32


def random_init_fallback(config, model_name: str, expected: str) -> None:
    """Gate the no-weights path: loud by default.

    The reference never silently runs a random-weight model — it either
    auto-downloads (CLIP via pip, vggish via URL) or crashes on a missing
    checkpoint path (ref models/i3d/extract_i3d.py:23-26). Callers invoke
    this before falling back to deterministic random init; it raises
    unless ``--allow_random_init`` was passed, and warns loudly when it
    was.
    """
    if getattr(config, "allow_random_init", False):
        print(
            f"WARNING: {model_name}: no pretrained weights loaded — running "
            "with deterministic random init; extracted features are "
            "MEANINGLESS (--allow_random_init)."
        )
        return
    raise RuntimeError(
        f"{model_name}: no pretrained weights. Expected {expected}. "
        "Pass --weights_path, or --allow_random_init to run with random "
        "weights (meaningless features; tests/benchmarks only)."
    )


def tree_to_device(params: Any, device):
    import jax

    return jax.device_put(params, device)
