"""Shared inference-graph layers used across model families."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class EvalBatchNorm(nn.Module):
    """Inference-mode BatchNorm: running stats are plain params.

    Folds to ``x * inv + shift`` where ``inv = scale / sqrt(var + eps)`` —
    one fused multiply-add that XLA merges into the preceding conv.
    """

    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        C = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (C,))
        bias = self.param("bias", nn.initializers.zeros, (C,))
        mean = self.param("mean", nn.initializers.zeros, (C,))
        var = self.param("var", nn.initializers.ones, (C,))
        inv = scale * jax.lax.rsqrt(var + self.eps)
        # stats/fold math stays fp32 under --dtype bfloat16 (stats are
        # fp32 params; promotion does the rest); activations keep their
        # incoming dtype so the bf16 stream isn't silently widened
        return (x.astype(jnp.float32) * inv + (bias - mean * inv)).astype(x.dtype)
