"""Shared inference-graph layers used across model families."""

from __future__ import annotations

import os
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


class EvalBatchNorm(nn.Module):
    """Inference-mode BatchNorm: running stats are plain params.

    Folds to ``x * inv + shift`` where ``inv = scale / sqrt(var + eps)`` —
    one fused multiply-add that XLA merges into the preceding conv.
    """

    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        C = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (C,))
        bias = self.param("bias", nn.initializers.zeros, (C,))
        mean = self.param("mean", nn.initializers.zeros, (C,))
        var = self.param("var", nn.initializers.ones, (C,))
        inv = scale * jax.lax.rsqrt(var + self.eps)
        # stats/fold math stays fp32 under --dtype bfloat16 (stats are
        # fp32 params; promotion does the rest); activations keep their
        # incoming dtype so the bf16 stream isn't silently widened
        return (x.astype(jnp.float32) * inv + (bias - mean * inv)).astype(x.dtype)


def conv3d_impl() -> str:
    """Which lowering Conv3DCompat uses: ``direct`` (one
    ``lax.conv_general_dilated`` over DHW — XLA's native 3D conv) or
    ``decomposed`` (a sum of kt 2D convs over strided time slices —
    mathematically identical, avoids the TPU 3D-conv lowering that has
    crashed the axon compile helper, BASELINE.md round-4 chip log).

    Env knob ``VFT_CONV3D_IMPL`` so the bench's compile-probe child can
    select the safe path for subsequent subprocesses without config
    plumbing; the CLI exposes it as ``--conv3d_impl``.
    """
    impl = os.environ.get("VFT_CONV3D_IMPL", "direct")
    if impl not in ("direct", "decomposed"):
        raise ValueError(f"VFT_CONV3D_IMPL must be direct|decomposed, got {impl!r}")
    return impl


def explicit_conv3d_impl(config) -> str | None:
    """The per-extractor --conv3d_impl contract, shared by the 3D-conv
    families (i3d, r21d): an explicit direct/decomposed choice threads
    into THAT extractor's Conv3DCompat modules; 'auto' (None) defers to
    the VFT_CONV3D_IMPL env var at trace time."""
    impl = getattr(config, "conv3d_impl", "auto")
    return None if impl in (None, "auto") else impl


class Conv3DCompat(nn.Module):
    """3D conv with a checkpoint-identical choice of TPU lowering.

    Parameter names/shapes match ``nn.Conv`` exactly (``kernel``
    (kt, kh, kw, Cin, Cout) + optional ``bias``), so converted reference
    checkpoints load identically under either impl (ref
    i3d_net.py:37-105 is a plain torch Conv3d; the decomposition is our
    TPU-side workaround, not a semantic change).

    ``decomposed``: conv3d(x, w) == sum_i conv2d(x[:, i::st], w[i]) after
    explicit time padding — kt <= 7 everywhere in I3D, so the unrolled
    sum stays a handful of MXU-friendly 2D convs.
    """

    features: int
    kernel: Tuple[int, int, int]
    stride: Tuple[int, int, int]
    padding: Sequence[Tuple[int, int]]  # (lo, hi) per (t, h, w)
    use_bias: bool = False
    dtype: jnp.dtype = jnp.float32
    # None: read VFT_CONV3D_IMPL at trace time (process-wide default);
    # 'direct'/'decomposed': this model's explicit choice — threaded from
    # --conv3d_impl so one extractor's config never leaks into another's
    impl: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kt, kh, kw = self.kernel
        w = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (kt, kh, kw, x.shape[-1], self.features),
        )
        b = (
            self.param("bias", nn.initializers.zeros, (self.features,))
            if self.use_bias
            else None
        )
        w = w.astype(self.dtype)
        x = x.astype(self.dtype)
        if (self.impl or conv3d_impl()) == "direct":
            out = jax.lax.conv_general_dilated(
                x,
                w,
                window_strides=self.stride,
                padding=list(self.padding),
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            )
        else:
            st = self.stride[0]
            lo, hi = self.padding[0]
            xp = jnp.pad(x, ((0, 0), (lo, hi), (0, 0), (0, 0), (0, 0)))
            t_out = (xp.shape[1] - kt) // st + 1
            out = None
            for i in range(kt):
                xi = jax.lax.slice_in_dim(
                    xp, i, i + (t_out - 1) * st + 1, stride=st, axis=1
                )
                B = xi.shape[0]
                oi = jax.lax.conv_general_dilated(
                    xi.reshape((B * t_out,) + xi.shape[2:]),
                    w[i],
                    window_strides=self.stride[1:],
                    padding=list(self.padding[1:]),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                out = oi if out is None else out + oi
            out = out.reshape((B, t_out) + out.shape[1:])
        if b is not None:
            out = out + b.astype(self.dtype)
        return out
