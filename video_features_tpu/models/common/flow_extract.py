"""Shared runtime for pairwise optical-flow extractors (RAFT, PWC).

Both reference extractors run the identical loop — streaming decode,
optional ``--side_size`` PIL resize, raw [0,255] float frames, batches of
B+1 frames sharing one boundary frame so B flow pairs come out per call
(ref models/raft/extract_raft.py:93-146, models/pwc/extract_pwc.py:93-144)
— and differ only in the model and RAFT's /8 replicate padding.

TPU-first: every batch runs at ONE static shape — the tail batch is
filled by repeating the last frame and the surplus pair outputs dropped —
so XLA compiles a single executable per video resolution.

Output contract: ``{<type>: (T-1, 2, H, W), fps, timestamps_ms}``
(ref extract_raft.py:155-160), flow at input resolution.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import jax

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import probe, stream_frames
from video_features_tpu.models.common.weights import load_params, random_init_fallback
from video_features_tpu.ops.preprocess import pil_resize


class NullPadder:
    """PWC needs no host-side padding — the /64 resize lives in-model."""

    def pad(self, x: np.ndarray) -> np.ndarray:
        return x

    def unpad(self, x: np.ndarray) -> np.ndarray:
        return x


class PairwiseFlowExtractor(BaseExtractor):
    """Subclasses provide ``_model()``, ``_convert_state_dict`` and
    optionally ``_make_padder(shape)``.

    ``--sharding mesh`` shards the FRAME axis of each B+1-frame window
    over the mesh 'data' axis — the sequence-parallel story for flow:
    the consecutive-pair views (``fmap[:-1]``/``fmap[1:]`` inside the
    models) couple neighboring shards, and GSPMD inserts the one-frame
    halo exchange (collective-permute over ICI); weights replicate.
    Verified bit-identical to single-device on the virtual mesh
    (tests/test_parallel.py::test_mesh_raft_sequence_parallel...).
    """

    mesh_capable = True  # DP/sequence-parallel over the frame axis

    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.batch_size = max(int(self.config.batch_size or 1), 1)
        self.side_size = self.config.side_size
        self.resize_to_smaller_edge = self.config.resize_to_smaller_edge
        self._host_params = None

    # --- model hooks -------------------------------------------------------
    def _model(self):
        raise NotImplementedError

    def _init_params(self):
        raise NotImplementedError

    @staticmethod
    def _convert_state_dict(sd):
        raise NotImplementedError("subclass must set _convert_state_dict")

    def _make_padder(self, shape):
        return NullPadder()

    # --- shape-contracted device preprocess (--preprocess device) ----------
    # The host chain is decode -> optional --side_size PIL resize ->
    # float32 -> padder.pad -> model. Under --preprocess device those
    # collapse: raw uint8 HWC windows ship over H2D (4x fewer bytes) and
    # banded taps (ops/resize.py::shape_contract_banded) resize each
    # source frame DIRECTLY onto the model's padded grid — the /8
    # InputPadder target for RAFT (the replicate-pad rows are baked into
    # the taps), the exact resized shape for PWC (its /64 stretch lives
    # in-model and must see unpadded geometry, models/pwc/model.py::
    # internal_grid). With no --side_size the taps are the identity band,
    # so the device path is bit-exact against host ``InputPadder.pad``.

    def _device_grid(self, oh: int, ow: int):
        """(out_h, out_w, top, left): where the resized (oh, ow) image
        lands in the device output contract. Base: the exact resized
        shape (PWC). ExtractRAFT overrides with its InputPadder /8
        grid and centered placement."""
        return oh, ow, 0, 0

    def _device_contract(self, h: int, w: int):
        """(wy, wx, (bh, bw), (oh, ow)) for a source resolution: banded
        taps onto this extractor's output grid, the input spatial bucket
        the raw frames pad to, and the resized shape the per-video padder
        (and hence ``unpad``) is built from."""
        from video_features_tpu.ops.resize import resized_hw, shape_contract_banded
        from video_features_tpu.ops.window import spatial_bucket

        side = int(self.side_size) if self.side_size is not None else 0
        smaller = bool(self.resize_to_smaller_edge)
        oh, ow = resized_hw(h, w, side, smaller) if side else (h, w)
        out_h, out_w, top, left = self._device_grid(oh, ow)
        bh, bw = spatial_bucket(h, w, self.config.spatial_bucket)
        wt_y, idx_y, wt_x, idx_x = shape_contract_banded(
            h, w, side, out_h, out_w, top, left, "bilinear",
            pad_h=bh, pad_w=bw, pad_mode="edge", smaller_edge=smaller,
        )
        return (wt_y, idx_y), (wt_x, idx_x), (bh, bw), (oh, ow)

    # --- runtime -----------------------------------------------------------
    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path, type(self)._convert_state_dict
                )
            else:
                random_init_fallback(
                    self.config, self.feature_type,
                    "the reference flow checkpoint (raft: raft-sintel.pth; "
                    "pwc: network-default.pytorch) or a converted flax "
                    ".msgpack",
                )
                self._host_params = self._init_params()
        return self._host_params

    def _build(self, device):
        from video_features_tpu.parallel.sharding import is_mesh, place_params

        model = self._model()
        params = place_params(self._load_host_params(), device)

        def forward(p, frames):  # (B+1, H, W, 3) -> (B, H, W, 2)
            if is_mesh(device):
                # frame/time axis over 'data': sequence parallelism (the
                # models' shifted pair views become GSPMD halo exchanges)
                from jax.sharding import NamedSharding, PartitionSpec as P

                frames = jax.lax.with_sharding_constraint(
                    frames, NamedSharding(device, P("data"))
                )
            return model.apply({"params": p}, frames)

        # plain jit even on the mesh: the B-pair output length is one
        # short of the (data-divisible) frame axis, and explicit
        # out_shardings require divisibility — propagation handles it.
        # EXCEPT multi-host, where outputs pin replicated so every
        # process can fetch them (sharding.py::multihost_out_kwargs)
        from video_features_tpu.parallel.sharding import multihost_out_kwargs

        forward = jax.jit(forward, **multihost_out_kwargs(device))

        # --video_batch fused path: G whole windows forward as one call,
        # vmapped over the window axis (each window is an independent
        # sequence — the pair views must NOT couple across videos). On a
        # mesh the WINDOW axis shards over 'data' (pure DP, the same
        # placement CLIP's fused batch uses) instead of the solo path's
        # frame-axis sequence parallelism.
        def forward_group(p, windows):  # (G, B+1, Hp, Wp, 3)
            return jax.vmap(lambda w: model.apply({"params": p}, w))(windows)

        fns = {
            "params": params,
            "forward": forward,
            "forward_group": jax.jit(
                forward_group, **multihost_out_kwargs(device)
            ),
            "device": device,
        }

        if self._device_preprocess_enabled():
            from video_features_tpu.ops.preprocess import device_resize_frames

            def forward_raw(p, x_u8, wy, wx):
                # uint8 (B+1, bh, bw, 3) + shared (P, K) taps -> flow on
                # the contracted grid; resize+pad+float32 fuse into the
                # flow-model dispatch
                x = device_resize_frames(x_u8, wy, wx)
                return model.apply({"params": p}, x)

            if is_mesh(device):
                from video_features_tpu.parallel.sharding import (
                    fused_payload_shardings,
                )

                # fused contract on the mesh: the raw frame axis shards
                # over 'data' (the same sequence parallelism as the host
                # path — dispatch_prepared mesh-fills the window first)
                # and the banded taps replicate. Output pins REPLICATED:
                # the B-pair axis is one short of the data-divisible
                # frame axis, so a 'data' out spec would be rejected,
                # and the all-gather is value-preserving — mesh stays
                # bit-exact against queue.
                batch_sh, rep = fused_payload_shardings(device)
                fns["forward_raw"] = jax.jit(
                    forward_raw,
                    in_shardings=(None, batch_sh, (rep, rep), (rep, rep)),
                    out_shardings=rep,
                )
            else:

                def forward_raw_group(p, xs_u8, wy, wx):
                    # (G, B+1, bh, bw, 3) with PER-WINDOW (G, P, K) taps:
                    # mixed source resolutions fuse whenever they share
                    # the (input bucket, output grid, K) contract
                    x = device_resize_frames(xs_u8, wy, wx)
                    return jax.vmap(lambda w: model.apply({"params": p}, w))(x)

                from video_features_tpu.extract import ingest

                # donate only the raw uint8 windows (argnum 1): they are
                # placed fresh per call, while the banded taps are
                # placed once per video and reused across its windows
                fns["forward_raw"] = ingest.jit_donated(
                    forward_raw, donate_argnums=(1,),
                    **multihost_out_kwargs(device)
                )
                fns["forward_raw_group"] = ingest.jit_donated(
                    forward_raw_group, donate_argnums=(1,),
                    **multihost_out_kwargs(device)
                )

        return fns

    def _preprocess(self, frame: np.ndarray) -> np.ndarray:
        if self.side_size is not None:
            frame = pil_resize(frame, int(self.side_size), self.resize_to_smaller_edge)
        return frame.astype(np.float32)

    def _dispatch_batch(self, state, batch: List[np.ndarray], padder):
        """Enqueue one B+1-frame window (async under XLA); the result is
        fetched lazily by ``_fetch_batch`` with a one-batch lag so the
        device computes window k+1 while window k's flow copies out."""
        n_pairs = len(batch) - 1
        if n_pairs < 1:
            return None
        from video_features_tpu.parallel.sharding import is_mesh, place_batch

        # one static window length per run: B+1 frames, rounded up on a
        # mesh so the frame axis divides 'data' (last-frame repeats; the
        # [:n_pairs] slice below drops the surplus pair outputs). The
        # explicit sharded device_put assembles a global array — works
        # multi-host, unlike handing jit a process-local one.
        target_len = self.batch_size + 1
        if is_mesh(state["device"]):
            data = state["device"].shape["data"]
            target_len = -(-target_len // data) * data
        window = batch + [batch[-1]] * (target_len - len(batch))
        x = padder.pad(np.stack(window))
        x = place_batch(x, state["device"])
        out = state["forward"](state["params"], x)  # (B, Hp, Wp, 2) on device
        return out, n_pairs, (batch if self.config.show_pred else None)

    def _fetch_batch(self, pending, padder, flows: List[np.ndarray]) -> None:
        if pending is None:
            return
        out, n_pairs, batch = pending
        flow = padder.unpad(np.asarray(out))[:n_pairs]
        flows.extend(np.transpose(flow, (0, 3, 1, 2)))  # saved as (2, H, W)
        if batch is not None:
            from video_features_tpu.utils.flow_viz import show_flow_on_frame

            for i in range(n_pairs):
                show_flow_on_frame(flow[i], batch[i])

    def extract(self, device, state, path_entry, source=None) -> Dict[str, np.ndarray]:
        """``source``: an already-resolved (decode_path, selection_fps)
        from prepare's over-cap handoff — reusing it avoids re-running an
        ffmpeg re-encode the prepare pass already paid for."""
        video_path = video_path_of(path_entry)
        fps = (self.config.extraction_fps
               or probe(video_path, self.config.decoder).fps or 25.0)
        decode_path, sel_fps = source or self._fps_source(video_path)

        flows: List[np.ndarray] = []
        timestamps_ms: List[float] = []
        batch: List[np.ndarray] = []
        padder = None
        pending = None  # lag-1 window: fetch k after dispatching k+1
        for frame, ts in stream_frames(
            decode_path, sel_fps, self.config.decoder
        ):
            timestamps_ms.append(ts)
            frame = self._preprocess(frame)
            if padder is None:
                padder = self._make_padder(frame.shape[:2])
            batch.append(frame)
            # B+1 frames make B pairs; the boundary frame carries over
            if len(batch) - 1 == self.batch_size:
                nxt = self._dispatch_batch(state, batch, padder)
                self._fetch_batch(pending, padder, flows)
                pending = nxt
                batch = [batch[-1]]
        if len(batch) > 1:
            nxt = self._dispatch_batch(state, batch, padder)
            self._fetch_batch(pending, padder, flows)
            pending = nxt
        self._fetch_batch(pending, padder, flows)
        if padder is None:
            raise IOError(f"no frames decoded from {video_path}")

        return {
            self.feature_type: np.array(flows),
            "fps": np.array(fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    # --- async host pipeline (prepare/dispatch/fetch) ----------------------
    # The reference's flow loop is strictly serial (decode a window, run
    # it, repeat — ref extract_raft.py:93-146). Splitting it the same way
    # as the 2D nets lets flow videos ride the 3-stage pipeline: decode on
    # worker threads, all windows dispatched async, fetch overlapped.

    PIPELINE_MAX_BYTES = 4 << 30

    def _window_cap(self, frame: np.ndarray) -> int:
        """Prefetch cap in FRAMES given one decoded (padded) frame."""
        return self._prefetch_frame_cap(
            self.PIPELINE_MAX_BYTES, frame.nbytes, floor=4 * self.batch_size
        )

    def prepare(self, path_entry):
        # show_pred draws flow onto the raw frames per pair — keep the
        # serial path where the frames are still in hand
        if self.config.show_pred:
            return ("stream", path_entry)
        from video_features_tpu.ops.window import pad_hw

        video_path = video_path_of(path_entry)
        fps = (self.config.extraction_fps
               or probe(video_path, self.config.decoder).fps or 25.0)
        decode_path, sel_fps = self._fps_source(video_path)

        # device preprocess keeps windows as raw uint8 at the input
        # bucket (4x more frames fit under the same byte cap; the resize
        # happens in-dispatch against the contract taps)
        device_pre = self._device_preprocess_enabled()
        windows: List[np.ndarray] = []
        n_pairs: List[int] = []
        timestamps_ms: List[float] = []
        batch: List[np.ndarray] = []
        padder = None
        contract = None
        cap = None
        count = 0

        def flush(batch):
            # static (B+1)-frame shape: the tail window repeats its last
            # frame (identical pairs compute zero-ish flow and are cut by
            # the n_pairs slice), exactly like _dispatch_batch
            n = len(batch) - 1
            window = batch + [batch[-1]] * (self.batch_size + 1 - len(batch))
            if device_pre:
                windows.append(pad_hw(np.stack(window), *contract[2]))
            else:
                windows.append(padder.pad(np.stack(window)))
            n_pairs.append(n)

        for frame, ts in stream_frames(
            decode_path, sel_fps, self.config.decoder
        ):
            count += 1
            if not device_pre:
                frame = self._preprocess(frame)
            if padder is None:
                if device_pre:
                    contract = self._device_contract(*frame.shape[:2])
                    # the padder serves fetch-side unpad: built from the
                    # RESIZED shape, whose grid the taps target
                    padder = self._make_padder(contract[3])
                    cap = self._window_cap(pad_hw(frame[None], *contract[2])[0])
                else:
                    padder = self._make_padder(frame.shape[:2])
                    cap = self._window_cap(padder.pad(frame[None])[0])
            if count > cap:
                # too big to prefetch whole; hand the resolved decode
                # source over so a completed re-encode isn't re-run
                return ("stream", path_entry, (decode_path, sel_fps))
            timestamps_ms.append(ts)
            batch.append(frame)
            if len(batch) - 1 == self.batch_size:
                flush(batch)
                batch = [batch[-1]]
        if len(batch) > 1:
            flush(batch)
        if padder is None:
            raise IOError(f"no frames decoded from {video_path}")
        if device_pre:
            head = ("dev", windows, contract[0], contract[1])
            return head, n_pairs, padder, fps, timestamps_ms
        return windows, n_pairs, padder, fps, timestamps_ms

    def _mesh_fill(self, state, w: np.ndarray) -> np.ndarray:
        """Extend a (B+1)-frame window so the frame axis divides the mesh
        'data' axis (last-frame repeat; surplus pairs fall to the n_pairs
        slice) — the same rounding _dispatch_batch applies inline."""
        from video_features_tpu.parallel.sharding import is_mesh

        if not is_mesh(state["device"]):
            return w
        data = state["device"].shape["data"]
        target = -(-w.shape[0] // data) * data
        if target == w.shape[0]:
            return w
        reps = np.repeat(w[-1:], target - w.shape[0], axis=0)
        return np.concatenate([w, reps], axis=0)

    def dispatch_prepared(self, device, state, path_entry, payload):
        if payload[0] == "stream":
            # ("stream", entry) from show_pred (no source resolved yet) or
            # ("stream", entry, (decode_path, sel_fps)) from the over-cap
            # handoff
            source = payload[2] if len(payload) > 2 else None
            return ("done", self.extract(device, state, payload[1], source))
        from video_features_tpu.parallel.sharding import place_batch

        head, n_pairs, padder, fps, timestamps_ms = payload
        if isinstance(head, tuple) and head[0] == "dev":
            # device contract: raw uint8 windows + shared taps. On a mesh
            # the taps replicate (per-shape metadata) and each window
            # mesh-fills so its frame axis divides 'data' — matching the
            # in_shardings the fused entry was jitted with.
            from jax.sharding import PartitionSpec as P

            _, windows, wy, wx = head
            wy = tuple(place_batch(a, state["device"], spec=P()) for a in wy)
            wx = tuple(place_batch(a, state["device"], spec=P()) for a in wx)
            outs = []
            for w, n in zip(windows, n_pairs):
                x = place_batch(self._mesh_fill(state, w), state["device"])
                outs.append(
                    (state["forward_raw"](state["params"], x, wy, wx), n)
                )
            return ("batched", outs, padder, fps, timestamps_ms)
        outs = []
        for w, n in zip(head, n_pairs):
            x = place_batch(self._mesh_fill(state, w), state["device"])
            outs.append((state["forward"](state["params"], x), n))
        return ("batched", outs, padder, fps, timestamps_ms)

    def fetch_dispatched(self, handle) -> Dict[str, np.ndarray]:
        if handle[0] == "done":
            return handle[1]
        _, outs, padder, fps, timestamps_ms = handle
        flows: List[np.ndarray] = []
        for out, n in outs:
            flow = padder.unpad(np.asarray(out))[:n]
            flows.extend(np.transpose(flow, (0, 3, 1, 2)))
        return {
            self.feature_type: np.array(flows),
            "fps": np.array(fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    # --- cross-video aggregation (--video_batch) ---------------------------
    # A corpus of short clips yields windows with most pad-pairs wasted
    # and one tiny dispatch per video on the deepest nets (VERDICT r03
    # weak #4). Same-resolution windows are shape-identical, so G of them
    # — from ANY mix of videos — fuse into one vmapped forward; outputs
    # split back per video by window counts. The reference batches pairs
    # only WITHIN a video (ref extract_raft.py:143-146).

    AGG_MAX_BYTES = 512 << 20

    def agg_key(self, payload):
        if payload[0] == "stream":
            return None
        head = payload[0]
        if isinstance(head, tuple) and head[0] == "dev":
            # mesh ships only the solo fused entry (frame-axis sequence
            # parallelism); the group path's window-axis DP would need its
            # own sharding contract, so cross-video fusion stays queue-only
            if self.config.sharding == "mesh":
                return None
            _, windows, wy, wx = head
            if not windows:
                return None
            if len(windows) * windows[0].nbytes > self.AGG_MAX_BYTES:
                return None
            # fuse per (input bucket window shape, output grid, K): the
            # output-bucket id rides in via the tap shapes (out_h, K) /
            # (out_w, K) — mixed source resolutions sharing the contract
            # stack their per-window taps in dispatch_group
            return ("dev", windows[0].shape, wy[0].shape, wx[0].shape)
        windows = head
        # a 1-frame video makes zero pairs, hence zero windows — nothing
        # to fuse; the solo path returns its empty flow array
        if not windows:
            return None
        if len(windows) * windows[0].nbytes > self.AGG_MAX_BYTES:
            return None
        return windows[0].shape  # (B+1, Hp, Wp, 3)

    def dispatch_group(self, device, state, entries, payloads):
        from video_features_tpu.ops.window import pad_batch
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        group = max(int(self.config.video_batch or 1), 1)
        head0 = payloads[0][0]
        if isinstance(head0, tuple) and head0[0] == "dev":
            # per-window taps: each window resizes with its own video's
            # contract, so mixed resolutions sharing the agg key fuse.
            # pad_batch's zero taps feed zero frames to the pad windows,
            # whose outputs the [:g] slice drops anyway.
            flat_w, flat_taps, flat_n = [], [], []
            for p in payloads:
                _, wins, wy, wx = p[0]
                flat_w.extend(wins)
                flat_taps.extend([(wy, wx)] * len(wins))
                flat_n.extend(p[1])
            outs = []
            for i in range(0, len(flat_w), group):
                chunk = flat_w[i : i + group]
                taps = flat_taps[i : i + group]
                g = len(chunk)
                x = place_batch(
                    pad_batch(np.stack(chunk), group), state["device"]
                )
                wy_g = tuple(
                    pad_batch(np.stack([t[0][k] for t in taps]), group)
                    for k in (0, 1)
                )
                wx_g = tuple(
                    pad_batch(np.stack([t[1][k] for t in taps]), group)
                    for k in (0, 1)
                )
                outs.append(
                    (state["forward_raw_group"](state["params"], x, wy_g, wx_g), g)
                )
            metas = [(len(p[0][1]), p[2], p[3], p[4]) for p in payloads]
            return outs, flat_n, metas
        flat_w = [w for p in payloads for w in p[0]]
        flat_n = [n for p in payloads for n in p[1]]
        outs = []
        for i in range(0, len(flat_w), group):
            chunk = flat_w[i : i + group]
            g = len(chunk)
            x = pad_batch(np.stack(chunk), group)  # one executable per key
            x = pad_batch_for(state["device"], x)
            x = place_batch(x, state["device"])
            outs.append((state["forward_group"](state["params"], x), g))
        metas = [(len(p[0]), p[2], p[3], p[4]) for p in payloads]
        return outs, flat_n, metas

    def fetch_group(self, handle):
        outs, flat_n, metas = handle
        per_window: List[np.ndarray] = []
        i = 0
        for out, g in outs:
            arr = np.asarray(out)[:g]
            for w in arr:
                per_window.append(w[: flat_n[i]])
                i += 1
        dicts, off = [], 0
        for count, padder, fps, timestamps_ms in metas:
            flows: List[np.ndarray] = []
            for w in per_window[off : off + count]:
                flow = padder.unpad(w)
                flows.extend(np.transpose(flow, (0, 3, 1, 2)))
            off += count
            dicts.append(
                {
                    self.feature_type: np.array(flows),
                    "fps": np.array(fps),
                    "timestamps_ms": np.array(timestamps_ms),
                }
            )
        return dicts
