"""CLIP frame-feature extractor (ref models/CLIP/extract_clip.py).

Pipeline per video: ``fix_N``/``uni_N`` frame sampling (ref
utils/utils.py:297-333) -> PIL bicubic resize + center crop + CLIP
normalization on the host (byte-identical to the pip ``clip`` package's
``preprocess``) -> padded static-shape batch -> jit-compiled Flax
``encode_image`` on the device -> ``{feature_type, fps, timestamps_ms}``.

Returns T x 512 for ViT-B/32 / CLIP4CLIP, T x 512 for ViT-B/16 (ref
extract_clip.py:126-128; BASELINE.md CLIP contract).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
from PIL import Image

import jax
import jax.numpy as jnp

from video_features_tpu.extract import ingest
from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import extract_frames
from video_features_tpu.models.clip.convert import convert_state_dict
from video_features_tpu.models.clip.model import CONFIGS, VisionTransformer, init_params
from video_features_tpu.models.common.weights import (
    compute_dtype,
    load_params,
    random_init_fallback,
)
from video_features_tpu.ops.preprocess import (
    CLIP_MEAN,
    CLIP_STD,
    device_preprocess_frames,
    normalize_chw,
    pil_center_crop,
    pil_resize,
    to_float_chw,
)
from video_features_tpu.ops.resize import fused_resize_crop_banded
from video_features_tpu.ops.sampler import copy_forward, frame_delta_keep_mask
from video_features_tpu.ops.window import bucket_size, pad_batch, pad_hw, spatial_bucket


class ExtractCLIP(BaseExtractor):
    # --sharding mesh: Megatron-style TP over attention/MLP weights plus
    # data parallelism over the sampled-frame batch (parallel/sharding.py)
    mesh_capable = True
    mesh_tp_capable = True  # clip_vit_param_specs shard the 'model' axis
    mesh_context_capable = True  # ring attention over the patch-token axis

    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        if self.config.extract_method is None:
            raise ValueError(
                "CLIP extraction needs --extract_method (e.g. uni_12 or fix_2)"
            )
        self.model_cfg = CONFIGS[self.feature_type]
        self._host_params = None  # converted once, device_put per device

    def _load_host_params(self):
        # called under _build_lock (warmup serializes _build calls)
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path,
                    lambda sd: convert_state_dict(sd, self.model_cfg.layers),
                )
            else:
                random_init_fallback(
                    self.config, self.feature_type,
                    "an OpenAI CLIP / HF CLIP-vision state dict "
                    "(.pt/.npz) or a converted flax .msgpack",
                )
                self._host_params = init_params(self.model_cfg)
        return self._host_params

    def _build(self, device):
        from video_features_tpu.models.common.weights import (
            cast_floats_for_compute,
            compute_dtype,
        )
        from video_features_tpu.parallel.sharding import (
            build_sharded_apply,
            clip_vit_param_specs,
            is_mesh,
            place_params,
        )

        dt = compute_dtype(self.config)
        context = is_mesh(device) and self.config.mesh_context
        if context:
            from video_features_tpu.parallel.ring_attention import (
                make_context_parallel_core,
            )

            attn_core = make_context_parallel_core(device)
        elif self.config.attn == "flash":
            # --attn flash: the Pallas kernel on the REAL extraction path
            # (VERDICT r02 #8). Exact vs fused, so features are unchanged;
            # off-TPU backends run the kernel in interpreter mode.
            import functools

            from video_features_tpu.ops.pallas.flash_attention import (
                flash_attention,
            )

            attn_core = functools.partial(
                flash_attention, interpret=jax.default_backend() != "tpu"
            )
        elif self.config.attn == "blockwise":
            from video_features_tpu.ops.attention import blockwise_attention

            attn_core = blockwise_attention
        else:
            attn_core = None
        model = VisionTransformer(self.model_cfg, dtype=dt, attn_core=attn_core)
        from video_features_tpu.models.common.weights import (
            is_orbax_checkpoint,
            load_orbax,
        )

        def cast(params):
            if dt != jnp.float32:
                # final projection stays fp32 (the 512-d embedding contract)
                return cast_floats_for_compute(params, dt, exclude=("proj",))
            return params

        wp = self.config.weights_path
        if is_mesh(device):
            # one GSPMD-sharded executable: TP over attention/MLP weights,
            # plus either DP over the frame batch (default) or context
            # parallelism over the patch-token axis (--mesh_context: ring
            # attention, KV shards rotating over ICI; the batch replicates
            # and the token axis shards inside the model)
            from jax.sharding import PartitionSpec as P

            if wp and is_orbax_checkpoint(wp):
                # orbax + mesh: restore each weight DIRECTLY onto its
                # destination devices under the TP specs — no full-tree
                # host copy (multi-host-safe: each process reads only its
                # shards), then cast in place for --dtype
                params = cast(load_orbax(wp, device, clip_vit_param_specs))
            else:
                params = place_params(
                    cast(self._load_host_params()), device, clip_vit_param_specs
                )
            spec = P() if context else P("data")
            encode_image = build_sharded_apply(
                model, device, batch_spec=spec, out_spec=spec
            )
        else:
            params = jax.device_put(cast(self._load_host_params()), device)

            def encode_image(p, x):
                return model.apply({"params": p}, x)

            # the frame batch is freshly placed per dispatch, so the
            # entry donates it: XLA reuses the ingest HBM in place
            # (extract/ingest.py; CPU can't alias and keeps a copy)
            encode_image = ingest.jit_donated(encode_image, donate_argnums=(1,))

        state = {"params": params, "encode_image": encode_image,
                 "device": device, "pad_data": not context}
        if self._device_preprocess_enabled():
            # --preprocess device: raw uint8 HWC frames + the per-video
            # banded resize/crop taps enter as jit INPUTS, so one
            # executable serves every source resolution in a spatial
            # bucket. The fused program: resize+crop (two K-tap banded
            # passes) -> normalize -> encoder forward, one dispatch.
            def encode_raw(p, x_u8, wy, wx):
                x = device_preprocess_frames(
                    x_u8, wy, wx, CLIP_MEAN, CLIP_STD, out_dtype=dt
                )
                if x.ndim == 5:  # fused --video_batch group: (N, T, ...)
                    x = x.reshape((-1,) + x.shape[2:])
                return model.apply({"params": p}, x)

            if is_mesh(device):
                # mesh + device preprocess (sanity_check admits CLIP
                # only): the frame axis shards over 'data' — each shard
                # resizes and encodes its own frame slice, the taps
                # replicate. Explicit in/out shardings are the GC502
                # contract: params inherit their TP placement (None),
                # frames split over 'data' (place_raw_payload padded the
                # axis divisible pre-split), taps replicate.
                from jax.sharding import NamedSharding, PartitionSpec
                from video_features_tpu.parallel.sharding import (
                    _mesh_out_sharding,
                )

                batch_sh = NamedSharding(device, PartitionSpec("data"))
                rep = NamedSharding(device, PartitionSpec())
                encode_raw = jax.jit(
                    encode_raw,
                    in_shardings=(None, batch_sh, (rep, rep), (rep, rep)),
                    out_shardings=_mesh_out_sharding(
                        device, PartitionSpec("data")
                    ),
                )
            else:
                # donate the raw uint8 frames (freshly placed per
                # dispatch by transfer_group / place_raw_payload); the
                # lru_cached resize taps are NOT donated — they are
                # reused across every video sharing a source resolution
                encode_raw = ingest.jit_donated(encode_raw, donate_argnums=(1,))
            state["encode_raw"] = encode_raw
        return state

    def _preprocess(self, frame: np.ndarray) -> np.ndarray:
        size = self.model_cfg.image_size
        img = pil_resize(frame, size, interpolation=Image.BICUBIC)
        img = pil_center_crop(img, size)
        return normalize_chw(to_float_chw(img), CLIP_MEAN, CLIP_STD)

    def _preprocess_frames(self, frames) -> np.ndarray:
        """Sampled frames -> (T, 3, size, size). ``--host_preprocess
        native`` routes through the C++ BICUBIC chain (one call for the
        whole batch, ~1/255/pixel of PIL); 'pil' is the pip-``clip``-exact
        path. Backend decided once (BaseExtractor._native_decided)."""
        if self._native_decided():
            from video_features_tpu import native

            return native.clip_preprocess_batch(
                np.stack(frames),
                size=self.model_cfg.image_size,
                threads=self._native_threads,
            )
        return np.stack([self._preprocess(f) for f in frames])

    # host half: decode + preprocess + static-shape pad (runs on
    # --decode_workers threads under the async pipeline)
    def prepare(self, path_entry):
        video_path = video_path_of(path_entry)
        frames, fps, timestamps_ms = extract_frames(
            video_path, self.config.extract_method, self.config.decoder
        )
        # --frame_delta_threshold: drop near-duplicate sampled frames on
        # the host, BEFORE padding/H2D; the fetch path copy-forwards
        # their feature rows back onto the full grid. ``keep=None``
        # means the gate is off or kept everything — the payload (and
        # therefore the features) is then bit-identical to an ungated
        # run.
        keep = None
        thr = getattr(self.config, "frame_delta_threshold", None)
        if thr is not None:
            mask = frame_delta_keep_mask(frames, float(thr))
            skipped = int(mask.size - int(mask.sum()))
            if skipped:
                self._note_windows_skipped(path_entry, skipped, int(mask.size))
                keep = mask
                frames = [f for f, k in zip(frames, mask) if k]
        if self._device_preprocess_enabled():
            # raw uint8 HWC frames, padded (time bucket x spatial bucket);
            # resize/crop/normalize happens inside encode_raw on-device.
            # Payload slot 0 is the (frames, (wt_y, idx_y), (wt_x, idx_x))
            # triple — the banded taps are lru_cached per source
            # resolution, so a corpus pays the host tap construction once
            # per (h, w).
            arr = np.stack(frames)  # (T, H, W, 3) uint8
            T, h, w = arr.shape[:3]
            bh, bw = spatial_bucket(h, w, self.config.spatial_bucket)
            size = self.model_cfg.image_size
            wt_y, idx_y, wt_x, idx_x = fused_resize_crop_banded(
                h, w, size, size, "bicubic", pad_h=bh, pad_w=bw
            )
            arr = pad_batch(arr, bucket_size(T, buckets=self.config.shape_buckets))
            arr = pad_hw(arr, bh, bw)
            return (arr, (wt_y, idx_y), (wt_x, idx_x)), T, fps, timestamps_ms, keep
        batch = self._preprocess_frames(frames)  # (T, 3, H, W)
        T = batch.shape[0]
        padded = pad_batch(batch, bucket_size(T, buckets=self.config.shape_buckets))
        if compute_dtype(self.config) != jnp.float32:
            # pre-cast on the host (decode-worker) thread: the ViT's first
            # conv casts inputs to bf16 anyway, so numerics are identical,
            # and the host->device transfer halves — which matters when
            # dispatch rides a tunnel/DCN
            import ml_dtypes

            padded = padded.astype(ml_dtypes.bfloat16)
        return padded, T, fps, timestamps_ms, keep

    # device half, split for the device pipeline (extract/base.py): enqueue
    # transfer + async forward, fetch later — video k+1's transfer/compute
    # overlaps video k's result fetch
    def _place(self, state, padded):
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        if state.get("pad_data", True):  # mesh DP: /data-divisible batch
            padded = pad_batch_for(state["device"], padded)
            return place_batch(padded, state["device"])
        # mesh_context: batch replicates, tokens shard in-model
        from jax.sharding import PartitionSpec as P

        return place_batch(padded, state["device"], spec=P())

    def dispatch_prepared(self, device, state, path_entry, payload):
        padded, T, fps, timestamps_ms, keep = payload
        if isinstance(padded, tuple):  # --preprocess device
            from video_features_tpu.parallel.sharding import place_raw_payload

            x_u8, wy, wx = place_raw_payload(padded, state["device"])
            out = state["encode_raw"](state["params"], x_u8, wy, wx)
            return out, T, fps, timestamps_ms, keep
        x = self._place(state, padded)
        return state["encode_image"](state["params"], x), T, fps, timestamps_ms, keep

    def fetch_dispatched(self, handle) -> Dict[str, np.ndarray]:
        out, T, fps, timestamps_ms, keep = handle
        feats = np.asarray(out)[:T]
        if keep is not None:  # gated: expand kept rows to the full grid
            feats = copy_forward(feats, keep)
        return {
            self.feature_type: feats,
            "fps": np.array(fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    # --- cross-video aggregation (--video_batch): N videos' sampled-frame
    # batches concatenate into ONE (N*bucket)-image encode_image call;
    # features slice apart per video on fetch. A lone uni_12 batch (12-16
    # images) leaves the MXU ~idle — the fused batch is what fills it.
    # Above AGG_MAX_FRAMES sampled frames (fix_N over a long video), a
    # video dispatches alone: N-1 such payloads waiting host-side plus an
    # N-fold fused transfer is the OOM shape the cap exists to avoid.
    AGG_MAX_FRAMES = 256

    def agg_key(self, payload):
        head = payload[0]
        if isinstance(head, tuple):  # --preprocess device: bucketed uint8
            if self.config.sharding == "mesh":
                # mesh already spreads ONE video's frame axis over
                # 'data'; cross-video fusion would stack an N axis the
                # encode_raw in_shardings contract does not cover
                return None
            if head[0].shape[0] > self.AGG_MAX_FRAMES:
                return None
            # the spatial bucket rides the key via the frame shape, so
            # mixed-resolution videos fuse exactly when they share a
            # (T_pad, bucket_h, bucket_w) executable
            return ("dev", head[0].shape)
        if head.shape[0] > self.AGG_MAX_FRAMES:
            return None
        return head.shape  # the bucketed (T_pad, 3, H, W) shape

    def transfer_group(self, device, state, entries, payloads):
        """The dedicated H2D stage of the async ingest pipeline: stack
        the group's host arrays and device_put them NOW (under the
        loop's ``h2d`` span), so the fused forward in
        ``dispatch_group`` enqueues against already-staged buffers —
        and those buffers, fresh per group, are what the donated
        entries (``donate_argnums``) let XLA reuse in place."""
        group = max(int(self.config.video_batch or 1), 1)
        head = payloads[0][0]
        if isinstance(head, tuple):  # --preprocess device: per-video
            # frames AND taps stack — each video keeps its own source
            # resolution's taps inside the shared bucket executable (K is
            # bucket-stable, so the tap arrays agree in shape)
            bucket = head[0].shape[0]
            xs = np.stack([p[0][0] for p in payloads])
            wys = tuple(np.stack([p[0][1][j] for p in payloads]) for j in range(2))
            wxs = tuple(np.stack([p[0][2][j] for p in payloads]) for j in range(2))
            if len(payloads) < group:  # partial flush: keep the shape
                xs = pad_batch(xs, group)
                wys = tuple(pad_batch(a, group) for a in wys)
                wxs = tuple(pad_batch(a, group) for a in wxs)
            from video_features_tpu.parallel.sharding import place_raw_payload

            # mesh never groups (agg_key returns None there), so this is
            # always the plain queue-mode device_put of the fused tuple
            placed = place_raw_payload((xs, wys, wxs), state["device"])
            metas = [
                (i * bucket, p[1], p[2], p[3], p[4])
                for i, p in enumerate(payloads)
            ]
            return ingest.StagedGroup(placed, metas)
        bucket = head.shape[0]
        x = np.concatenate([p[0] for p in payloads], axis=0)
        if len(payloads) < group:  # partial flush: keep the compiled shape
            x = pad_batch(x, group * bucket)
        metas = [
            (i * bucket, p[1], p[2], p[3], p[4]) for i, p in enumerate(payloads)
        ]
        return ingest.StagedGroup((self._place(state, x),), metas)

    def dispatch_group(self, device, state, entries, payloads):
        if not isinstance(payloads, ingest.StagedGroup):
            # direct callers (and any path skipping the transfer stage)
            # still get the assemble+place+dispatch composition
            payloads = self.transfer_group(device, state, entries, payloads)
        arrays, metas = payloads.arrays, payloads.metas
        if len(arrays) == 3:  # --preprocess device: fused raw entry
            xs, wys, wxs = arrays
            out = state["encode_raw"](state["params"], xs, wys, wxs)
        else:
            out = state["encode_image"](state["params"], arrays[0])
        return out, metas

    def fetch_group(self, handle):
        out, metas = handle
        arr = np.asarray(out)
        dicts = []
        for off, t, fps, ts, keep in metas:
            feats = arr[off : off + t]
            if keep is not None:  # gated: expand to the full grid
                feats = copy_forward(feats, keep)
            dicts.append(
                {
                    self.feature_type: feats,
                    "fps": np.array(fps),
                    "timestamps_ms": np.array(ts),
                }
            )
        return dicts
