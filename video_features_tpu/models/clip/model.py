"""CLIP visual transformer in Flax.

The reference consumes OpenAI's pip ``clip`` package (``clip.load`` at ref
models/CLIP/extract_clip.py:46-63) and only ever calls
``model.encode_image`` (ref :128). This module is that encoder rebuilt
TPU-first: NHWC patchify conv, fused qkv attention einsums in fp32 MXU
precision, QuickGELU MLPs, and a projection head — one jit-compiled
function per device, batch = sampled frames.

Matches OpenAI ViT-B/32 / B/16 semantics: pre-LN transformer, QuickGELU
(x * sigmoid(1.702x)), LayerNorm eps 1e-5, class token + learned position
embeddings, ln_post on the class token, then ``@ proj`` to the embed dim.
CLIP4CLIP-ViT-B-32 (ref :58-63) is the same graph with a fine-tuned
checkpoint.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from video_features_tpu.ops.attention import attention as fused_attention

HIGHEST = jax.lax.Precision.HIGHEST


@dataclasses.dataclass(frozen=True)
class CLIPVisionConfig:
    patch_size: int = 32
    width: int = 768
    layers: int = 12
    heads: int = 12
    embed_dim: int = 512
    image_size: int = 224
    quick_gelu: bool = True
    eps: float = 1e-5

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size


CLIP_VIT_B32 = CLIPVisionConfig(patch_size=32)
CLIP_VIT_B16 = CLIPVisionConfig(patch_size=16)

CONFIGS = {
    "CLIP-ViT-B/32": CLIP_VIT_B32,
    "CLIP-ViT-B/16": CLIP_VIT_B16,
    "CLIP4CLIP-ViT-B-32": CLIP_VIT_B32,
}


def quick_gelu(x: jnp.ndarray) -> jnp.ndarray:
    return x * jax.nn.sigmoid(1.702 * x)


class Attention(nn.Module):
    """Multi-head self-attention with a swappable core.

    ``attn_core(q, k, v) -> out`` on (N, H, L, hd) tensors replaces the
    fused full-score-matrix core (ops/attention.py semantics). The mesh
    ``--mesh_context`` path injects ring attention here
    (parallel/ring_attention.py::make_context_parallel_core): the token
    axis shards over the mesh and KV shards rotate over ICI."""

    width: int
    heads: int
    dtype: jnp.dtype = jnp.float32
    attn_core: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # (N, L, D)
        N, L, D = x.shape
        hd = self.width // self.heads
        q = nn.Dense(self.width, dtype=self.dtype, name="q_proj")(x)
        k = nn.Dense(self.width, dtype=self.dtype, name="k_proj")(x)
        v = nn.Dense(self.width, dtype=self.dtype, name="v_proj")(x)
        q = q.reshape(N, L, self.heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(N, L, self.heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(N, L, self.heads, hd).transpose(0, 2, 1, 3)
        core = self.attn_core if self.attn_core is not None else fused_attention
        out = core(q, k, v)
        out = out.transpose(0, 2, 1, 3).reshape(N, L, D)
        return nn.Dense(self.width, dtype=self.dtype, name="out_proj")(out)


class Block(nn.Module):
    width: int
    heads: int
    quick_gelu: bool
    eps: float
    dtype: jnp.dtype = jnp.float32
    attn_core: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # LayerNorm statistics stay fp32 under --dtype bfloat16; the
        # residual stream and the MXU matmuls run in self.dtype
        act = quick_gelu if self.quick_gelu else nn.gelu
        y = nn.LayerNorm(epsilon=self.eps, dtype=jnp.float32, name="ln_1")(x)
        y = y.astype(self.dtype)
        x = x + Attention(self.width, self.heads, self.dtype,
                          self.attn_core, name="attn")(y)
        y = nn.LayerNorm(epsilon=self.eps, dtype=jnp.float32, name="ln_2")(x)
        y = y.astype(self.dtype)
        y = nn.Dense(self.width * 4, dtype=self.dtype, name="c_fc")(y)
        y = nn.Dense(self.width, dtype=self.dtype, name="c_proj")(act(y))
        return x + y


class VisionTransformer(nn.Module):
    """``encode_image``: (N, 3, H, W) normalized fp32 -> (N, embed_dim).

    ``dtype=jnp.bfloat16`` runs the residual stream and every MXU matmul
    in bf16 (params should be cast with ``cast_floats_for_compute``);
    LayerNorm statistics, attention softmax, and the final projection
    stay fp32. Output is always fp32."""

    cfg: CLIPVisionConfig
    dtype: jnp.dtype = jnp.float32
    # optional swapped attention core, e.g. context-parallel ring
    # attention under --sharding mesh --mesh_context (parity: the core is
    # mathematically exact, so converted OpenAI weights are unaffected)
    attn_core: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = self.cfg
        N = x.shape[0]
        x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW -> NHWC (TPU-native layout)
        x = nn.Conv(
            c.width,
            (c.patch_size, c.patch_size),
            strides=(c.patch_size, c.patch_size),
            use_bias=False,
            padding="VALID",
            dtype=self.dtype,
            name="conv1",
        )(x)
        x = x.reshape(N, -1, c.width)  # (N, grid*grid, width)

        cls = self.param(
            "class_embedding", nn.initializers.normal(c.width ** -0.5), (c.width,)
        )
        pos = self.param(
            "positional_embedding",
            nn.initializers.normal(c.width ** -0.5),
            (c.grid * c.grid + 1, c.width),
        )
        x = jnp.concatenate([jnp.tile(cls[None, None], (N, 1, 1)).astype(x.dtype), x], axis=1)
        x = (x + pos[None]).astype(self.dtype)
        x = nn.LayerNorm(epsilon=c.eps, dtype=jnp.float32, name="ln_pre")(x)
        x = x.astype(self.dtype)
        for i in range(c.layers):
            x = Block(c.width, c.heads, c.quick_gelu, c.eps, self.dtype,
                      self.attn_core, name=f"resblock_{i}")(x)
        x = nn.LayerNorm(epsilon=c.eps, dtype=jnp.float32, name="ln_post")(x[:, 0])
        proj = self.param(
            "proj", nn.initializers.normal(c.width ** -0.5), (c.width, c.embed_dim)
        )
        # fp32 projection regardless of dtype: the 512-d embedding is the
        # user-facing contract
        return jnp.dot(x.astype(jnp.float32), proj.astype(jnp.float32),
                       precision=HIGHEST)


def init_params(cfg: CLIPVisionConfig, seed: int = 0):
    model = VisionTransformer(cfg)
    dummy = jnp.zeros((1, 3, cfg.image_size, cfg.image_size), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)["params"]
