"""Checkpoint converters into the Flax CLIP visual tower.

Two source formats:

- OpenAI ``clip`` checkpoints — what the reference loads via ``clip.load``
  (ref models/CLIP/extract_clip.py:46-63), including CLIP4CLIP fine-tunes
  saved in the same naming (``visual.transformer.resblocks.*``; fused
  ``attn.in_proj_weight``). Text-tower tensors are ignored: the reference
  only ever calls ``encode_image``.
- HuggingFace ``CLIPVisionModelWithProjection`` state dicts
  (``vision_model.encoder.layers.*`` with split q/k/v) — the practical
  offline weight source, and the torch oracle used by the parity tests.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from video_features_tpu.models.common.weights import (
    check_all_consumed,
    conv2d_kernel,
    transpose_linear,
)


def _ln(sd, name):
    return {"scale": sd[f"{name}.weight"], "bias": sd[f"{name}.bias"]}


def _dense(sd, name):
    return {"kernel": transpose_linear(sd[f"{name}.weight"]), "bias": sd[f"{name}.bias"]}


def from_openai(sd: Dict[str, np.ndarray], layers: int = 12) -> Dict:
    """OpenAI clip state dict (full model or visual-only) -> flax params."""
    v = {k: np.asarray(val, np.float32) for k, val in sd.items() if k.startswith("visual.")}
    if not v:
        raise ValueError("no 'visual.*' tensors found — not an OpenAI CLIP checkpoint?")
    consumed = set()

    def take(key):
        consumed.add(f"visual.{key}")
        return v[f"visual.{key}"]

    params = {
        "class_embedding": take("class_embedding"),
        "positional_embedding": take("positional_embedding"),
        "proj": take("proj"),
        "conv1": {"kernel": conv2d_kernel(take("conv1.weight"))},
        "ln_pre": {"scale": take("ln_pre.weight"), "bias": take("ln_pre.bias")},
        "ln_post": {"scale": take("ln_post.weight"), "bias": take("ln_post.bias")},
    }
    for i in range(layers):
        p = f"transformer.resblocks.{i}"
        in_w = take(f"{p}.attn.in_proj_weight")  # (3D, D)
        in_b = take(f"{p}.attn.in_proj_bias")
        D = in_w.shape[1]
        qw, kw, vw = in_w[:D], in_w[D : 2 * D], in_w[2 * D :]
        qb, kb, vb = in_b[:D], in_b[D : 2 * D], in_b[2 * D :]
        params[f"resblock_{i}"] = {
            "ln_1": {"scale": take(f"{p}.ln_1.weight"), "bias": take(f"{p}.ln_1.bias")},
            "ln_2": {"scale": take(f"{p}.ln_2.weight"), "bias": take(f"{p}.ln_2.bias")},
            "attn": {
                "q_proj": {"kernel": transpose_linear(qw), "bias": qb},
                "k_proj": {"kernel": transpose_linear(kw), "bias": kb},
                "v_proj": {"kernel": transpose_linear(vw), "bias": vb},
                "out_proj": {
                    "kernel": transpose_linear(take(f"{p}.attn.out_proj.weight")),
                    "bias": take(f"{p}.attn.out_proj.bias"),
                },
            },
            "c_fc": {
                "kernel": transpose_linear(take(f"{p}.mlp.c_fc.weight")),
                "bias": take(f"{p}.mlp.c_fc.bias"),
            },
            "c_proj": {
                "kernel": transpose_linear(take(f"{p}.mlp.c_proj.weight")),
                "bias": take(f"{p}.mlp.c_proj.bias"),
            },
        }
    check_all_consumed(v, consumed, "CLIP-visual(openai)")
    return params


def from_hf_vision(sd: Dict[str, np.ndarray], layers: int = 12) -> Dict:
    """HF CLIPVisionModelWithProjection state dict -> flax params.

    Full ``CLIPModel`` checkpoints work too: text-tower tensors are
    filtered out up front, mirroring ``from_openai``'s visual-only filter.
    """
    sd = {
        k: np.asarray(val, np.float32)
        for k, val in sd.items()
        if k.startswith(("vision_model.", "visual_projection."))
    }
    if not sd:
        raise ValueError("no 'vision_model.*' tensors found — not an HF CLIP checkpoint?")
    consumed = set()

    def take(key):
        consumed.add(key)
        return sd[key]

    emb = "vision_model.embeddings"
    params = {
        "class_embedding": take(f"{emb}.class_embedding"),
        "positional_embedding": take(f"{emb}.position_embedding.weight"),
        "proj": transpose_linear(take("visual_projection.weight")),
        "conv1": {"kernel": conv2d_kernel(take(f"{emb}.patch_embedding.weight"))},
        # yes, HF really spells it 'pre_layrnorm'
        "ln_pre": _ln_take(take, "vision_model.pre_layrnorm"),
        "ln_post": _ln_take(take, "vision_model.post_layernorm"),
    }
    for i in range(layers):
        p = f"vision_model.encoder.layers.{i}"
        params[f"resblock_{i}"] = {
            "ln_1": _ln_take(take, f"{p}.layer_norm1"),
            "ln_2": _ln_take(take, f"{p}.layer_norm2"),
            "attn": {
                name: {
                    "kernel": transpose_linear(take(f"{p}.self_attn.{name}.weight")),
                    "bias": take(f"{p}.self_attn.{name}.bias"),
                }
                for name in ("q_proj", "k_proj", "v_proj", "out_proj")
            },
            "c_fc": {
                "kernel": transpose_linear(take(f"{p}.mlp.fc1.weight")),
                "bias": take(f"{p}.mlp.fc1.bias"),
            },
            "c_proj": {
                "kernel": transpose_linear(take(f"{p}.mlp.fc2.weight")),
                "bias": take(f"{p}.mlp.fc2.bias"),
            },
        }
    # position_ids is a buffer, not a weight
    consumed.add(f"{emb}.position_ids")
    check_all_consumed(sd, consumed, "CLIP-visual(hf)")
    return params


def _ln_take(take, name):
    return {"scale": take(f"{name}.weight"), "bias": take(f"{name}.bias")}


def convert_state_dict(sd: Dict[str, np.ndarray], layers: int = 12) -> Dict:
    """Auto-detect the checkpoint flavor."""
    if any(k.startswith("visual.") for k in sd):
        return from_openai(sd, layers)
    if any(k.startswith("vision_model.") for k in sd):
        return from_hf_vision(sd, layers)
    raise ValueError("unrecognized CLIP checkpoint format")
