"""ResNet frame-feature extractor (ref models/resnet/extract_resnet.py).

Per video: streaming cv2 decode (optionally on an ``--extraction_fps``
grid — done in-process, no ffmpeg re-encode subprocess), torchvision
Resize(256)/CenterCrop(224)/Normalize on the host, frames batched to the
static ``--batch_size`` shape (partial tail batches are zero-padded so XLA
compiles exactly one executable), jit forward returning features AND
logits in one pass, ``--show_pred`` printing top-5 ImageNet classes
(ref extract_resnet.py:112-114, utils/utils.py:19-46).

Output contract: ``{resnetXX: (T, feat_dim), fps, timestamps_ms}``
(ref extract_resnet.py:162-167); 2048-d for resnet50+ (BASELINE.md).
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import stream_frames
from video_features_tpu.models.common.weights import load_params, random_init_fallback
from video_features_tpu.models.resnet.convert import convert_state_dict
from video_features_tpu.models.resnet.model import build, init_params
from video_features_tpu.ops.preprocess import imagenet_preprocess
from video_features_tpu.utils.labels import show_predictions_on_dataset


class ExtractResNet(BaseExtractor):
    # --sharding mesh: pure data parallelism — conv weights replicate,
    # the frame-batch axis shards over 'data' (parallel/sharding.py)
    mesh_capable = True

    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.batch_size = max(int(self.config.batch_size or 1), 1)
        self._host_params = None

    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path,
                    lambda sd: convert_state_dict(sd, self.feature_type),
                )
            else:
                random_init_fallback(
                    self.config, self.feature_type,
                    f"a torchvision {self.feature_type} state dict "
                    "(.pt/.pth) or a converted flax .msgpack",
                )
                self._host_params = init_params(self.feature_type)
        return self._host_params

    def _build(self, device):
        from video_features_tpu.models.common.weights import (
            cast_floats_for_compute,
            compute_dtype,
        )

        from video_features_tpu.parallel.sharding import (
            jit_sharded_forward,
            place_params,
        )

        dt = compute_dtype(self.config)
        model = build(self.feature_type, dtype=dt)
        params = self._load_host_params()
        if dt != jnp.float32:
            params = cast_floats_for_compute(params, dt, exclude=("fc",))
        params = place_params(params, device)  # mesh: replicated (DP)

        def forward(p, x):
            return model.apply({"params": p}, x)

        forward = jit_sharded_forward(forward, device, n_out=2)
        return {"params": params, "forward": forward, "device": device}

    def _preprocess_batch(self, batch: List[np.ndarray]) -> np.ndarray:
        """raw uint8 HWC frames -> (n, 3, 224, 224) normalized float32.

        'native' routes through the threaded C++ chain (same-resolution
        frames batched in one call); 'pil' is the reference-exact path.
        Backend decided once (BaseExtractor._native_decided)."""
        if self._native_decided():
            from video_features_tpu import native

            return native.imagenet_preprocess_batch(
                np.stack(batch), threads=self._native_threads
            )
        return np.stack([imagenet_preprocess(f) for f in batch])

    # A prepared video holds preprocessed fp32 224x224 frames (~600 KB
    # each); the pipeline keeps decode_workers+2 prepared videos resident,
    # so the guard is a byte budget split over those slots (advisor r02:
    # a flat frame cap scaled host RAM with the worker count). Over-cap
    # videos hand decode back to the device thread as a stream.
    PIPELINE_MAX_BYTES = 4 << 30
    _FRAME_BYTES = 3 * 224 * 224 * 4

    @property
    def PIPELINE_MAX_FRAMES(self) -> int:
        return self._prefetch_frame_cap(
            self.PIPELINE_MAX_BYTES, self._FRAME_BYTES, floor=64
        )

    # host half: stream-decode + preprocess into padded static-shape
    # batches (runs on --decode_workers threads under the async pipeline)
    def prepare(self, path_entry):
        video_path = video_path_of(path_entry)
        fps = self.config.extraction_fps
        decode_path, sel_fps = self._fps_source(video_path)
        batch: List[np.ndarray] = []
        batches: List[np.ndarray] = []
        counts: List[int] = []
        timestamps_ms: List[float] = []

        def flush():
            n = len(batch)
            x = self._preprocess_batch(batch)
            if n < self.batch_size:
                x = np.pad(x, [(0, self.batch_size - n)] + [(0, 0)] * 3)
            batches.append(x)
            counts.append(n)

        n_frames = 0
        for frame, ts in stream_frames(decode_path, sel_fps, self.config.decoder):
            n_frames += 1
            if n_frames > self.PIPELINE_MAX_FRAMES:
                # hand the (possibly re-encoded) decode source over, with
                # the matching selection fps
                return ("stream", (decode_path, sel_fps))
            batch.append(frame)
            timestamps_ms.append(ts)
            if len(batch) == self.batch_size:
                flush()
                batch = []
        if batch:
            flush()
        if not batches:
            raise IOError(f"no frames decoded from {video_path}")
        from video_features_tpu.io.video import probe

        actual_fps = fps or probe(video_path, self.config.decoder).fps or 25.0
        return batches, counts, actual_fps, timestamps_ms

    def _extract_streaming(self, state, source) -> Dict[str, np.ndarray]:
        """Bounded-memory fallback: decode/preprocess one batch at a time
        on the consuming thread (the round-1 behavior; no video-level
        prefetch, but host memory stays at one batch). ``source`` is
        prepare's (decode_path, selection_fps) — already past the
        --fps_retarget policy."""
        video_path, sel_fps = source
        fps = self.config.extraction_fps
        batch: List[np.ndarray] = []
        feats_out: List[np.ndarray] = []
        timestamps_ms: List[float] = []

        def run(batch):
            from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

            n = len(batch)
            x = self._preprocess_batch(batch)
            if n < self.batch_size:
                x = np.pad(x, [(0, self.batch_size - n)] + [(0, 0)] * 3)
            x = pad_batch_for(state["device"], x)
            x = place_batch(x, state["device"])
            feats, logits = state["forward"](state["params"], x)
            feats_out.append(np.asarray(feats)[:n])
            if self.config.show_pred:
                show_predictions_on_dataset(np.asarray(logits)[:n], "imagenet")

        for frame, ts in stream_frames(video_path, sel_fps, self.config.decoder):
            batch.append(frame)
            timestamps_ms.append(ts)
            if len(batch) == self.batch_size:
                run(batch)
                batch = []
        if batch:
            run(batch)
        if not feats_out:
            raise IOError(f"no frames decoded from {video_path}")
        from video_features_tpu.io.video import probe

        actual_fps = fps or probe(video_path, self.config.decoder).fps or 25.0
        return {
            self.feature_type: np.concatenate(feats_out, axis=0),
            "fps": np.array(actual_fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    # device half: transfer + jitted forward per batch
    # split for the device pipeline (extract/base.py): all frame batches
    # dispatch async, results fetched while the next video transfers.
    # The too-big-to-prefetch "stream" fallback cannot defer (it decodes
    # interleaved with compute), so it completes eagerly at dispatch and
    # fetch passes the ready dict through.
    def dispatch_prepared(self, device, state, path_entry, payload):
        if payload[0] == "stream":
            return ("done", self._extract_streaming(state, payload[1]))
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        batches, counts, actual_fps, timestamps_ms = payload
        outs = []
        for x, n in zip(batches, counts):
            x = pad_batch_for(state["device"], x)
            x = place_batch(x, state["device"])
            feats, logits = state["forward"](state["params"], x)
            # drop the 1000-class logits unless show_pred needs them —
            # the handle pins its buffers until fetch
            outs.append((feats, logits if self.config.show_pred else None, n))
        return "batched", outs, actual_fps, timestamps_ms

    def fetch_dispatched(self, handle) -> Dict[str, np.ndarray]:
        if handle[0] == "done":
            return handle[1]
        _, outs, actual_fps, timestamps_ms = handle
        feats_out: List[np.ndarray] = []
        for feats, logits, n in outs:
            feats_out.append(np.asarray(feats)[:n])
            if logits is not None:
                show_predictions_on_dataset(np.asarray(logits)[:n], "imagenet")
        return {
            self.feature_type: np.concatenate(feats_out, axis=0),
            "fps": np.array(actual_fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    # --- cross-video aggregation (--video_batch): the valid frames of N
    # videos re-chunk into (N*batch_size)-row forwards — short videos whose
    # lone tail batch would waste most of its pad rows share a dispatch.
    # Large videos (> AGG_MAX_FRAMES valid rows resident while a group
    # fills) and show_pred (per-video print interleaving) keep the
    # individual path via agg_key=None.
    AGG_MAX_FRAMES = 512

    def agg_key(self, payload):
        if payload[0] == "stream" or self.config.show_pred:
            return None
        batches, counts, _, _ = payload
        if sum(counts) > self.AGG_MAX_FRAMES:
            return None
        return batches[0].shape  # (batch_size, 3, 224, 224)

    def dispatch_group(self, device, state, entries, payloads):
        group = max(int(self.config.video_batch or 1), 1)
        rows, totals = [], []
        for batches, counts, _, _ in payloads:
            rows.extend(x[:n] for x, n in zip(batches, counts))
            totals.append(sum(counts))
        outs = self._dispatch_rows_grouped(state, rows, self.batch_size * group)
        return outs, totals, [(p[2], p[3]) for p in payloads]

    def fetch_group(self, handle):
        outs, totals, metas = handle
        return [
            {
                self.feature_type: feats,
                "fps": np.array(fps),
                "timestamps_ms": np.array(ts),
            }
            for feats, (fps, ts) in zip(self._split_grouped_rows(outs, totals), metas)
        ]
