"""ResNet frame-feature extractor (ref models/resnet/extract_resnet.py).

Per video: streaming cv2 decode (optionally on an ``--extraction_fps``
grid — done in-process, no ffmpeg re-encode subprocess), torchvision
Resize(256)/CenterCrop(224)/Normalize on the host, frames batched to the
static ``--batch_size`` shape (partial tail batches are zero-padded so XLA
compiles exactly one executable), jit forward returning features AND
logits in one pass, ``--show_pred`` printing top-5 ImageNet classes
(ref extract_resnet.py:112-114, utils/utils.py:19-46).

Output contract: ``{resnetXX: (T, feat_dim), fps, timestamps_ms}``
(ref extract_resnet.py:162-167); 2048-d for resnet50+ (BASELINE.md).
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import stream_frames
from video_features_tpu.models.common.weights import load_params
from video_features_tpu.models.resnet.convert import convert_state_dict
from video_features_tpu.models.resnet.model import build, init_params
from video_features_tpu.ops.preprocess import imagenet_preprocess
from video_features_tpu.utils.labels import show_predictions_on_dataset


class ExtractResNet(BaseExtractor):
    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.batch_size = max(int(self.config.batch_size or 1), 1)
        self._host_params = None
        self._use_native = None  # decided (with one-time warning) on first batch
        self._native_threads = 1

    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path,
                    lambda sd: convert_state_dict(sd, self.feature_type),
                )
            else:
                self._host_params = init_params(self.feature_type)
        return self._host_params

    def _build(self, device):
        model = build(self.feature_type)
        params = jax.device_put(self._load_host_params(), device)

        @jax.jit
        def forward(p, x):
            return model.apply({"params": p}, x)

        return {"params": params, "forward": forward, "device": device}

    def _preprocess_batch(self, batch: List[np.ndarray]) -> np.ndarray:
        """raw uint8 HWC frames -> (n, 3, 224, 224) normalized float32.

        'native' routes through the threaded C++ chain (same-resolution
        frames batched in one call); 'pil' is the reference-exact path.
        The backend decision (and any unavailability warning) happens once."""
        if self._use_native is None:
            if self.config.host_preprocess == "native":
                from video_features_tpu import native

                self._use_native = native.available()
                if not self._use_native:
                    print(
                        f"native preprocess unavailable "
                        f"({native.build_error()}); using PIL"
                    )
                else:
                    # share host cores across concurrent device workers
                    from video_features_tpu.parallel.devices import resolve_devices

                    n_workers = max(len(resolve_devices(self.config)), 1)
                    self._native_threads = max(
                        (os.cpu_count() or 1) // n_workers, 1
                    )
            else:
                self._use_native = False
        if self._use_native:
            from video_features_tpu import native

            return native.imagenet_preprocess_batch(
                np.stack(batch), threads=self._native_threads
            )
        return np.stack([imagenet_preprocess(f) for f in batch])

    def _run_batch(self, state, batch: List[np.ndarray], feats_out: List[np.ndarray]):
        """Pad to the static batch size, run, keep the valid rows
        (ref extract_resnet.py:104-116)."""
        n = len(batch)
        x = self._preprocess_batch(batch)
        if n < self.batch_size:
            x = np.pad(x, [(0, self.batch_size - n)] + [(0, 0)] * 3)
        x = jax.device_put(jnp.asarray(x), state["device"])
        feats, logits = state["forward"](state["params"], x)
        feats_out.append(np.asarray(feats)[:n])
        if self.config.show_pred:
            show_predictions_on_dataset(np.asarray(logits)[:n], "imagenet")

    def extract(self, device, state, path_entry) -> Dict[str, np.ndarray]:
        video_path = video_path_of(path_entry)
        fps = self.config.extraction_fps
        batch: List[np.ndarray] = []
        feats_out: List[np.ndarray] = []
        timestamps_ms: List[float] = []
        actual_fps = None
        for frame, ts in stream_frames(video_path, fps):
            batch.append(frame)  # raw uint8; preprocessing happens per batch
            timestamps_ms.append(ts)
            if len(batch) == self.batch_size:
                self._run_batch(state, batch, feats_out)
                batch = []
        if batch:
            self._run_batch(state, batch, feats_out)
        if not feats_out:
            raise IOError(f"no frames decoded from {video_path}")
        if actual_fps is None:
            from video_features_tpu.io.video import probe

            actual_fps = fps or probe(video_path).fps or 25.0
        return {
            self.feature_type: np.concatenate(feats_out, axis=0),
            "fps": np.array(actual_fps),
            "timestamps_ms": np.array(timestamps_ms),
        }
