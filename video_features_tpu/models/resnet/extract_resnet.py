"""ResNet frame-feature extractor (ref models/resnet/extract_resnet.py).

Per video: streaming cv2 decode (optionally on an ``--extraction_fps``
grid — done in-process, no ffmpeg re-encode subprocess), torchvision
Resize(256)/CenterCrop(224)/Normalize on the host, frames batched to the
static ``--batch_size`` shape (partial tail batches are zero-padded so XLA
compiles exactly one executable), jit forward returning features AND
logits in one pass, ``--show_pred`` printing top-5 ImageNet classes
(ref extract_resnet.py:112-114, utils/utils.py:19-46).

Output contract: ``{resnetXX: (T, feat_dim), fps, timestamps_ms}``
(ref extract_resnet.py:162-167); 2048-d for resnet50+ (BASELINE.md).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import stream_frames
from video_features_tpu.models.common.weights import load_params, random_init_fallback
from video_features_tpu.models.resnet.convert import convert_state_dict
from video_features_tpu.models.resnet.model import build, init_params
from video_features_tpu.ops.preprocess import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    device_preprocess_frames,
    imagenet_preprocess,
)
from video_features_tpu.ops.resize import fused_resize_crop_banded
from video_features_tpu.ops.window import pad_batch, pad_hw, spatial_bucket
from video_features_tpu.utils.labels import show_predictions_on_dataset


class ExtractResNet(BaseExtractor):
    # --sharding mesh: pure data parallelism — conv weights replicate,
    # the frame-batch axis shards over 'data' (parallel/sharding.py)
    mesh_capable = True

    def __init__(self, config, external_call: bool = False) -> None:
        super().__init__(config, external_call)
        self.batch_size = max(int(self.config.batch_size or 1), 1)
        self._host_params = None

    def _load_host_params(self):
        if self._host_params is None:
            if self.config.weights_path:
                self._host_params = load_params(
                    self.config.weights_path,
                    lambda sd: convert_state_dict(sd, self.feature_type),
                )
            else:
                random_init_fallback(
                    self.config, self.feature_type,
                    f"a torchvision {self.feature_type} state dict "
                    "(.pt/.pth) or a converted flax .msgpack",
                )
                self._host_params = init_params(self.feature_type)
        return self._host_params

    def _build(self, device):
        from video_features_tpu.models.common.weights import (
            cast_floats_for_compute,
            compute_dtype,
        )

        from video_features_tpu.parallel.sharding import (
            is_mesh,
            jit_sharded_forward,
            place_params,
        )

        dt = compute_dtype(self.config)
        model = build(self.feature_type, dtype=dt)
        params = self._load_host_params()
        if dt != jnp.float32:
            params = cast_floats_for_compute(params, dt, exclude=("fc",))
        params = place_params(params, device)  # mesh: replicated (DP)

        def forward(p, x):
            return model.apply({"params": p}, x)

        forward = jit_sharded_forward(forward, device, n_out=2)
        state = {"params": params, "forward": forward, "device": device}
        if self._device_preprocess_enabled() and not is_mesh(device):
            from video_features_tpu.extract import ingest

            # --preprocess device (sanity_check excludes mesh for ResNet;
            # the `not is_mesh` conjunct makes that visible to GC50x):
            # raw uint8 frames + the video's banded resize/crop taps fuse
            # the bilinear-256/crop-224/normalize chain into the forward.
            # Only the frame chunk (argnum 1) is donated — it is placed
            # fresh per call, while the taps (wy_d/wx_d) are reused
            # across every chunk of a video and must stay alive.
            def forward_raw(p, x_u8, wy, wx):
                x = device_preprocess_frames(
                    x_u8, wy, wx, IMAGENET_MEAN, IMAGENET_STD, out_dtype=dt
                )
                return model.apply({"params": p}, x)

            # --video_batch: rows from different videos share a chunked
            # forward; ids gather each row's own source-resolution taps
            # from the stacked per-video matrices
            def forward_raw_group(p, x_u8, wy_vids, wx_vids, ids):
                x = device_preprocess_frames(
                    x_u8,
                    tuple(a[ids] for a in wy_vids),
                    tuple(a[ids] for a in wx_vids),
                    IMAGENET_MEAN, IMAGENET_STD, out_dtype=dt,
                )
                return model.apply({"params": p}, x)

            state["forward_raw"] = ingest.jit_donated(
                forward_raw, donate_argnums=(1,)
            )
            state["forward_raw_group"] = ingest.jit_donated(
                forward_raw_group, donate_argnums=(1,)
            )
        return state

    def _preprocess_batch(self, batch: List[np.ndarray]) -> np.ndarray:
        """raw uint8 HWC frames -> (n, 3, 224, 224) normalized float32.

        'native' routes through the threaded C++ chain (same-resolution
        frames batched in one call); 'pil' is the reference-exact path.
        Backend decided once (BaseExtractor._native_decided)."""
        if self._native_decided():
            from video_features_tpu import native

            return native.imagenet_preprocess_batch(
                np.stack(batch), threads=self._native_threads
            )
        return np.stack([imagenet_preprocess(f) for f in batch])

    # A prepared video holds preprocessed fp32 224x224 frames (~600 KB
    # each); the pipeline keeps decode_workers+2 prepared videos resident,
    # so the guard is a byte budget split over those slots (advisor r02:
    # a flat frame cap scaled host RAM with the worker count). Over-cap
    # videos hand decode back to the device thread as a stream.
    PIPELINE_MAX_BYTES = 4 << 30
    _FRAME_BYTES = 3 * 224 * 224 * 4

    @property
    def PIPELINE_MAX_FRAMES(self) -> int:
        return self._prefetch_frame_cap(
            self.PIPELINE_MAX_BYTES, self._FRAME_BYTES, floor=64
        )

    # host half: stream-decode + preprocess into padded static-shape
    # batches (runs on --decode_workers threads under the async pipeline)
    def _device_geometry(self, h: int, w: int):
        """(bucket_h, bucket_w, (wt_y, idx_y), (wt_x, idx_x)) for a source
        resolution under --preprocess device: the ResNet chain's bilinear
        Resize(256) + CenterCrop(224) as bucket-padded banded taps."""
        bh, bw = spatial_bucket(h, w, self.config.spatial_bucket)
        wt_y, idx_y, wt_x, idx_x = fused_resize_crop_banded(
            h, w, 256, 224, "bilinear", pad_h=bh, pad_w=bw
        )
        return bh, bw, (wt_y, idx_y), (wt_x, idx_x)

    def _prepare_device(self, path_entry):
        """--preprocess device prepare: batches hold raw uint8 HWC frames
        padded to the spatial bucket; resize/crop/normalize fuses into
        forward_raw on-device. The prefetch cap is resolution-dependent
        here — a resident frame costs bucket_h*bucket_w*3 uint8 bytes, not
        the host path's fixed 224x224 float32 — so it is computed from the
        first decoded frame."""
        video_path = video_path_of(path_entry)
        fps = self.config.extraction_fps
        decode_path, sel_fps = self._fps_source(video_path)
        batch: List[np.ndarray] = []
        batches: List[np.ndarray] = []
        counts: List[int] = []
        timestamps_ms: List[float] = []
        geom = None
        max_frames = self.PIPELINE_MAX_FRAMES

        def flush():
            n = len(batch)
            x = pad_hw(np.stack(batch), geom[0], geom[1])
            if n < self.batch_size:
                x = np.pad(x, [(0, self.batch_size - n)] + [(0, 0)] * 3)
            batches.append(x)
            counts.append(n)

        n_frames = 0
        for frame, ts in stream_frames(decode_path, sel_fps, self.config.decoder):
            if geom is None:
                geom = self._device_geometry(*frame.shape[:2])
                max_frames = self._prefetch_frame_cap(
                    self.PIPELINE_MAX_BYTES, geom[0] * geom[1] * 3, floor=64
                )
            n_frames += 1
            if n_frames > max_frames:
                return ("stream", (decode_path, sel_fps))
            batch.append(frame)
            timestamps_ms.append(ts)
            if len(batch) == self.batch_size:
                flush()
                batch = []
        if batch:
            flush()
        if not batches:
            raise IOError(f"no frames decoded from {video_path}")
        from video_features_tpu.io.video import probe

        actual_fps = fps or probe(video_path, self.config.decoder).fps or 25.0
        return (
            "dev",
            (batches, counts, actual_fps, timestamps_ms, geom[2], geom[3]),
        )

    def prepare(self, path_entry):
        if self._device_preprocess_enabled():
            return self._prepare_device(path_entry)
        video_path = video_path_of(path_entry)
        fps = self.config.extraction_fps
        decode_path, sel_fps = self._fps_source(video_path)
        batch: List[np.ndarray] = []
        batches: List[np.ndarray] = []
        counts: List[int] = []
        timestamps_ms: List[float] = []

        def flush():
            n = len(batch)
            x = self._preprocess_batch(batch)
            if n < self.batch_size:
                x = np.pad(x, [(0, self.batch_size - n)] + [(0, 0)] * 3)
            batches.append(x)
            counts.append(n)

        n_frames = 0
        for frame, ts in stream_frames(decode_path, sel_fps, self.config.decoder):
            n_frames += 1
            if n_frames > self.PIPELINE_MAX_FRAMES:
                # hand the (possibly re-encoded) decode source over, with
                # the matching selection fps
                return ("stream", (decode_path, sel_fps))
            batch.append(frame)
            timestamps_ms.append(ts)
            if len(batch) == self.batch_size:
                flush()
                batch = []
        if batch:
            flush()
        if not batches:
            raise IOError(f"no frames decoded from {video_path}")
        from video_features_tpu.io.video import probe

        actual_fps = fps or probe(video_path, self.config.decoder).fps or 25.0
        return batches, counts, actual_fps, timestamps_ms

    def _extract_streaming(self, state, source) -> Dict[str, np.ndarray]:
        """Bounded-memory fallback: decode/preprocess one batch at a time
        on the consuming thread (the round-1 behavior; no video-level
        prefetch, but host memory stays at one batch). ``source`` is
        prepare's (decode_path, selection_fps) — already past the
        --fps_retarget policy."""
        video_path, sel_fps = source
        fps = self.config.extraction_fps
        batch: List[np.ndarray] = []
        feats_out: List[np.ndarray] = []
        timestamps_ms: List[float] = []

        def run(batch):
            from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

            n = len(batch)
            if self._device_preprocess_enabled():
                bh, bw, wy, wx = self._device_geometry(*batch[0].shape[:2])
                x = pad_hw(np.stack(batch), bh, bw)
                if n < self.batch_size:
                    x = np.pad(x, [(0, self.batch_size - n)] + [(0, 0)] * 3)
                x, wy, wx = jax.device_put((x, wy, wx), state["device"])
                feats, logits = state["forward_raw"](state["params"], x, wy, wx)
            else:
                x = self._preprocess_batch(batch)
                if n < self.batch_size:
                    x = np.pad(x, [(0, self.batch_size - n)] + [(0, 0)] * 3)
                x = pad_batch_for(state["device"], x)
                x = place_batch(x, state["device"])
                feats, logits = state["forward"](state["params"], x)
            feats_out.append(np.asarray(feats)[:n])
            if self.config.show_pred:
                show_predictions_on_dataset(np.asarray(logits)[:n], "imagenet")

        for frame, ts in stream_frames(video_path, sel_fps, self.config.decoder):
            batch.append(frame)
            timestamps_ms.append(ts)
            if len(batch) == self.batch_size:
                run(batch)
                batch = []
        if batch:
            run(batch)
        if not feats_out:
            raise IOError(f"no frames decoded from {video_path}")
        from video_features_tpu.io.video import probe

        actual_fps = fps or probe(video_path, self.config.decoder).fps or 25.0
        return {
            self.feature_type: np.concatenate(feats_out, axis=0),
            "fps": np.array(actual_fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    # device half: transfer + jitted forward per batch
    # split for the device pipeline (extract/base.py): all frame batches
    # dispatch async, results fetched while the next video transfers.
    # The too-big-to-prefetch "stream" fallback cannot defer (it decodes
    # interleaved with compute), so it completes eagerly at dispatch and
    # fetch passes the ready dict through.
    def dispatch_prepared(self, device, state, path_entry, payload):
        if payload[0] == "stream":
            return ("done", self._extract_streaming(state, payload[1]))
        if payload[0] == "dev":  # --preprocess device (never mesh)
            batches, counts, actual_fps, timestamps_ms, wy, wx = payload[1]
            wy_d, wx_d = jax.device_put((wy, wx), state["device"])
            outs = []
            for x, n in zip(batches, counts):
                x = jax.device_put(x, state["device"])
                feats, logits = state["forward_raw"](
                    state["params"], x, wy_d, wx_d
                )
                outs.append((feats, logits if self.config.show_pred else None, n))
            return "batched", outs, actual_fps, timestamps_ms
        from video_features_tpu.parallel.sharding import pad_batch_for, place_batch

        batches, counts, actual_fps, timestamps_ms = payload
        outs = []
        for x, n in zip(batches, counts):
            x = pad_batch_for(state["device"], x)
            x = place_batch(x, state["device"])
            feats, logits = state["forward"](state["params"], x)
            # drop the 1000-class logits unless show_pred needs them —
            # the handle pins its buffers until fetch
            outs.append((feats, logits if self.config.show_pred else None, n))
        return "batched", outs, actual_fps, timestamps_ms

    def fetch_dispatched(self, handle) -> Dict[str, np.ndarray]:
        if handle[0] == "done":
            return handle[1]
        _, outs, actual_fps, timestamps_ms = handle
        feats_out: List[np.ndarray] = []
        for feats, logits, n in outs:
            feats_out.append(np.asarray(feats)[:n])
            if logits is not None:
                show_predictions_on_dataset(np.asarray(logits)[:n], "imagenet")
        return {
            self.feature_type: np.concatenate(feats_out, axis=0),
            "fps": np.array(actual_fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    # --- cross-video aggregation (--video_batch): the valid frames of N
    # videos re-chunk into (N*batch_size)-row forwards — short videos whose
    # lone tail batch would waste most of its pad rows share a dispatch.
    # Large videos (> AGG_MAX_FRAMES valid rows resident while a group
    # fills) and show_pred (per-video print interleaving) keep the
    # individual path via agg_key=None.
    AGG_MAX_FRAMES = 512

    def agg_key(self, payload):
        if payload[0] == "stream" or self.config.show_pred:
            return None
        if payload[0] == "dev":
            batches, counts = payload[1][0], payload[1][1]
            if sum(counts) > self.AGG_MAX_FRAMES:
                return None
            # (batch_size, bucket_h, bucket_w, 3): same-bucket videos fuse
            # even at different source resolutions — each keeps its own
            # taps via the per-video matrix stack in dispatch_group
            return ("dev", batches[0].shape)
        batches, counts, _, _ = payload
        if sum(counts) > self.AGG_MAX_FRAMES:
            return None
        return batches[0].shape  # (batch_size, 3, 224, 224)

    def dispatch_group(self, device, state, entries, payloads):
        group = max(int(self.config.video_batch or 1), 1)
        if payloads[0][0] == "dev":
            return self._dispatch_group_device(state, payloads, group)
        rows, totals = [], []
        for batches, counts, _, _ in payloads:
            rows.extend(x[:n] for x, n in zip(batches, counts))
            totals.append(sum(counts))
        outs = self._dispatch_rows_grouped(state, rows, self.batch_size * group)
        return outs, totals, [(p[2], p[3]) for p in payloads]

    def _dispatch_group_device(self, state, payloads, group):
        """Device-preprocess aggregation: the videos' valid uint8 rows
        concatenate and re-chunk like the host path, but each row carries
        a video id so forward_raw_group gathers that row's own
        source-resolution taps from the (group,)-stacked tap arrays —
        mixed resolutions inside one bucket share one compiled executable
        (K is bucket-stable, so the stacks agree in shape)."""
        rows, ids, totals, wys, wxs = [], [], [], [], []
        for i, (_, (batches, counts, _, _, wy, wx)) in enumerate(payloads):
            wys.append(wy)
            wxs.append(wx)
            for x, n in zip(batches, counts):
                rows.append(x[:n])
                ids.append(np.full(n, i, np.int32))
            totals.append(sum(counts))
        # partial flush keeps the compiled (group, ...) tap-stack shape
        wy_vids = tuple(
            pad_batch(np.stack([t[j] for t in wys]), group) for j in range(2)
        )
        wx_vids = tuple(
            pad_batch(np.stack([t[j] for t in wxs]), group) for j in range(2)
        )
        all_rows = np.concatenate(rows, axis=0)
        all_ids = np.concatenate(ids, axis=0)
        chunk = self.batch_size * group
        wy_d, wx_d = jax.device_put((wy_vids, wx_vids), state["device"])
        outs = []
        for i in range(0, all_rows.shape[0], chunk):
            piece = all_rows[i : i + chunk]
            n = piece.shape[0]
            x = pad_batch(piece, chunk)
            pid = pad_batch(all_ids[i : i + chunk], chunk)
            x, pid = jax.device_put((x, pid), state["device"])
            feats, _ = state["forward_raw_group"](
                state["params"], x, wy_d, wx_d, pid
            )
            outs.append((feats, n))
        return outs, totals, [(p[1][2], p[1][3]) for p in payloads]

    def fetch_group(self, handle):
        outs, totals, metas = handle
        return [
            {
                self.feature_type: feats,
                "fps": np.array(fps),
                "timestamps_ms": np.array(ts),
            }
            for feats, (fps, ts) in zip(self._split_grouped_rows(outs, totals), metas)
        ]
