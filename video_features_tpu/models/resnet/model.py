"""ResNet-18/34/50/101/152 in Flax (inference graph).

The reference takes these from ``torchvision.models`` and swaps ``fc`` for
``Identity`` while keeping the classifier head around for ``--show_pred``
(ref models/resnet/extract_resnet.py:52-71). Here the graph is rebuilt
TPU-first: NHWC layout end-to-end, BatchNorm folded to a single
multiply-add at apply time (inference only — running stats are params),
and the forward returns ``(features, logits)`` in one pass so the debug
rail costs one extra matmul, not a second traversal.

Semantics match torchvision's ResNet v1: 7x7/2 stem conv + BN + ReLU +
3x3/2 maxpool, four stages of BasicBlock (18/34) or Bottleneck (50+,
expansion 4, stride on conv2), global average pool, 1000-way fc.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Type

import jax
import jax.numpy as jnp
from flax import linen as nn

from video_features_tpu.models.common.layers import EvalBatchNorm


def _conv(features: int, kernel: int, stride: int = 1, name: str = None,
          dtype=jnp.float32):
    pad = (kernel - 1) // 2
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        use_bias=False,
        dtype=dtype,
        name=name,
    )


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    downsample: bool = False
    dtype: jnp.dtype = jnp.float32
    expansion = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        identity = x
        out = _conv(self.planes, 3, self.stride, name="conv1", dtype=self.dtype)(x)
        out = EvalBatchNorm(name="bn1")(out)
        out = nn.relu(out)
        out = _conv(self.planes, 3, 1, name="conv2", dtype=self.dtype)(out)
        out = EvalBatchNorm(name="bn2")(out)
        if self.downsample:
            identity = _conv(self.planes, 1, self.stride, name="downsample_conv",
                             dtype=self.dtype)(x)
            identity = EvalBatchNorm(name="downsample_bn")(identity)
        return nn.relu(out + identity)


class Bottleneck(nn.Module):
    planes: int
    stride: int = 1
    downsample: bool = False
    dtype: jnp.dtype = jnp.float32
    expansion = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        identity = x
        out = _conv(self.planes, 1, 1, name="conv1", dtype=self.dtype)(x)
        out = nn.relu(EvalBatchNorm(name="bn1")(out))
        out = _conv(self.planes, 3, self.stride, name="conv2", dtype=self.dtype)(out)
        out = nn.relu(EvalBatchNorm(name="bn2")(out))
        out = _conv(self.planes * 4, 1, 1, name="conv3", dtype=self.dtype)(out)
        out = EvalBatchNorm(name="bn3")(out)
        if self.downsample:
            identity = _conv(self.planes * 4, 1, self.stride, name="downsample_conv",
                             dtype=self.dtype)(x)
            identity = EvalBatchNorm(name="downsample_bn")(identity)
        return nn.relu(out + identity)


# feature_type -> (block, per-stage block counts), mirroring torchvision
ARCHS = {
    "resnet18": (BasicBlock, (2, 2, 2, 2)),
    "resnet34": (BasicBlock, (3, 4, 6, 3)),
    "resnet50": (Bottleneck, (3, 4, 6, 3)),
    "resnet101": (Bottleneck, (3, 4, 23, 3)),
    "resnet152": (Bottleneck, (3, 8, 36, 3)),
}


def feature_dim(arch: str) -> int:
    block, _ = ARCHS[arch]
    return 512 * block.expansion


class ResNet(nn.Module):
    """(N, 3, H, W) normalized fp32 -> (features (N, 512*exp), logits (N, classes))."""

    block: Type[nn.Module]
    layers: Sequence[int]
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW -> NHWC (TPU-native layout)
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=self.dtype, name="conv1",
        )(x)
        x = nn.relu(EvalBatchNorm(name="bn1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        in_planes = 64
        for stage, n_blocks in enumerate(self.layers):
            planes = 64 * (2 ** stage)
            stride = 1 if stage == 0 else 2
            for b in range(n_blocks):
                s = stride if b == 0 else 1
                need_ds = s != 1 or in_planes != planes * self.block.expansion
                x = self.block(
                    planes, s, need_ds, self.dtype, name=f"layer{stage + 1}_{b}"
                )(x)
                in_planes = planes * self.block.expansion

        # fp32 pool + head: features are the user-facing contract
        feats = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        logits = nn.Dense(self.num_classes, name="fc")(feats)
        return feats, logits


def build(arch: str, num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    block, layers = ARCHS[arch]
    return ResNet(block=block, layers=layers, num_classes=num_classes, dtype=dtype)


def init_params(arch: str, seed: int = 0, num_classes: int = 1000):
    model = build(arch, num_classes)
    dummy = jnp.zeros((1, 3, 224, 224), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)["params"]
