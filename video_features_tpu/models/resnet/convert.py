"""torchvision ResNet checkpoint -> Flax param tree.

Consumes the standard torchvision state-dict naming (``conv1.weight``,
``layer{s}.{b}.conv{k}.weight``, ``layer{s}.{b}.downsample.{0,1}.*``,
``fc.*``) that the reference loads via ``torchvision.models.resnetXX
(pretrained=True)`` (ref models/resnet/extract_resnet.py:52-63).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from video_features_tpu.models.common.weights import (
    bn_params as _bn,
    check_all_consumed,
    conv2d_kernel,
    strip_prefix,
    transpose_linear,
)
from video_features_tpu.models.resnet.model import ARCHS


def _conv(sd: Dict[str, np.ndarray], name: str, consumed) -> Dict[str, np.ndarray]:
    consumed.add(f"{name}.weight")
    return {"kernel": conv2d_kernel(sd[f"{name}.weight"])}


def convert_state_dict(sd: Dict[str, np.ndarray], arch: str):
    block, layers = ARCHS[arch]
    n_convs = 2 if block.__name__ == "BasicBlock" else 3
    sd = strip_prefix(sd, "module.")
    consumed = set()
    params = {
        "conv1": _conv(sd, "conv1", consumed),
        "bn1": _bn(sd, "bn1", consumed),
        "fc": {
            "kernel": transpose_linear(sd["fc.weight"]),
            "bias": sd["fc.bias"],
        },
    }
    consumed.update(("fc.weight", "fc.bias"))
    for stage, n_blocks in enumerate(layers):
        for b in range(n_blocks):
            ref = f"layer{stage + 1}.{b}"
            blk = {}
            for k in range(1, n_convs + 1):
                blk[f"conv{k}"] = _conv(sd, f"{ref}.conv{k}", consumed)
                blk[f"bn{k}"] = _bn(sd, f"{ref}.bn{k}", consumed)
            if f"{ref}.downsample.0.weight" in sd:
                blk["downsample_conv"] = _conv(sd, f"{ref}.downsample.0", consumed)
                blk["downsample_bn"] = _bn(sd, f"{ref}.downsample.1", consumed)
            params[f"layer{stage + 1}_{b}"] = blk
    check_all_consumed(sd, consumed, f"ResNet[{arch}]")
    return params
