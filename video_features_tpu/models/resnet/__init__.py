from video_features_tpu.models.resnet.model import ARCHS, ResNet, init_params  # noqa: F401
