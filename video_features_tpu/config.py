"""Typed configuration + CLI shim.

The reference passes a raw ``argparse.Namespace`` (ref main.py:94-137) into
every extractor. Here the canonical object is a typed dataclass; an
argparse parser with the reference's exact flag surface builds it, and a
``from_namespace`` shim accepts reference-style namespaces so external
callers (ref README.md:38-57) can migrate without changes.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

FEATURE_TYPES = [
    "i3d",
    "vggish",
    "vggish_torch",
    "r21d_rgb",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "raft",
    "pwc",
    "CLIP-ViT-B/32",
    "CLIP-ViT-B/16",
    "CLIP4CLIP-ViT-B-32",
]

RESNET_FEATURE_TYPES = [f"resnet{d}" for d in (18, 34, 50, 101, 152)]
CLIP_FEATURE_TYPES = ["CLIP-ViT-B/32", "CLIP-ViT-B/16", "CLIP4CLIP-ViT-B-32"]

# extractors whose dispatch honors --preprocess device: the image models
# (fixed 224-crop contract), the flow models (InputPadder-/exact-grid
# contract) and I3D (min-edge-256 output-bucket contract). sanity_check
# names this set in its rejection message, so it stays the single source
# of truth as coverage grows.
DEVICE_PREPROCESS_FEATURE_TYPES = (
    CLIP_FEATURE_TYPES + RESNET_FEATURE_TYPES + ["raft", "pwc", "i3d"]
)

# extractors whose fused --preprocess device entry also satisfies the
# GC50x sharding contract under --sharding mesh: the frame/stack axis
# shards over 'data' with explicit in_shardings/out_shardings and the
# shape-contract payload (resample taps, crop offsets) replicates —
# models/clip/extract_clip.py encode_raw, models/common/flow_extract.py
# forward_raw, models/i3d/extract_i3d.py's fused mesh branch. The
# remaining device-preprocess extractors (resnet*) keep their
# single-device fused path, so mesh+device stays rejected for them until
# their entries carry the contract too; graftcheck GC505 cross-checks
# this list against the declared entries.
MESH_DEVICE_PREPROCESS_FEATURE_TYPES = CLIP_FEATURE_TYPES + ["raft", "pwc", "i3d"]

# --dtype admission table (graftcheck GC804): model families whose
# low-precision graphs carry a committed relative-drift ceiling in
# analysis/parity_budget.json, each asserted end-to-end in tests/.
# sanity_check rejects low-precision dtypes for any family not listed
# here, and GC804 cross-checks this table against the budget file — so
# an admission, its ceiling, and its parity test land in one diff.
# VGGish stays fp32-only (the audio net is too small for bf16 to buy
# anything).
LOW_PRECISION_MODEL_FAMILIES = {
    "bfloat16": ("clip", "resnet", "r21d", "i3d", "raft", "pwc"),
}


def model_family(feature_type: str) -> str:
    """The parity/admission family of a feature type ('resnet50' ->
    'resnet', 'CLIP-ViT-B/16' -> 'clip', 'r21d_rgb' -> 'r21d')."""
    if feature_type in CLIP_FEATURE_TYPES:
        return "clip"
    if feature_type in RESNET_FEATURE_TYPES:
        return "resnet"
    if feature_type == "r21d_rgb":
        return "r21d"
    return feature_type


@dataclass
class ExtractionConfig:
    """All knobs for one extraction job.

    Field names intentionally match the reference CLI flags
    (ref main.py:94-137) so ``ExtractionConfig(**vars(args))`` works.
    """

    feature_type: str = "CLIP-ViT-B/32"

    # --- input selection (ref utils/utils.py:153-204) ---
    video_paths: Optional[List[str]] = None
    flow_paths: Optional[List[str]] = None
    file_with_video_paths: Optional[str] = None
    video_dir: Optional[str] = None
    flow_dir: Optional[str] = None

    # --- devices ---
    device_ids: Optional[List[int]] = None
    cpu: bool = False

    # --- output ---
    tmp_path: str = "./tmp"
    keep_tmp_files: bool = False
    on_extraction: str = "print"  # print | save_numpy | save_pickle
    output_path: str = "./output"
    output_direct: bool = False

    # --- sampling / windowing ---
    extraction_fps: Optional[float] = None
    extract_method: Optional[str] = None  # e.g. 'fix_2', 'uni_12'
    stack_size: Optional[int] = None
    step_size: Optional[int] = None
    streams: Optional[List[str]] = None  # subset of ['rgb', 'flow']
    flow_type: str = "pwc"  # raft | pwc | flow (pre-extracted)
    batch_size: int = 1
    resize_to_smaller_edge: bool = True
    side_size: Optional[int] = None

    # --- debug rails ---
    show_pred: bool = False

    # --- TPU-native knobs (no reference equivalent) ---
    # Numerics: 'float32' for parity with the fp32 reference; 'bfloat16'
    # runs the conv/matmul stacks of every LOW_PRECISION_MODEL_FAMILIES
    # family in bf16 — including RAFT/PWC since r4 (LayerNorm, softmax,
    # BatchNorm math, flow refinement carries/corr pyramids and the
    # feature heads stay fp32). Per-family drift ceilings live in
    # analysis/parity_budget.json and are asserted by the parity tests;
    # sanity_check rejects the flag for unadmitted families (vggish*).
    dtype: str = "float32"
    # Path to converted model weights (.npz / orbax dir). Absent or
    # incomplete weights are a hard error unless allow_random_init is set
    # (the reference either downloads weights or crashes —
    # ref models/i3d/extract_i3d.py:23-26).
    weights_path: Optional[str] = None
    # Escape hatch for tests/benchmarks: run with deterministic random
    # init when weights are missing. Feature VALUES are then meaningless;
    # only shapes/dtypes/pipeline behavior are exercised.
    allow_random_init: bool = False
    # Async host pipeline: decode/preprocess worker threads per device,
    # prefetching upcoming videos' device-ready arrays while the current
    # video computes (extract/base.py::_run_pipelined). 0 = fully serial
    # decode->compute, the reference's behavior.
    decode_workers: int = 2
    # Decode backend (io/video.py): 'auto' (default) uses the native C++
    # libav loader (native/decoder.cpp) when its library builds, falling
    # back to cv2; 'cv2'/'native' force one. Both decode the same
    # bitstream through libavcodec — frames are bit-identical.
    decoder: str = "auto"
    # Host preprocessing backend for the PIL-chain extractors (the ResNet
    # family's bilinear chain and CLIP's bicubic chain): 'pil' reproduces
    # the reference bit-for-bit; 'native' uses the threaded C++ library
    # (native/preprocess.cpp, within ~1/255/pixel of PIL) for throughput.
    # Other extractors preprocess on-device and ignore this knob.
    host_preprocess: str = "pil"
    # R(2+1)D ships windows host->device as uint8 (4x less transfer, the
    # preprocess is fused on-device). 'off' pre-casts to fp32 on the host
    # — an escape hatch for transports whose uint8 DMA path is slow
    # (measured on the axon tunnel: 12.5 MB uint8 took 6.6 s vs 50 MB
    # fp32 at 0.026 s). Numerics identical either way.
    uint8_transfer: str = "on"
    # Skip videos whose output files already exist (job-level resume; the
    # reference recomputes and overwrites unconditionally).
    resume: bool = False
    # When set, wrap extraction in a jax.profiler trace written here and
    # print a per-stage wall-time summary at the end.
    profile_dir: Optional[str] = None
    # Resolution buckets for XLA static shapes (see ops/window.py).
    shape_buckets: Optional[List[int]] = None
    # Execution strategy over the selected devices (parallel/):
    #   'queue' — the reference-style video-level data parallelism, one
    #             model replica + work-queue thread per chip (scheduler.py);
    #   'mesh'  — ONE GSPMD-sharded executable over a (data, model)
    #             jax.sharding.Mesh of every selected chip: the frame/stack
    #             batch shards over 'data' (for video models that is the
    #             time axis — the sequence-parallel story) and, for
    #             mesh-capable transformer models, weights shard
    #             Megatron-style over 'model' (sharding.py). XLA inserts
    #             the ICI collectives.
    sharding: str = "queue"
    # 'model' (tensor-parallel) axis size of the mesh; 'data' gets the rest.
    mesh_model: int = 1
    # Attention core for the transformer extractors (CLIP family):
    #   'fused'     — full-score-matrix core; the right answer at ViT's
    #                 50/197 tokens (the whole matrix fits in VMEM).
    #   'flash'     — the Pallas flash-attention kernel
    #                 (ops/pallas/flash_attention.py): O(block) score
    #                 memory, the single-chip long-sequence core.
    #   'blockwise' — the XLA lax.scan online-softmax core (same math as
    #                 flash, no Pallas dependency).
    # All three are mathematically exact, so converted OpenAI weights
    # give identical features (tests/test_aggregation.py pins flash==fused
    # on the real extractor path). Non-transformer extractors ignore this.
    attn: str = "fused"
    # Cross-video batch aggregation: group up to this many prepared
    # videos' (same-shape) batches into ONE device dispatch, slicing
    # features apart per video on fetch (extract/base.py aggregation
    # protocol). 1 = off. The single-video batches the reference
    # dispatches (~12 CLIP frames, ~2 R21D stacks) leave an accelerator
    # >99% idle; with frozen weights nothing distinguishes frames of
    # different videos, so they can share a forward (SURVEY.md §5).
    # Requires decode_workers >= 1 (the async pipeline hosts the
    # grouping); show_pred keeps per-video dispatch.
    video_batch: int = 1
    # Depth of the async-ingest completion queue (extract/ingest.py):
    # how many dispatched groups/videos may stay in flight on the
    # device before the loop blocks on the oldest one's fetch. 2 is
    # the classic double-buffer (and today's behavior): group N+1's
    # H2D/compute is enqueued while group N finishes. Raising it deepens
    # the pipeline (more HBM pinned by in-flight payloads) for
    # high-latency transports; 1 degenerates to lockstep
    # dispatch-then-fetch.
    inflight_groups: int = 2
    # Frame-delta gating (--preprocess host or device, CLIP family
    # only): mean |uint8 delta| below this threshold vs the last KEPT
    # frame marks a sampled frame near-duplicate — it is skipped BEFORE
    # H2D and its feature row is filled by copy-forward at fetch time
    # (ops/sampler.py). None = off (the parity default); 0 keeps every
    # frame (the skip rule is strictly-below), so `0` is bit-identical
    # to off. FASTER (PAPERS.md) motivates the redundancy skip.
    frame_delta_threshold: Optional[float] = None
    # Context parallelism (--sharding mesh only): shard the transformer's
    # token axis over the mesh 'data' axis and run ring attention — KV
    # shards rotate chip-to-chip over ICI (parallel/ring_attention.py) —
    # instead of sharding the frame batch. The long-sequence regime:
    # activation memory per chip is O(L/n). CLIP only (the transformer).
    mesh_context: bool = False
    # How --extraction_fps re-targets the frame grid (resnet*/raft/pwc —
    # the families whose reference path re-encodes, ref utils/utils.py:
    # 222-244):
    #   'nearest'  — in-process nearest-frame selection on the native
    #                decode grid (io/video._resample_indices): no ffmpeg
    #                dependency, no transcode, bit-exact SOURCE pixels;
    #   'reencode' — the reference's ffmpeg re-encode into --tmp_path:
    #                reproduces its fps path bit-for-bit, including the
    #                resampled/re-compressed pixels (needs ffmpeg).
    fps_retarget: str = "nearest"
    # Where the resize/crop/normalize chain runs for the image-model
    # extractors (CLIP's bicubic chain, the ResNet family's bilinear
    # chain):
    #   'host'   — the reference-exact PIL chain (or --host_preprocess
    #              native) on the decode threads; the parity default.
    #   'device' — decode ships raw uint8 HWC frames (4x less H2D than
    #              float32), padded to a spatial bucket grid
    #              (ops/window.py::spatial_bucket), and one fused jit
    #              program does PIL-semantics resize + center crop +
    #              normalize + encoder forward (ops/preprocess.py::
    #              device_preprocess_frames). Lifts the ~300 fps host
    #              preprocess ceiling (BENCH_r05); within 1/255/pixel of
    #              PIL (tests/test_device_preprocess.py).
    preprocess: str = "host"
    # --preprocess device: each spatial axis of a source resolution
    # rounds up to the next multiple of this, so a variable-resolution
    # corpus compiles O(buckets) executables instead of O(shapes).
    # Bigger = fewer compiles, more padded-pixel compute.
    spatial_bucket: int = 64
    # Persistent XLA compilation cache directory: repeat runs skip
    # cold-start compiles of the bucketed executables (and everything
    # else). None = off (JAX's default in-memory cache only).
    compile_cache: Optional[str] = None
    # Only executables whose compile took at least this many seconds are
    # written to --compile_cache (jax_persistent_cache_min_compile_time_
    # secs) — keeps trivial compiles from churning the cache dir.
    compile_cache_min_s: float = 1.0
    # --- fault tolerance (runtime/faults.py; docs/robustness.md) ---
    # Retry budget for TRANSIENT per-video failures (I/O flakes, decode
    # deadlines, RESOURCE_EXHAUSTED): the video re-enters the work queue
    # with exponential backoff + deterministic jitter up to this many
    # extra attempts. Permanent failures (corrupt container, shape
    # mismatch) never retry. Also caps how often the queue scheduler
    # requeues a chunk orphaned by a worker death.
    retries: int = 2
    # Base backoff in seconds; attempt k waits base * 2^(k-1) * jitter.
    retry_backoff: float = 0.5
    # Any failed video / empty-feature warning / worker death in the run
    # manifest turns the exit code nonzero (CI and batch schedulers need
    # "completed" to mean "everything extracted").
    strict: bool = False
    # --resume: also re-attempt videos the manifest recorded as
    # PERMANENTLY failed (by default resume skips them — re-decoding a
    # corrupt container forever is the failure mode this flag gates).
    retry_failed: bool = False
    # Wall-clock budget (seconds) per decode: a reader (or ffmpeg
    # re-encode) exceeding it raises DecodeTimeout — classified
    # transient, so the video retries with a fresh deadline. None = off.
    decode_timeout: Optional[float] = None
    # Preflight probe (io/probe.py) before each video's first attempt:
    # 'on' rejects hostile/corrupt inputs as permanent manifest failures
    # (zero retries burned) and records metadata warnings; 'off' lets
    # the decode path discover problems itself (the pre-ISSUE-9
    # behaviour).
    preflight: str = "on"
    # Input resource caps, enforced twice (docs/robustness.md "hostile
    # input"): at preflight from declared metadata, and as a running
    # budget over actual decode so a lying header cannot blow host RAM.
    # Over-budget raises ResourceCapExceeded (permanent). None = off.
    max_pixels: Optional[int] = None        # per-frame width*height
    max_duration_s: Optional[float] = None  # declared/decoded clip length
    max_decode_bytes: Optional[int] = None  # total RGB bytes one reader may yield
    # Deterministic fault injection, test-only: STAGE:KIND:EVERY_N specs
    # (stage in decode/prepare/dispatch/sink; kind in error/corrupt/
    # hang/oom/compile/kill) raise or stall at that stage every N calls,
    # so the retry/fallback/manifest paths are exercised by fast CPU
    # tests (tests/test_faults.py).
    fault_inject: Optional[List[str]] = None
    # Structured telemetry (runtime/telemetry.py): 'on' records per-stage
    # spans to <output>/_telemetry/spans-*.jsonl plus a metrics block in
    # summary.json; 'off' degrades to the bare StageTimer aggregate (the
    # pre-telemetry behaviour, and the baseline the telemetry_overhead
    # bench part compares against).
    telemetry: str = "on"
    # Seconds between heartbeat progress lines on stderr (videos/sec,
    # decode fps, ETA) during save runs; 0 disables the heartbeat.
    heartbeat_s: float = 30.0
    # 3D-conv lowering for the 3D-conv families, i3d + r21d
    # (common/layers.py::Conv3DCompat):
    #   'auto'       — honor the VFT_CONV3D_IMPL env var, else direct;
    #   'direct'     — XLA's native 3D convolution (fastest when it works);
    #   'decomposed' — sum of kt 2D convs over strided time slices, byte-
    #                  compatible checkpoints, identical math. The escape
    #                  hatch for TPU stacks whose 3D-conv compile crashes
    #                  (BASELINE.md round-4 chip log; bench.py defaults the
    #                  i3d parts to 'decomposed' on TPU for this reason).
    # Explicit direct/decomposed overrides the env var either way.
    conv3d_impl: str = "auto"
    # --- content-addressed feature cache + shared-decode fan-out (ISSUE 17)
    # Root of the content-addressed feature store (extract/cache.py):
    # completed features keyed by (content hash, config digest) are
    # served as a file copy instead of a decode + forward pass. None
    # disables caching entirely.
    cache_dir: Optional[str] = None
    # 'fast' hashes size + head + sampled chunks + tail (never streams a
    # multi-GB file on the admission path); 'full' streams every byte —
    # the escape hatch for collision-paranoid setups.
    cache_hash: str = "fast"
    # Byte budget (MiB) for the shared-decode frame cache installed
    # around multi-model fan-out runs (extract/plan.py): decode once,
    # serve every requested model from the cached frames. 0 disables;
    # single-model runs never install it regardless.
    ingest_cache_mb: int = 512

    def __post_init__(self) -> None:
        if self.streams is not None and not isinstance(self.streams, (list, tuple)):
            self.streams = [self.streams]

    @classmethod
    def from_namespace(cls, args: argparse.Namespace) -> "ExtractionConfig":
        """Accept a reference-style argparse.Namespace (extra keys ignored,
        missing keys defaulted) — the migration path for external callers."""
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in vars(args).items() if k in known and v is not None}
        return cls(**kwargs)

    def replace(self, **kw) -> "ExtractionConfig":
        return dataclasses.replace(self, **kw)


def as_config(obj) -> ExtractionConfig:
    """Normalize user input (dataclass, Namespace, or dict) to a config."""
    if isinstance(obj, ExtractionConfig):
        return obj
    if isinstance(obj, argparse.Namespace):
        return ExtractionConfig.from_namespace(obj)
    if isinstance(obj, dict):
        return ExtractionConfig(**obj)
    raise TypeError(f"cannot build ExtractionConfig from {type(obj)!r}")


def sanity_check(cfg: ExtractionConfig) -> ExtractionConfig:
    """Cross-field validation, mirroring ref utils/utils.py:129-150."""
    if os.path.relpath(cfg.output_path) == os.path.relpath(cfg.tmp_path):
        raise AssertionError("The same path for out & tmp")
    if cfg.on_extraction not in ("print", "save_numpy", "save_pickle", "save_jpg"):
        raise ValueError(f"unknown on_extraction: {cfg.on_extraction}")
    if cfg.on_extraction == "save_jpg" and cfg.feature_type not in ("raft", "pwc"):
        raise ValueError(
            "save_jpg writes quantized flow JPEGs and only applies to "
            f"flow features (raft/pwc), not {cfg.feature_type!r}"
        )
    if cfg.show_pred:
        # predictions interleave across workers; pin to one device
        cfg = cfg.replace(device_ids=[cfg.device_ids[0]] if cfg.device_ids else [0])
    if cfg.feature_type == "i3d" and cfg.stack_size is not None and cfg.stack_size < 10:
        raise AssertionError(
            f"I3D does not support inputs shorter than 10 timestamps, got {cfg.stack_size}"
        )
    if cfg.feature_type not in FEATURE_TYPES:
        raise ValueError(f"unknown feature_type: {cfg.feature_type}")
    if cfg.sharding not in ("queue", "mesh"):
        raise ValueError(f"unknown sharding strategy: {cfg.sharding}")
    if cfg.mesh_model < 1:
        raise ValueError(f"mesh_model must be >= 1, got {cfg.mesh_model}")
    if cfg.mesh_context and cfg.sharding != "mesh":
        raise ValueError("--mesh_context requires --sharding mesh")
    if cfg.video_batch < 1:
        raise ValueError(f"video_batch must be >= 1, got {cfg.video_batch}")
    if cfg.video_batch > 1 and int(cfg.decode_workers or 0) < 1:
        raise ValueError(
            "--video_batch needs the async pipeline: set --decode_workers "
            ">= 1 (aggregation groups prepared videos, and only "
            "_run_pipelined prepares ahead)"
        )
    if cfg.inflight_groups < 1:
        raise ValueError(
            f"inflight_groups must be >= 1, got {cfg.inflight_groups}"
        )
    if cfg.frame_delta_threshold is not None:
        if cfg.frame_delta_threshold < 0:
            raise ValueError(
                "frame_delta_threshold must be >= 0, got "
                f"{cfg.frame_delta_threshold}"
            )
        if cfg.feature_type not in CLIP_FEATURE_TYPES:
            supported = ", ".join(CLIP_FEATURE_TYPES)
            raise ValueError(
                "--frame_delta_threshold gates per-frame features with "
                "copy-forward fill, which is only sound for the "
                f"frame-level extractors: {supported} "
                f"(got {cfg.feature_type!r}; windowed/flow models mix "
                "frames across time)"
            )
    if cfg.dtype != "float32":
        fams = LOW_PRECISION_MODEL_FAMILIES.get(cfg.dtype)
        if fams is None:
            raise ValueError(f"unknown dtype: {cfg.dtype!r}")
        if model_family(cfg.feature_type) not in fams:
            raise ValueError(
                f"--dtype {cfg.dtype} is not admitted for "
                f"{cfg.feature_type!r}: admission requires a committed "
                "drift ceiling in analysis/parity_budget.json plus an "
                "e2e parity test (graftcheck GC804) — see "
                "LOW_PRECISION_MODEL_FAMILIES and docs/tpu.md "
                "'Precision contract'"
            )
    if cfg.attn not in ("fused", "flash", "blockwise"):
        raise ValueError(f"unknown attn core: {cfg.attn}")
    if cfg.conv3d_impl not in ("auto", "direct", "decomposed"):
        raise ValueError(f"unknown conv3d_impl: {cfg.conv3d_impl}")
    if cfg.fps_retarget not in ("nearest", "reencode"):
        raise ValueError(f"unknown fps_retarget: {cfg.fps_retarget}")
    if cfg.fps_retarget == "reencode" and not (
        cfg.feature_type in ("raft", "pwc")
        or cfg.feature_type in RESNET_FEATURE_TYPES
    ):
        raise ValueError(
            "--fps_retarget reencode mirrors the reference's ffmpeg fps "
            "path, which only exists for resnet*/raft/pwc (ref utils/"
            "utils.py:222-244); other extractors sample their own grids "
            f"(got {cfg.feature_type!r})"
        )
    if cfg.preprocess not in ("host", "device"):
        raise ValueError(f"unknown preprocess mode: {cfg.preprocess}")
    if cfg.preprocess == "device":
        if cfg.feature_type not in DEVICE_PREPROCESS_FEATURE_TYPES:
            supported = ", ".join(sorted(DEVICE_PREPROCESS_FEATURE_TYPES))
            raise ValueError(
                "--preprocess device currently covers: "
                f"{supported} (got {cfg.feature_type!r})"
            )
        if cfg.feature_type == "i3d" and cfg.flow_type == "flow":
            raise ValueError(
                "--preprocess device on i3d requires an on-the-fly flow "
                "model (--flow_type raft or pwc); pre-extracted disk flow "
                "keeps the host chain (frames arrive already resized)"
            )
        if cfg.show_pred and cfg.feature_type in ("raft", "pwc"):
            raise ValueError(
                "--show_pred draws flow onto host-resized frames, which "
                "--preprocess device never materializes for raft/pwc — "
                "drop one of the two flags"
            )
        if cfg.sharding == "mesh":
            if cfg.feature_type not in MESH_DEVICE_PREPROCESS_FEATURE_TYPES:
                supported = ", ".join(sorted(MESH_DEVICE_PREPROCESS_FEATURE_TYPES))
                raise ValueError(
                    "--preprocess device under --sharding mesh needs the "
                    "fused entry to declare its sharding contract (GC502); "
                    f"today that covers: {supported} "
                    f"(got {cfg.feature_type!r})"
                )
            if cfg.mesh_context:
                raise ValueError(
                    "--preprocess device shards the raw frame axis over "
                    "'data'; --mesh_context replicates the batch and "
                    "shards tokens in-model — the two layouts conflict, "
                    "drop one"
                )
    if cfg.spatial_bucket < 1:
        raise ValueError(f"spatial_bucket must be >= 1, got {cfg.spatial_bucket}")
    if cfg.compile_cache_min_s < 0:
        raise ValueError(
            f"compile_cache_min_s must be >= 0, got {cfg.compile_cache_min_s}"
        )
    if cfg.retries < 0:
        raise ValueError(f"retries must be >= 0, got {cfg.retries}")
    if cfg.retry_backoff < 0:
        raise ValueError(f"retry_backoff must be >= 0, got {cfg.retry_backoff}")
    if cfg.decode_timeout is not None and cfg.decode_timeout <= 0:
        raise ValueError(f"decode_timeout must be > 0, got {cfg.decode_timeout}")
    if cfg.preflight not in ("on", "off"):
        raise ValueError(f"preflight must be 'on' or 'off', got {cfg.preflight!r}")
    if cfg.max_pixels is not None and cfg.max_pixels < 1:
        raise ValueError(f"max_pixels must be >= 1, got {cfg.max_pixels}")
    if cfg.max_duration_s is not None and cfg.max_duration_s <= 0:
        raise ValueError(f"max_duration_s must be > 0, got {cfg.max_duration_s}")
    if cfg.max_decode_bytes is not None and cfg.max_decode_bytes < 1:
        raise ValueError(
            f"max_decode_bytes must be >= 1, got {cfg.max_decode_bytes}"
        )
    if cfg.retry_failed and not cfg.resume:
        raise ValueError(
            "--retry_failed only modifies --resume (it re-attempts videos "
            "the manifest recorded as permanently failed); add --resume"
        )
    if cfg.fault_inject:
        from video_features_tpu.runtime.faults import parse_fault_specs

        parse_fault_specs(cfg.fault_inject)  # raises naming the bad spec
    if cfg.telemetry not in ("on", "off"):
        raise ValueError(f"telemetry must be 'on' or 'off', got {cfg.telemetry!r}")
    if cfg.heartbeat_s < 0:
        raise ValueError(f"heartbeat_s must be >= 0, got {cfg.heartbeat_s}")
    if cfg.mesh_context and cfg.attn != "fused":
        raise ValueError(
            "--mesh_context injects the ring-attention core; it cannot "
            "combine with --attn flash/blockwise (ring already chunks KV "
            "blockwise per arriving shard)"
        )
    if cfg.cache_hash not in ("fast", "full"):
        raise ValueError(
            f"cache_hash must be 'fast' or 'full', got {cfg.cache_hash!r}"
        )
    if cfg.ingest_cache_mb < 0:
        raise ValueError(
            f"ingest_cache_mb must be >= 0, got {cfg.ingest_cache_mb}"
        )
    # flag-surface hygiene (graftcheck GC703): every free-form string
    # flag gets at least a shape check here, so junk values fail at
    # parse time instead of deep inside a run
    for flag, val in (
        ("file_with_video_paths", cfg.file_with_video_paths),
        ("video_dir", cfg.video_dir),
        ("flow_dir", cfg.flow_dir),
        ("weights_path", cfg.weights_path),
        ("profile_dir", cfg.profile_dir),
        ("compile_cache", cfg.compile_cache),
        ("cache_dir", cfg.cache_dir),
    ):
        if val is not None and not str(val).strip():
            raise ValueError(f"--{flag} must be a non-empty path")
    for flag, paths in (
        ("video_paths", cfg.video_paths),
        ("flow_paths", cfg.flow_paths),
    ):
        if paths and any(not str(pth).strip() for pth in paths):
            raise ValueError(f"--{flag} contains an empty path")
    if cfg.extract_method is not None and not re.fullmatch(
        r"(uni|fix)_[0-9]+", cfg.extract_method
    ):
        raise ValueError(
            "extract_method must look like uni_<N> or fix_<fps> (the "
            f"io/video.py samplers), got {cfg.extract_method!r}"
        )
    if cfg.shape_buckets is not None and (
        not cfg.shape_buckets or any(b < 1 for b in cfg.shape_buckets)
    ):
        raise ValueError(
            f"shape_buckets must be positive ints, got {cfg.shape_buckets}"
        )
    return cfg


def enable_compile_cache(cfg: ExtractionConfig) -> None:
    """Wire --compile_cache into JAX's persistent compilation cache.

    Must run before the first device/compile touch (cli.py calls it right
    after parse_args). Safe to call repeatedly — jax.config.update is
    idempotent for equal values."""
    if not cfg.compile_cache:
        return
    import jax

    os.makedirs(cfg.compile_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cfg.compile_cache)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(cfg.compile_cache_min_s),
    )


def build_arg_parser(feature_required: bool = True) -> argparse.ArgumentParser:
    """The reference CLI surface (ref main.py:94-137), plus TPU knobs.

    ``feature_required=False`` relaxes ``--feature_type`` for front-ends
    that pick the feature type per request (the ``serve`` daemon declares
    ``--feature_types`` instead)."""
    p = argparse.ArgumentParser(description="Extract features (TPU-native)")
    # required-ness is enforced post-parse (parse_batch_args): either
    # --feature_type or the batch --feature_types list satisfies it
    p.add_argument("--feature_type", required=False, choices=FEATURE_TYPES)
    p.add_argument("--video_paths", nargs="+", help="space-separated paths to videos")
    p.add_argument("--flow_paths", nargs="+", help="space-separated paths to video flow images")
    p.add_argument("--file_with_video_paths", help=".txt file where each line is a path")
    p.add_argument("--video_dir", type=str, help="dir of videos")
    p.add_argument(
        "--flow_dir", type=str,
        help="dir of optical flow of videos: [flow_dir]/[video id]/[flow_(x/y)_000001.jpg]",
    )
    p.add_argument(
        "--device_ids", type=int, nargs="+",
        help="space-separated device ids (indices into jax.devices())",
    )
    p.add_argument("--cpu", action="store_true", help="use cpu only")
    p.add_argument("--tmp_path", default="./tmp")
    p.add_argument("--keep_tmp_files", action="store_true", default=False)
    p.add_argument("--on_extraction", default="print",
                   choices=["print", "save_numpy", "save_pickle", "save_jpg"])
    p.add_argument("--output_path", default="./output")
    p.add_argument("--output_direct", action="store_true",
                   help="save as <stem>.npy instead of <stem>_<key>.npy")
    p.add_argument("--extraction_fps", type=float)
    p.add_argument("--fps_retarget", default="nearest",
                   choices=["nearest", "reencode"],
                   help="how --extraction_fps re-targets the frame grid "
                        "(resnet*/raft/pwc): in-process nearest-frame "
                        "selection (default), or the reference's ffmpeg "
                        "re-encode into --tmp_path")
    p.add_argument("--extract_method", type=str, help="e.g. fix_2 or uni_12")
    p.add_argument("--stack_size", type=int)
    p.add_argument("--step_size", type=int)
    p.add_argument("--streams", nargs="+", choices=["flow", "rgb"])
    p.add_argument("--flow_type", choices=["raft", "pwc", "flow"], default="pwc")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--resize_to_larger_edge", dest="resize_to_smaller_edge",
                   action="store_false", default=True)
    p.add_argument("--side_size", type=int)
    p.add_argument("--show_pred", action="store_true", default=False)
    # TPU-native extras
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--weights_path", type=str, default=None)
    p.add_argument("--allow_random_init", action="store_true", default=False,
                   help="run with random weights when --weights_path is "
                        "absent/incomplete (features will be meaningless; "
                        "for tests/benchmarks)")
    p.add_argument("--decode_workers", type=int, default=2)
    p.add_argument("--decoder", default="auto", choices=["auto", "cv2", "native"])
    p.add_argument("--host_preprocess", default="pil", choices=["pil", "native"])
    p.add_argument("--uint8_transfer", default="on", choices=["on", "off"],
                   help="'off' pre-casts R(2+1)D windows to fp32 on the "
                        "host — for transports with a slow uint8 DMA path")
    p.add_argument("--resume", action="store_true", default=False,
                   help="skip videos whose outputs already exist")
    p.add_argument("--profile_dir", type=str, default=None,
                   help="write a jax.profiler trace + stage timing summary")
    p.add_argument("--sharding", default="queue", choices=["queue", "mesh"],
                   help="queue: one model replica + work queue per device; "
                        "mesh: one GSPMD-sharded executable over a "
                        "(data, model) mesh of all selected devices")
    p.add_argument("--mesh_model", type=int, default=1,
                   help="tensor-parallel axis size of the --sharding mesh")
    p.add_argument("--attn", default="fused",
                   choices=["fused", "flash", "blockwise"],
                   help="attention core for the CLIP family: fused "
                        "full-score (default, best at ViT lengths), the "
                        "Pallas flash kernel, or the XLA blockwise core")
    p.add_argument("--conv3d_impl", default="auto",
                   choices=["auto", "direct", "decomposed"],
                   help="3D-conv lowering (i3d/r21d): XLA's native 3D "
                        "conv, or the checkpoint-identical "
                        "sum-of-2D-convs decomposition (the workaround "
                        "for TPU stacks whose 3D-conv compile crashes); "
                        "auto honors VFT_CONV3D_IMPL, else direct")
    p.add_argument("--video_batch", type=int, default=1,
                   help="aggregate up to N videos' prepared batches into "
                        "one device dispatch (CLIP/ResNet/R21D); 1 = off")
    p.add_argument("--inflight_groups", type=int, default=2,
                   help="async-ingest completion-queue depth: dispatched "
                        "groups that may stay in flight before the loop "
                        "blocks on the oldest fetch (2 = the classic "
                        "double-buffer; 1 = lockstep dispatch-then-fetch)")
    p.add_argument("--frame_delta_threshold", type=float, default=None,
                   help="skip sampled frames whose mean |uint8 delta| vs "
                        "the last kept frame is strictly below this, "
                        "filling their feature rows by copy-forward "
                        "(CLIP family only; default off, 0 is "
                        "bit-identical to off)")
    p.add_argument("--preprocess", default="host", choices=["host", "device"],
                   help="where the resize/crop/normalize chain runs: "
                        "'host' (reference-exact PIL, the default) or "
                        "'device' (raw uint8 frames H2D, one fused jit "
                        "does the PIL-semantics resize + geometry + model "
                        "forward). Covers CLIP/ResNet (224-crop "
                        "contract), raft/pwc (padded flow-grid contract) "
                        "and i3d (min-edge-256 output buckets) — see "
                        "docs/tpu.md's coverage matrix")
    p.add_argument("--spatial_bucket", type=int, default=64,
                   help="--preprocess device: round each source-resolution "
                        "axis up to a multiple of this before compiling "
                        "(O(buckets) executables on mixed-resolution "
                        "corpora, not O(shapes))")
    p.add_argument("--shape_buckets", type=int, nargs="+", default=None,
                   help="explicit resolution bucket edges for XLA static "
                        "shapes (ops/window.py); default derives buckets "
                        "per extractor instead of from a fixed list")
    p.add_argument("--compile_cache", type=str, default=None,
                   help="persistent XLA compilation cache dir "
                        "(jax_compilation_cache_dir): repeat runs skip "
                        "cold-start compiles of the bucketed executables")
    p.add_argument("--compile_cache_min_s", type=float, default=1.0,
                   help="min compile seconds before an executable is "
                        "written to --compile_cache")
    p.add_argument("--retries", type=int, default=2,
                   help="retry budget per video for TRANSIENT failures "
                        "(I/O flakes, decode deadlines, "
                        "RESOURCE_EXHAUSTED); backoff is exponential "
                        "with deterministic jitter")
    p.add_argument("--retry_backoff", type=float, default=0.5,
                   help="base retry backoff seconds (attempt k waits "
                        "base * 2^(k-1) * jitter)")
    p.add_argument("--strict", action="store_true", default=False,
                   help="exit nonzero if the run manifest records any "
                        "failed video, empty-feature warning, or worker "
                        "death")
    p.add_argument("--retry_failed", action="store_true", default=False,
                   help="with --resume: re-attempt videos the manifest "
                        "recorded as permanently failed (default: skip "
                        "them)")
    p.add_argument("--decode_timeout", type=float, default=None,
                   help="wall-clock seconds per decode before a "
                        "DecodeTimeout (transient -> retried with a "
                        "fresh deadline)")
    p.add_argument("--preflight", choices=["on", "off"], default="on",
                   help="probe each input before its first attempt "
                        "(io/probe.py): hostile/corrupt media fails "
                        "permanent with the probe's reason and zero "
                        "retries; 'off' restores discover-at-decode")
    p.add_argument("--max_pixels", type=int, default=None,
                   help="reject/abort any input whose frames exceed this "
                        "many pixels (width*height) — checked against "
                        "declared metadata at preflight AND against "
                        "actual decoded frames")
    p.add_argument("--max_duration_s", type=float, default=None,
                   help="reject/abort any input longer than this many "
                        "seconds (declared at preflight; enforced again "
                        "over actual decode)")
    p.add_argument("--max_decode_bytes", type=int, default=None,
                   help="abort any single video whose decoded RGB bytes "
                        "exceed this budget (a lying frame_count/"
                        "resolution header cannot blow host RAM)")
    p.add_argument("--fault_inject", action="append", default=None,
                   metavar="STAGE:KIND:EVERY_N",
                   help="TEST-ONLY deterministic fault injection: raise/"
                        "stall at STAGE (decode|prepare|dispatch|sink) "
                        "every N calls; KIND in error|corrupt|hang|oom|"
                        "compile|kill; repeatable")
    p.add_argument("--telemetry", choices=["on", "off"], default="on",
                   help="structured telemetry: per-stage spans to "
                        "<output>/_telemetry/spans-*.jsonl, metrics + "
                        "overlap-efficiency block in summary.json, and a "
                        "heartbeat progress line (default on)")
    p.add_argument("--heartbeat_s", type=float, default=30.0,
                   help="seconds between telemetry heartbeat lines "
                        "(videos/sec, decode fps, ETA) on stderr; 0 "
                        "disables")
    p.add_argument("--mesh_context", action="store_true",
                   help="context parallelism under --sharding mesh: shard "
                        "the transformer token axis over the mesh and run "
                        "ring attention (KV shards rotate over ICI); "
                        "composes with --mesh_model head sharding")
    p.add_argument("--cache_dir", type=str, default=None,
                   help="content-addressed feature store root: completed "
                        "features keyed by (content hash, config digest) "
                        "are reused as a file copy instead of re-"
                        "extracting (docs/serving.md); omit to disable")
    p.add_argument("--cache_hash", choices=["fast", "full"], default="fast",
                   help="content hash mode: 'fast' samples head + spread "
                        "chunks + tail (default; never streams a huge "
                        "file), 'full' streams every byte")
    p.add_argument("--ingest_cache_mb", type=int, default=512,
                   help="byte budget (MiB) for the shared-decode frame "
                        "cache used by multi-model fan-out: decode each "
                        "clip once and serve all requested models from "
                        "the cached frames; 0 disables")
    if feature_required:
        # batch fan-out: the serve parser adds its own --feature_types in
        # the serve group, so this one only exists on the batch surface
        p.add_argument(
            "--feature_types", nargs="+", choices=FEATURE_TYPES,
            help="extract SEVERAL feature types in one run, decoding each "
                 "video once (shared-ingest fan-out, extract/plan.py); "
                 "alternative to --feature_type")
    return p


def parse_batch_args(
    argv: Optional[Sequence[str]] = None,
) -> "tuple[ExtractionConfig, List[str]]":
    """Parse the batch CLI into ``(config, feature_types)``. Exactly one
    of ``--feature_type`` / ``--feature_types`` is required; a multi-
    model list routes cli.py through the shared-ingest fan-out
    (extract/plan.py) — one decode per clip, every model served from it.
    The returned config carries the FIRST feature type; callers re-key
    with ``cfg.replace(feature_type=ft)`` per model."""
    p = build_arg_parser()
    args = p.parse_args(argv)
    fts = list(
        dict.fromkeys(
            args.feature_types
            or ([args.feature_type] if args.feature_type else [])
        )
    )
    if not fts:
        p.error("one of --feature_type or --feature_types is required")
    args.feature_type = fts[0]
    # from_namespace drops feature_types (not an ExtractionConfig field)
    cfg = sanity_check(ExtractionConfig.from_namespace(args))
    return cfg, fts


def parse_args(argv: Optional[Sequence[str]] = None) -> ExtractionConfig:
    cfg, _ = parse_batch_args(argv)
    return cfg


# ---------------------------------------------------------------------------
# serve mode (video_features_tpu/serve/): the long-lived daemon's knobs
# ---------------------------------------------------------------------------

# every extraction flag the serve parser inherits still applies (devices,
# dtype, weights, --preprocess device, --compile_cache, telemetry...);
# ServeConfig only adds what a daemon needs on top: which models stay
# resident, the request sources, and the admission-control bounds.


@dataclass
class ServeConfig:
    """Knobs for ``video-features-tpu serve`` (see docs/serving.md)."""

    extraction: ExtractionConfig
    # models kept resident; requests naming anything else are rejected
    feature_types: List[str] = field(default_factory=list)
    # HTTP source (port=None disables; port=0 binds ephemeral, for tests)
    host: str = "127.0.0.1"
    port: Optional[int] = None
    # spool source (air-gapped twin of the HTTP door; None disables)
    spool_dir: Optional[str] = None
    spool_poll_s: float = 0.5
    # admission control: coalescing deadline, fused group bound, and the
    # backpressure bound (reject/503 past max_queue admitted-not-terminal)
    max_batch_wait_ms: float = 50.0
    max_group_size: int = 8
    max_queue: int = 256
    # cross-key dispatch scheduling (serve/scheduler.py): EDF with
    # priority tiers and aging by default; "fifo" is the A/B baseline;
    # "edf-cost" additionally consults the online service-time model
    # (serve/costmodel.py) to demote infeasible groups and rank by
    # latest start time. default_slack_ms is the effective deadline
    # assigned to requests that declare none; aging_ms is one priority-
    # tier boost per that much queue wait (0 disables aging)
    scheduler: str = "edf"
    default_slack_ms: float = 30000.0
    aging_ms: float = 10000.0
    # rolling window for the SLO tracker behind /metrics, /v1/stats,
    # and the heartbeat's deadline-miss rate
    slo_window_s: float = 300.0
    # supervision (serve/supervisor.py): bound on one group's extraction
    # wall time (0 = unbounded), and the per-feature-type circuit
    # breaker (open after `threshold` consecutive group-level failures,
    # half-open probe after `cooldown_s`)
    group_timeout_s: float = 0.0
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 30.0
    # retention for <output>/_requests/: terminal records older than the
    # TTL or beyond the count bound are pruned every retention_sweep_s
    # (0 disables the background sweeper; startup still sweeps once)
    request_ttl_s: float = 86400.0
    max_request_records: int = 10000
    retention_sweep_s: float = 60.0
    # warmup preflight specs, each "<feature_type>:<W>x<H>"
    warmup: List[str] = field(default_factory=list)
    warmup_only: bool = False
    # fail warmup fast when the cost ledger's projected resident HBM for
    # the resident models exceeds this many bytes (0 = unlimited)
    hbm_budget_bytes: int = 0
    # HBM-aware preemption (serve/preemptor.py, ISSUE 18): "on" lets an
    # overcommitting burst evict the lowest-value resident extractor
    # instead of being rejected; hysteresis = one preemption per
    # cooldown + a min-residency guard on every victim
    preempt: str = "off"
    preempt_cooldown_s: float = 30.0
    preempt_min_residency_s: float = 60.0
    # fleet identity + spool work-stealing (serve/sources.py): replicas
    # sharing one spool/output claim via per-replica lease files; a
    # lease whose heartbeat is older than lease_timeout_s is stolen by
    # a survivor (0 disables stealing — single-replica behavior)
    replica_id: Optional[str] = None
    lease_timeout_s: float = 0.0
    # hit-rate-aware shedding: past this fraction of max_queue, likely-
    # cache-miss requests are shed first (0 disables; only acts when
    # the observed cache hit rate says hits are common enough to save
    # room for)
    shed_watermark: float = 0.0

    def warmup_pairs(self) -> List[tuple]:
        return [parse_warmup_spec(s) for s in self.warmup]

    def resolved_replica_id(self) -> str:
        """The configured ``--replica_id`` or a pid-derived default —
        stable for the life of the process, unique enough on one host;
        multi-host fleets should set it explicitly."""
        return self.replica_id or f"r{os.getpid()}"


def parse_warmup_spec(spec: str) -> tuple:
    """``"<feature_type>:<W>x<H>"`` -> ``(feature_type, W, H)``; raises
    ValueError naming the bad spec (feature types may contain ':'-free
    slashes like CLIP-ViT-B/32, so split on the LAST colon)."""
    ft, sep, shape = spec.rpartition(":")
    m = re.fullmatch(r"(\d+)x(\d+)", shape) if sep else None
    if not ft or m is None:
        raise ValueError(
            f"bad warmup spec {spec!r}: expected <feature_type>:<W>x<H>, "
            "e.g. CLIP-ViT-B/32:640x480"
        )
    if ft not in FEATURE_TYPES:
        raise ValueError(f"bad warmup spec {spec!r}: unknown feature_type {ft!r}")
    w, h = int(m.group(1)), int(m.group(2))
    if w < 16 or h < 16:
        raise ValueError(f"bad warmup spec {spec!r}: sides must be >= 16")
    return (ft, w, h)


def build_serve_arg_parser() -> argparse.ArgumentParser:
    """The extraction parser (feature type optional — it is per-request
    in serve mode) plus the daemon flags."""
    p = build_arg_parser(feature_required=False)
    p.description = "Run the long-lived extraction daemon"
    g = p.add_argument_group("serve")
    g.add_argument("--feature_types", nargs="+", choices=FEATURE_TYPES,
                   help="models to keep resident; requests naming "
                        "anything else are rejected (default: just "
                        "--feature_type)")
    g.add_argument("--host", default="127.0.0.1",
                   help="HTTP bind address (default loopback; put a real "
                        "proxy in front before exposing further)")
    g.add_argument("--port", type=int, default=None,
                   help="HTTP port (0 = ephemeral; omit to disable the "
                        "HTTP source)")
    g.add_argument("--spool_dir", type=str, default=None,
                   help="watched spool directory of request JSON files "
                        "(air-gapped source; omit to disable)")
    g.add_argument("--spool_poll_s", type=float, default=0.5,
                   help="spool poll interval in seconds")
    g.add_argument("--max_batch_wait_ms", type=float, default=50.0,
                   help="max milliseconds a request waits for same-"
                        "(feature_type, bucket) company before its group "
                        "dispatches anyway")
    g.add_argument("--max_group_size", type=int, default=8,
                   help="max requests fused into one --video_batch group")
    g.add_argument("--max_queue", type=int, default=256,
                   help="admission bound: requests admitted but not yet "
                        "terminal; past it new requests get 503/rejected")
    g.add_argument("--scheduler", choices=("edf", "fifo", "edf-cost"),
                   default="edf",
                   help="cross-key dispatch order: earliest-effective-"
                        "deadline-first with priority tiers and aging "
                        "(default), plain arrival order, or cost-aware "
                        "EDF that consults the online service-time "
                        "model to skip infeasible groups")
    g.add_argument("--default_slack_ms", type=float, default=30000.0,
                   help="effective deadline assigned to requests that "
                        "declare no deadline_ms (EDF ranking only; "
                        "never expires a request)")
    g.add_argument("--aging_ms", type=float, default=10000.0,
                   help="one priority-tier boost per this much queue "
                        "wait, so low-priority work cannot starve "
                        "(0 disables aging)")
    g.add_argument("--slo_window_s", type=float, default=300.0,
                   help="rolling window (seconds) for the SLO tracker's "
                        "latency quantiles and deadline-miss rate "
                        "(/metrics, /v1/stats, heartbeat)")
    g.add_argument("--group_timeout_s", type=float, default=0.0,
                   help="watchdog bound on one group's extraction wall "
                        "time; on timeout the group fails transient and "
                        "the extractor is rebuilt (0 = unbounded)")
    g.add_argument("--breaker_threshold", type=int, default=3,
                   help="consecutive group-level failures that open a "
                        "feature type's circuit breaker (503 for that "
                        "model only)")
    g.add_argument("--breaker_cooldown_s", type=float, default=30.0,
                   help="seconds an open breaker waits before admitting "
                        "one half-open probe group")
    g.add_argument("--request_ttl_s", type=float, default=86400.0,
                   help="terminal request records older than this are "
                        "pruned from <output>/_requests/")
    g.add_argument("--max_request_records", type=int, default=10000,
                   help="keep at most this many terminal request "
                        "records (oldest pruned first)")
    g.add_argument("--retention_sweep_s", type=float, default=60.0,
                   help="how often the retention sweeper runs "
                        "(0 disables it; startup still sweeps once)")
    g.add_argument("--warmup", action="append", default=None,
                   metavar="FEATURE_TYPE:WxH",
                   help="pre-build the fused executable for this "
                        "(feature_type, resolution) pair before accepting "
                        "traffic; repeatable")
    g.add_argument("--hbm_budget_bytes", type=int, default=0,
                   help="fail warmup when the cost ledger projects the "
                        "resident models' HBM footprint past this many "
                        "bytes (0 = unlimited; see docs/observability.md "
                        "\"Device cost ledger\")")
    g.add_argument("--preempt", choices=("on", "off"), default="off",
                   help="HBM-aware preemption: a burst whose ledger-"
                        "projected footprint cannot fit evicts the "
                        "lowest-value resident extractor (breaker "
                        "teardown + re-warm) instead of being rejected "
                        "(see docs/serving.md \"Fleet operation\")")
    g.add_argument("--preempt_cooldown_s", type=float, default=30.0,
                   help="minimum seconds between preemptions (hysteresis "
                        "so two bursts cannot thrash-evict each other)")
    g.add_argument("--preempt_min_residency_s", type=float, default=60.0,
                   help="a resident extractor younger than this is never "
                        "chosen as a preemption victim")
    g.add_argument("--replica_id", type=str, default=None,
                   help="this replica's stable identity in a multi-"
                        "replica fleet sharing one spool + output store "
                        "(default: pid-derived; set explicitly across "
                        "hosts)")
    g.add_argument("--lease_timeout_s", type=float, default=0.0,
                   help="spool claims become per-replica leases; a lease "
                        "whose heartbeat is older than this is stolen by "
                        "a surviving replica (0 disables work-stealing)")
    g.add_argument("--shed_watermark", type=float, default=0.0,
                   help="queue-saturation fraction of --max_queue past "
                        "which likely-cache-miss requests are shed first "
                        "(cache hits are ~ms and are never shed; 0 "
                        "disables)")
    return p


def parse_serve_args(argv: Optional[Sequence[str]] = None) -> ServeConfig:
    """Parse ``serve [warmup] <flags>`` into a validated ServeConfig.
    A leading bare ``warmup`` token selects preflight-only mode (build
    the declared executables against --compile_cache, then exit)."""
    argv = list(argv if argv is not None else [])
    warmup_only = bool(argv) and argv[0] == "warmup"
    if warmup_only:
        argv = argv[1:]
    args = build_serve_arg_parser().parse_args(argv)
    feature_types = args.feature_types or [args.feature_type or ExtractionConfig.feature_type]
    cfg = ExtractionConfig.from_namespace(args)
    cfg = sanity_check(cfg.replace(feature_type=feature_types[0]))
    scfg = ServeConfig(
        extraction=cfg,
        feature_types=list(dict.fromkeys(feature_types)),
        host=args.host,
        port=args.port,
        spool_dir=args.spool_dir,
        spool_poll_s=args.spool_poll_s,
        max_batch_wait_ms=args.max_batch_wait_ms,
        max_group_size=args.max_group_size,
        max_queue=args.max_queue,
        scheduler=args.scheduler,
        default_slack_ms=args.default_slack_ms,
        aging_ms=args.aging_ms,
        slo_window_s=args.slo_window_s,
        group_timeout_s=args.group_timeout_s,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
        request_ttl_s=args.request_ttl_s,
        max_request_records=args.max_request_records,
        retention_sweep_s=args.retention_sweep_s,
        warmup=list(args.warmup or []),
        warmup_only=warmup_only,
        hbm_budget_bytes=args.hbm_budget_bytes,
        preempt=args.preempt,
        preempt_cooldown_s=args.preempt_cooldown_s,
        preempt_min_residency_s=args.preempt_min_residency_s,
        replica_id=args.replica_id,
        lease_timeout_s=args.lease_timeout_s,
        shed_watermark=args.shed_watermark,
    )
    return sanity_check_serve(scfg)


def sanity_check_serve(scfg: ServeConfig) -> ServeConfig:
    if not scfg.feature_types:
        raise ValueError("serve needs at least one --feature_types entry")
    for ft in scfg.feature_types:
        if ft not in FEATURE_TYPES:
            raise ValueError(f"unknown feature_type in --feature_types: {ft!r}")
        # fail at startup, not on the first request of that type
        sanity_check(scfg.extraction.replace(feature_type=ft))
    if not str(scfg.host).strip():
        raise ValueError("--host must be a non-empty bind address")
    if scfg.spool_dir is not None and not str(scfg.spool_dir).strip():
        raise ValueError("--spool_dir must be a non-empty path")
    if scfg.max_group_size < 1:
        raise ValueError(f"max_group_size must be >= 1, got {scfg.max_group_size}")
    if scfg.max_queue < 1:
        raise ValueError(f"max_queue must be >= 1, got {scfg.max_queue}")
    if scfg.max_batch_wait_ms < 0:
        raise ValueError(f"max_batch_wait_ms must be >= 0, got {scfg.max_batch_wait_ms}")
    if scfg.spool_poll_s <= 0:
        raise ValueError(f"spool_poll_s must be > 0, got {scfg.spool_poll_s}")
    if scfg.scheduler not in ("edf", "fifo", "edf-cost"):
        raise ValueError(
            f"scheduler must be 'edf', 'fifo', or 'edf-cost', got {scfg.scheduler!r}"
        )
    if scfg.default_slack_ms <= 0:
        raise ValueError(f"default_slack_ms must be > 0, got {scfg.default_slack_ms}")
    if scfg.aging_ms < 0:
        raise ValueError(f"aging_ms must be >= 0, got {scfg.aging_ms}")
    if scfg.slo_window_s <= 0:
        raise ValueError(f"slo_window_s must be > 0, got {scfg.slo_window_s}")
    if scfg.group_timeout_s < 0:
        raise ValueError(f"group_timeout_s must be >= 0, got {scfg.group_timeout_s}")
    if scfg.breaker_threshold < 1:
        raise ValueError(f"breaker_threshold must be >= 1, got {scfg.breaker_threshold}")
    if scfg.breaker_cooldown_s < 0:
        raise ValueError(f"breaker_cooldown_s must be >= 0, got {scfg.breaker_cooldown_s}")
    if scfg.request_ttl_s <= 0:
        raise ValueError(f"request_ttl_s must be > 0, got {scfg.request_ttl_s}")
    if scfg.max_request_records < 1:
        raise ValueError(f"max_request_records must be >= 1, got {scfg.max_request_records}")
    if scfg.retention_sweep_s < 0:
        raise ValueError(f"retention_sweep_s must be >= 0, got {scfg.retention_sweep_s}")
    if scfg.hbm_budget_bytes < 0:
        raise ValueError(f"hbm_budget_bytes must be >= 0, got {scfg.hbm_budget_bytes}")
    if scfg.preempt not in ("on", "off"):
        raise ValueError(f"preempt must be 'on' or 'off', got {scfg.preempt!r}")
    if scfg.preempt_cooldown_s < 0:
        raise ValueError(
            f"preempt_cooldown_s must be >= 0, got {scfg.preempt_cooldown_s}")
    if scfg.preempt_min_residency_s < 0:
        raise ValueError(
            "preempt_min_residency_s must be >= 0, got "
            f"{scfg.preempt_min_residency_s}")
    if scfg.replica_id is not None and not re.fullmatch(
            r"[A-Za-z0-9][A-Za-z0-9._-]{0,63}", scfg.replica_id):
        # replica ids become claim-file suffixes and heartbeat filenames
        raise ValueError(
            "replica_id must be 1-64 chars of [A-Za-z0-9._-] starting "
            f"alphanumeric, got {scfg.replica_id!r}")
    if scfg.lease_timeout_s < 0:
        raise ValueError(
            f"lease_timeout_s must be >= 0, got {scfg.lease_timeout_s}")
    if not 0 <= scfg.shed_watermark <= 1:
        raise ValueError(
            f"shed_watermark must be in [0, 1], got {scfg.shed_watermark}")
    scfg.warmup_pairs()  # raises naming any bad spec
    if scfg.warmup_only and not scfg.warmup:
        raise ValueError("serve warmup needs at least one --warmup FEATURE_TYPE:WxH")
    if scfg.extraction.on_extraction not in ("save_numpy", "save_pickle"):
        # the daemon's unit of output is a result file per request;
        # 'print' has nothing durable to point the status record at
        scfg = dataclasses.replace(
            scfg, extraction=scfg.extraction.replace(on_extraction="save_numpy")
        )
    for ft, w, h in scfg.warmup_pairs():
        if ft not in scfg.feature_types:
            raise ValueError(
                f"--warmup {ft}:{w}x{h} names a feature_type not in "
                f"--feature_types ({', '.join(scfg.feature_types)})"
            )
    return scfg
