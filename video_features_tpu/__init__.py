"""TPU-native video feature extraction framework.

A ground-up JAX/XLA/Flax/Pallas rebuild of the capabilities of
Kamino666/video_features (reference: /root/reference): per-video visual
(CLIP ViT, ResNet, I3D, R(2+1)D), optical-flow (RAFT, PWC-Net) and audio
(VGGish) features from pretrained nets, data-parallel across accelerator
chips.

Design stance (see SURVEY.md §7): the reference's *contracts* are kept —
CLI flags and feature types (ref main.py:94-137), the output dict
``{feature_type, 'fps', 'timestamps_ms'}`` routed through an output sink
(ref utils/utils.py:50-114), per-video error isolation, and the
external-call API. The *internals* are TPU-first: Flax modules compiled
once per device with ``jax.jit`` on bucketed static shapes, a host-side
decode/prefetch pipeline feeding device queues, XLA collectives over a
``jax.sharding.Mesh`` for the batched multi-chip path, and Pallas kernels
for the reference's custom CUDA ops.
"""

__version__ = "0.1.0"

from video_features_tpu.config import ExtractionConfig, build_arg_parser  # noqa: F401
