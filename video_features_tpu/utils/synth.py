"""Synthetic media generation shared by tests and bench.py.

This environment has no ffmpeg binary and zero egress (no sample
downloads), so deterministic cv2-written clips stand in for real videos:
a moving gradient (smooth global motion for flow models) plus a random
box (texture + occlusion edges).
"""

from __future__ import annotations

import numpy as np


def synth_video(
    path: str,
    n_frames: int = 60,
    width: int = 320,
    height: int = 240,
    fps: float = 25.0,
    seed: int = 0,
    static: bool = False,
) -> str:
    """``static=True`` freezes the scene: every frame repeats frame 0's
    gradient+box (modulo codec noise) — the near-duplicate corpus the
    --frame_delta_threshold gate and its bench/tests are pinned on."""
    import cv2

    writer = cv2.VideoWriter(
        path, cv2.VideoWriter_fourcc(*"mp4v"), fps, (width, height)
    )
    assert writer.isOpened(), "cv2.VideoWriter could not open mp4 writer"
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:height, 0:width]
    for t in range(n_frames):
        ts = 0 if static else t
        frame = np.stack(
            [
                (xx + 2 * ts) % 256,
                (yy + ts) % 256,
                np.full((height, width), (ts * 4) % 256),
            ],
            axis=-1,
        ).astype(np.uint8)
        x0 = (10 + 3 * ts) % (width - 40)
        y0 = (20 + 2 * ts) % (height - 40)
        color = rng.randint(0, 255, 3)  # one rng draw per frame either way
        if static and t > 0:
            color = box_color
        else:
            box_color = color
        frame[y0 : y0 + 30, x0 : x0 + 30] = color
        writer.write(frame)
    writer.release()
    return path
