from video_features_tpu.utils.labels import load_classes, show_predictions_on_dataset  # noqa: F401
