"""Debug rail: top-5 class printing against ImageNet-1k / Kinetics-400.

The class-name lists are data assets (video_features_tpu/data/*.json,
converted from the reference's utils/IN_label_map.txt and
utils/K400_label_map.txt). Behavior mirrors ref utils/utils.py:19-46:
print ``logit softmax class`` for the top-5 per batch row.
"""

from __future__ import annotations

import functools
import json
import os
from typing import List

import numpy as np

_DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "data")
_DATASET_FILES = {
    "imagenet": "imagenet_classes.json",
    "kinetics": "kinetics400_classes.json",
}


@functools.lru_cache(maxsize=None)
def load_classes(dataset: str) -> List[str]:
    try:
        fname = _DATASET_FILES[dataset]
    except KeyError:
        raise NotImplementedError(f"unknown label dataset: {dataset}") from None
    with open(os.path.join(_DATA_DIR, fname)) as f:
        return json.load(f)


def show_predictions_on_dataset(logits: np.ndarray, dataset: str, k: int = 5) -> None:
    """Print top-k (logit, softmax, class) per row (ref utils/utils.py:19-46)."""
    classes = load_classes(dataset)
    logits = np.asarray(logits, dtype=np.float32)
    if logits.ndim == 1:
        logits = logits[None]
    z = logits - logits.max(axis=-1, keepdims=True)
    softmaxes = np.exp(z) / np.exp(z).sum(axis=-1, keepdims=True)
    top_idx = np.argsort(-softmaxes, axis=-1)[:, :k]
    for b in range(len(logits)):
        for idx in top_idx[b]:
            print(f"{logits[b, idx]:.3f} {softmaxes[b, idx]:.3f} {classes[idx]}")
        print()
