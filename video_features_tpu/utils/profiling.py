"""Tracing & per-stage timing — first-class here, absent in the reference
(SURVEY.md §5: tqdm was its only observability).

``device_trace(dir)`` wraps a region in a ``jax.profiler`` trace
(XPlane/TensorBoard format, viewable with xprof/tensorboard-profile).
The profiler is process-global, so concurrent device workers share one
refcounted trace session. ``StageTimer`` aggregates wall-clock per
pipeline stage (decode / preprocess / device / sink) across videos.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

_trace_lock = threading.Lock()
_trace_refs = 0


@contextmanager
def device_trace(profile_dir: Optional[str]) -> Iterator[None]:
    """Refcounted jax.profiler trace over a region; no-op when dir is None.

    Exception-safe: if ``start_trace`` raises (unwritable dir, profiler
    already running outside us), the refcount is NOT bumped and any
    half-started profiler session is stopped best-effort, so a later
    caller sees refs==0 and can start cleanly instead of deadlocking on
    a wedged session or double-starting. The dir is created up front —
    the profiler's own error for a missing path is opaque."""
    global _trace_refs
    if not profile_dir:
        yield
        return
    import os

    import jax

    os.makedirs(profile_dir, exist_ok=True)
    with _trace_lock:
        if _trace_refs == 0:
            try:
                jax.profiler.start_trace(profile_dir)
            except BaseException:
                try:
                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 - nothing was started; leave refs at 0
                    pass
                raise
        _trace_refs += 1
    try:
        yield
    finally:
        with _trace_lock:
            _trace_refs -= 1
            if _trace_refs == 0:
                jax.profiler.stop_trace()


class StageTimer:
    """Thread-safe accumulated wall time per named stage."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.seconds[name] += dt
                self.counts[name] += 1

    def summary(self) -> str:
        with self._lock:
            rows = [
                f"  {name:<12} {self.seconds[name]:8.2f}s over {self.counts[name]} calls"
                for name in sorted(self.seconds)
            ]
        return "per-stage wall time:\n" + "\n".join(rows) if rows else ""
