"""Optical-flow -> RGB visualization (Middlebury color wheel).

The debug rail the reference exposes through ``--show_pred`` on the flow
extractors (ref models/raft/raft_src/utils/flow_viz.py and
models/pwc/pwc_src/utils/flow_viz.py; invoked from
models/raft/extract_raft.py:165-178). Pure NumPy; colors follow the
standard Baker et al. wheel (55 hue bins: RY/YG/GC/CB/BM/MR arcs).
"""

from __future__ import annotations

import numpy as np


def _make_colorwheel() -> np.ndarray:
    RY, YG, GC, CB, BM, MR = 15, 6, 4, 11, 13, 6
    wheel = np.zeros((RY + YG + GC + CB + BM + MR, 3))
    col = 0
    for n, (a, b, flip) in (
        (RY, (0, 1, False)),
        (YG, (1, 0, True)),
        (GC, (1, 2, False)),
        (CB, (2, 1, True)),
        (BM, (2, 0, False)),
        (MR, (0, 2, True)),
    ):
        ramp = np.floor(255 * np.arange(n) / n)
        wheel[col : col + n, a] = 255 - ramp if flip else 255
        wheel[col : col + n, b] = ramp if not flip else 255
        col += n
    return wheel


_COLORWHEEL = _make_colorwheel()


def flow_uv_to_colors(u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Map normalized (|uv| <= 1) flow components to RGB uint8."""
    ncols = _COLORWHEEL.shape[0]
    rad = np.sqrt(u ** 2 + v ** 2)
    a = np.arctan2(-v, -u) / np.pi
    fk = (a + 1) / 2 * (ncols - 1)
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = (fk - k0)[..., None]
    col = (1 - f) * _COLORWHEEL[k0] / 255.0 + f * _COLORWHEEL[k1] / 255.0
    small = rad[..., None] <= 1
    col = np.where(small, 1 - rad[..., None] * (1 - col), col * 0.75)
    return np.floor(255 * col).astype(np.uint8)


def flow_to_image(flow_uv: np.ndarray, clip_flow: float = None) -> np.ndarray:
    """(H, W, 2) flow -> (H, W, 3) RGB uint8, magnitude-normalized."""
    assert flow_uv.ndim == 3 and flow_uv.shape[2] == 2, "expected (H, W, 2) flow"
    if clip_flow is not None:
        flow_uv = np.clip(flow_uv, 0, clip_flow)
    u, v = flow_uv[..., 0], flow_uv[..., 1]
    rad_max = np.max(np.sqrt(u ** 2 + v ** 2))
    eps = 1e-5
    return flow_uv_to_colors(u / (rad_max + eps), v / (rad_max + eps))


def show_flow_on_frame(flow: np.ndarray, frame: np.ndarray) -> None:
    """cv2.imshow the frame stacked over its flow rendering, waiting for a
    key (ref models/raft/extract_raft.py:165-178). No-op off-display."""
    import cv2

    img_flow = np.concatenate([frame.astype(np.uint8), flow_to_image(flow)], axis=0)
    try:
        cv2.imshow("Press any key to see the next frame...", img_flow[:, :, ::-1] / 255.0)
        cv2.waitKey()
    except cv2.error as e:  # headless host: report instead of crashing the job
        print(f"(show_pred) display unavailable ({e}); flow stats: "
              f"min={flow.min():.3f} max={flow.max():.3f}")
