"""Static-shape helpers: XLA compiles one executable per input shape, so
variable-length frame batches are padded up to a small set of bucket sizes
(SURVEY.md §7 hard part #2). The pad rows are sliced off after the model
runs — features for them are computed and discarded, which on TPU is far
cheaper than a recompile per length.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

from video_features_tpu.runtime import telemetry


def bucket_size(n: int, multiple: int = 8, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest allowed padded size >= n."""
    if buckets:
        for b in sorted(buckets):
            if n <= b:
                return b
        return int(math.ceil(n / multiple) * multiple)
    return max(int(math.ceil(n / multiple) * multiple), multiple)


def pad_batch(x: np.ndarray, to: int) -> np.ndarray:
    """Zero-pad axis 0 of ``x`` up to ``to`` rows."""
    if x.shape[0] == to:
        return x
    pad = [(0, to - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad)


def spatial_bucket(
    h: int, w: int, multiple: int = 64,
    buckets: Optional[Sequence[Tuple[int, int]]] = None,
) -> Tuple[int, int]:
    """The time-axis bucketing above, extended to H x W: the padded
    (bucket_h, bucket_w) a raw-resolution frame rounds up to under
    ``--preprocess device``. Each axis rounds up independently to the
    next ``multiple`` (floor ``multiple``), so a variable-resolution
    corpus compiles O(distinct buckets) executables instead of
    O(distinct shapes); explicit ``buckets`` — (h, w) pairs — pick the
    smallest that fits both axes instead. The pad region carries zero
    resize weight (ops/resize.py::fused_resize_crop_matrices), so
    bucketing never changes the output, only the compiled shape."""
    if buckets:
        for bh, bw in sorted(buckets, key=lambda b: b[0] * b[1]):
            if h <= bh and w <= bw:
                telemetry.note_bucket((int(bh), int(bw)))
                return int(bh), int(bw)
    out = bucket_size(h, multiple), bucket_size(w, multiple)
    # distinct buckets scale the recompile watch's runtime allowance
    # (runtime/telemetry.py): compiles may grow O(buckets), never O(videos)
    telemetry.note_bucket(out)
    return out


def flow_output_bucket(
    oh: int,
    ow: int,
    multiple: int = 64,
    div: int = 8,
    min_size: int = 128,
) -> Tuple[int, int]:
    """Output-side bucket for a shape-contracted flow grid: the resized
    (oh, ow) first rounds up to the flow model's padded input grid
    (``/div`` multiples with a ``min_size`` floor — RAFT's InputPadder
    geometry, models/raft/model.py::input_grid), then up to ``multiple``
    so a variable-resolution corpus lands on a small set of output
    contracts. ``multiple=div`` collapses the second rounding: the bucket
    IS the exact padder grid (the standalone-flow case, where exact
    geometry buys bit parity with host ``InputPadder.pad``). These ids
    join the aggregation key, so ``--video_batch`` still fuses per
    (input bucket, output bucket) pair."""
    tgt_h = max(int(math.ceil(oh / div) * div), min_size)
    tgt_w = max(int(math.ceil(ow / div) * div), min_size)
    out = bucket_size(tgt_h, multiple), bucket_size(tgt_w, multiple)
    telemetry.note_bucket(("flow",) + out)
    return out


def pad_hw(x: np.ndarray, to_h: int, to_w: int) -> np.ndarray:
    """Zero-pad the (H, W) axes of (..., H, W, C) frames up to the
    spatial bucket (the uint8-HWC layout the decode path produces)."""
    h, w = x.shape[-3], x.shape[-2]
    if h == to_h and w == to_w:
        return x
    pad = [(0, 0)] * (x.ndim - 3) + [(0, to_h - h), (0, to_w - w), (0, 0)]
    return np.pad(x, pad)
