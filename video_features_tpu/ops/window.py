"""Static-shape helpers: XLA compiles one executable per input shape, so
variable-length frame batches are padded up to a small set of bucket sizes
(SURVEY.md §7 hard part #2). The pad rows are sliced off after the model
runs — features for them are computed and discarded, which on TPU is far
cheaper than a recompile per length.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np


def bucket_size(n: int, multiple: int = 8, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest allowed padded size >= n."""
    if buckets:
        for b in sorted(buckets):
            if n <= b:
                return b
        return int(math.ceil(n / multiple) * multiple)
    return max(int(math.ceil(n / multiple) * multiple), multiple)


def pad_batch(x: np.ndarray, to: int) -> np.ndarray:
    """Zero-pad axis 0 of ``x`` up to ``to`` rows."""
    if x.shape[0] == to:
        return x
    pad = [(0, to - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad)
