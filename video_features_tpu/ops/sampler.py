"""Bilinear grid sampling for TPU — the op JAX doesn't ship.

Both flow networks need ``torch.nn.functional.grid_sample`` semantics:
RAFT's correlation-pyramid lookup samples with pixel coordinates and
``align_corners=True`` (ref models/raft/raft_src/utils/utils.py:57-71,
called 4 levels x 20 GRU iterations), and PWC's ``Backward`` warp samples
a normalized grid + flow with zero padding (ref
models/pwc/pwc_src/pwc_net.py:23-41). SURVEY.md §7 ranks this the #1 hard
part.

The implementation is a vectorized **gather + lerp** (TPU-friendly: one
flat ``take_along_axis`` per corner over the fused H*W axis; no scatter),
with exact torch unnormalization for both ``align_corners`` conventions
and ``zeros``/``border`` padding.
"""

from __future__ import annotations

import jax.numpy as jnp


def _unnormalize(coord, size: int, align_corners: bool):
    """[-1, 1] grid coordinate -> continuous pixel index, torch convention."""
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def grid_sample(
    img: jnp.ndarray,
    grid: jnp.ndarray,
    padding_mode: str = "zeros",
    align_corners: bool = False,
) -> jnp.ndarray:
    """Bilinear sample ``img`` (N, C, H, W) at ``grid`` (N, Hg, Wg, 2).

    ``grid[..., 0]`` is x in [-1, 1], ``grid[..., 1]`` is y — exactly
    ``torch.nn.functional.grid_sample(mode='bilinear')``.
    """
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(f"padding_mode={padding_mode!r}")
    N, C, H, W = img.shape

    x = _unnormalize(grid[..., 0], W, align_corners)  # (N, Hg, Wg)
    y = _unnormalize(grid[..., 1], H, align_corners)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def corner(xi, yi):
        """Gather img[n, :, yi, xi] with padding; also return in-bounds mask."""
        inb = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        flat = (yc * W + xc).reshape(N, 1, -1)  # (N, 1, Hg*Wg)
        vals = jnp.take_along_axis(
            img.reshape(N, C, H * W), jnp.broadcast_to(flat, (N, C, flat.shape[-1])),
            axis=2,
        ).reshape(N, C, *x.shape[1:])
        if padding_mode == "zeros":
            vals = vals * inb[:, None].astype(img.dtype)
        return vals

    v00 = corner(x0, y0)
    v01 = corner(x0 + 1, y0)
    v10 = corner(x0, y0 + 1)
    v11 = corner(x0 + 1, y0 + 1)

    wx = wx[:, None].astype(img.dtype)
    wy = wy[:, None].astype(img.dtype)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def bilinear_sampler(
    img: jnp.ndarray,
    coords: jnp.ndarray,
    mask: bool = False,
):
    """RAFT's pixel-coordinate wrapper (ref raft_src/utils/utils.py:57-71):
    coords (N, Hg, Wg, 2) in pixels; align_corners=True, zero padding."""
    H, W = img.shape[-2:]
    xgrid = 2.0 * coords[..., 0] / (W - 1) - 1.0
    ygrid = 2.0 * coords[..., 1] / (H - 1) - 1.0
    grid = jnp.stack([xgrid, ygrid], axis=-1)
    out = grid_sample(img, grid, padding_mode="zeros", align_corners=True)
    if mask:
        m = (xgrid > -1) & (ygrid > -1) & (xgrid < 1) & (ygrid < 1)
        return out, m.astype(img.dtype)
    return out
