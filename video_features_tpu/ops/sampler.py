"""Bilinear grid sampling for TPU — the op JAX doesn't ship.

Both flow networks need ``torch.nn.functional.grid_sample`` semantics:
RAFT's correlation-pyramid lookup samples with pixel coordinates and
``align_corners=True`` (ref models/raft/raft_src/utils/utils.py:57-71,
called 4 levels x 20 GRU iterations), and PWC's ``Backward`` warp samples
a normalized grid + flow with zero padding (ref
models/pwc/pwc_src/pwc_net.py:23-41). SURVEY.md §7 ranks this the #1 hard
part.

The implementation is a vectorized **gather + lerp** (TPU-friendly: one
flat ``take_along_axis`` per corner over the fused H*W axis; no scatter),
with exact torch unnormalization for both ``align_corners`` conventions
and ``zeros``/``border`` padding.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def _unnormalize(coord, size: int, align_corners: bool):
    """[-1, 1] grid coordinate -> continuous pixel index, torch convention."""
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def grid_sample(
    img: jnp.ndarray,
    grid: jnp.ndarray,
    padding_mode: str = "zeros",
    align_corners: bool = False,
) -> jnp.ndarray:
    """Bilinear sample ``img`` (N, C, H, W) at ``grid`` (N, Hg, Wg, 2).

    ``grid[..., 0]`` is x in [-1, 1], ``grid[..., 1]`` is y — exactly
    ``torch.nn.functional.grid_sample(mode='bilinear')``.
    """
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(f"padding_mode={padding_mode!r}")
    N, C, H, W = img.shape

    x = _unnormalize(grid[..., 0], W, align_corners)  # (N, Hg, Wg)
    y = _unnormalize(grid[..., 1], H, align_corners)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def corner(xi, yi):
        """Gather img[n, :, yi, xi] with padding; also return in-bounds mask."""
        inb = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        flat = (yc * W + xc).reshape(N, 1, -1)  # (N, 1, Hg*Wg)
        vals = jnp.take_along_axis(
            img.reshape(N, C, H * W), jnp.broadcast_to(flat, (N, C, flat.shape[-1])),
            axis=2,
        ).reshape(N, C, *x.shape[1:])
        if padding_mode == "zeros":
            vals = vals * inb[:, None].astype(img.dtype)
        return vals

    v00 = corner(x0, y0)
    v01 = corner(x0 + 1, y0)
    v10 = corner(x0, y0 + 1)
    v11 = corner(x0 + 1, y0 + 1)

    wx = wx[:, None].astype(img.dtype)
    wy = wy[:, None].astype(img.dtype)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def bilinear_sampler(
    img: jnp.ndarray,
    coords: jnp.ndarray,
    mask: bool = False,
):
    """RAFT's pixel-coordinate wrapper (ref raft_src/utils/utils.py:57-71):
    coords (N, Hg, Wg, 2) in pixels; align_corners=True, zero padding."""
    H, W = img.shape[-2:]
    xgrid = 2.0 * coords[..., 0] / (W - 1) - 1.0
    ygrid = 2.0 * coords[..., 1] / (H - 1) - 1.0
    grid = jnp.stack([xgrid, ygrid], axis=-1)
    out = grid_sample(img, grid, padding_mode="zeros", align_corners=True)
    if mask:
        m = (xgrid > -1) & (ygrid > -1) & (xgrid < 1) & (ygrid < 1)
        return out, m.astype(img.dtype)
    return out


# --- frame-delta gating (--frame_delta_threshold) -------------------------
#
# FASTER (PAPERS.md) observes that adjacent sampled frames of real video
# are largely redundant; for frame-level extractors (the CLIP family) a
# near-duplicate frame's feature can be copied from its predecessor
# instead of re-encoded. The gate runs host-side on the decoded uint8
# frames — skipped frames never cross H2D — and the fetch path expands
# the kept rows back to the full sampling grid with ``copy_forward``.


def frame_delta_keep_mask(frames, threshold: float) -> np.ndarray:
    """Boolean keep-mask over ``frames`` (sequence of HWC uint8 arrays).

    Frame 0 is always kept. Frame i is SKIPPED when its mean absolute
    uint8 delta vs the last *kept* frame is strictly below
    ``threshold`` — comparing against the last kept (not merely
    previous) frame bounds the accumulated drift of a long
    slowly-changing shot to one threshold, and the strict inequality
    makes ``threshold=0`` keep every frame (the bit-identical parity
    contract for the flag's zero value)."""
    n = len(frames)
    keep = np.ones(n, dtype=bool)
    if n <= 1 or threshold <= 0:
        return keep
    last = np.asarray(frames[0], dtype=np.int16)
    for i in range(1, n):
        cur = np.asarray(frames[i], dtype=np.int16)
        if float(np.mean(np.abs(cur - last))) < threshold:
            keep[i] = False
        else:
            last = cur
    return keep


def copy_forward(rows: np.ndarray, keep: np.ndarray) -> np.ndarray:
    """Expand per-kept-frame feature ``rows`` back to the full sampling
    grid: position i takes the row of the latest kept frame at or
    before i (``keep[0]`` is always True, so every position has one).
    ``rows`` has ``keep.sum()`` rows; the result has ``keep.size``."""
    keep = np.asarray(keep, dtype=bool)
    return rows[np.cumsum(keep) - 1]
