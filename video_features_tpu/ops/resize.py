"""Device-side bilinear resize with torch ``F.interpolate`` semantics.

Needed because the flow nets bake resizes into their forward passes with
*both* corner conventions: RAFT's ``upflow8`` uses ``align_corners=True``
(ref raft_src/utils/utils.py:89-91); PWC resizes inputs to /64 multiples
and upsamples flow with the default ``align_corners=False`` (ref
pwc_src/pwc_net.py:241-261). ``jax.image.resize('linear')`` only matches
the half-pixel (False) convention, so both are implemented here on the
shared gather machinery.
"""

from __future__ import annotations

import jax.numpy as jnp


def _source_coords(out_size: int, in_size: int, align_corners: bool) -> jnp.ndarray:
    i = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners:
        if out_size == 1:
            return jnp.zeros((1,), jnp.float32)
        return i * (in_size - 1) / (out_size - 1)
    scale = in_size / out_size
    return jnp.clip((i + 0.5) * scale - 0.5, 0.0, float(in_size - 1))


def _lerp_axis(x: jnp.ndarray, out_size: int, axis: int, align_corners: bool) -> jnp.ndarray:
    in_size = x.shape[axis]
    if in_size == out_size:
        return x
    src = _source_coords(out_size, in_size, align_corners)
    lo = jnp.floor(src).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_size - 1)
    w = (src - lo).astype(x.dtype)
    xl = jnp.take(x, lo, axis=axis)
    xh = jnp.take(x, hi, axis=axis)
    shape = [1] * x.ndim
    shape[axis] = out_size
    w = w.reshape(shape)
    return xl * (1 - w) + xh * w


def resize_bilinear(
    x: jnp.ndarray,
    size,
    align_corners: bool = False,
) -> jnp.ndarray:
    """Resize the last two axes of ``x`` (..., H, W) to ``size`` = (H', W')."""
    H, W = size
    x = _lerp_axis(x, H, x.ndim - 2, align_corners)
    x = _lerp_axis(x, W, x.ndim - 1, align_corners)
    return x
