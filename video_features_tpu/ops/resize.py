"""Device-side resize.

Two families live here:

- ``resize_bilinear`` — torch ``F.interpolate`` semantics, needed because
  the flow nets bake resizes into their forward passes with *both* corner
  conventions: RAFT's ``upflow8`` uses ``align_corners=True``
  (ref raft_src/utils/utils.py:89-91); PWC resizes inputs to /64 multiples
  and upsamples flow with the default ``align_corners=False`` (ref
  pwc_src/pwc_net.py:241-261). ``jax.image.resize('linear')`` only matches
  the half-pixel (False) convention, so both are implemented on the shared
  gather machinery.

- the PIL-semantics resamplers (``resample_matrix`` / ``resize_bicubic`` /
  ``fused_resize_crop_matrices`` / ``fused_resize_crop_banded``) — the
  device half of ``--preprocess device``. PIL's convolution resample
  (what torchvision's Resize bottoms out in, and what the pip ``clip``
  package's bicubic preprocess uses) is an antialiased separable filter:
  half-pixel centers, support scaled by the downsampling ratio, taps
  truncated at the image edge and renormalized. For a fixed (in, out)
  size pair the taps are a constant dense (out, in) matrix, and a center
  crop composes into the SAME matrix by building only the output
  rows/cols inside the crop window. What actually ships to the device is
  the matrix's banded form — per-output-pixel (weights, indices) of the
  ~K contiguous nonzero taps — because the dense matmul pays the whole
  bucket-padded axis per output pixel where PIL pays K: free on an MXU,
  a ~50x FLOP tax on a CPU core. The taps are computed on the host per
  source resolution and shipped as jit *inputs*, with K fixed per bucket
  (ops/window.py::spatial_bucket), so one executable serves every source
  resolution within a bucket: padded columns simply carry zero weight,
  which is the per-bucket valid-region masking — pad pixels can never
  bleed into the resize.

  PIL rounds+clips to uint8 between the horizontal and vertical passes
  and after the last one — load-bearing under bicubic overshoot, so the
  fused device chain (ops/preprocess.py::device_preprocess_frames)
  replays that quantization between its two passes, and accumulates taps
  in PIL's own ascending-index order. The residual vs PIL is PIL's 8-bit
  fixed-point coefficient table, ~1/255 per pixel (tolerance-pinned in
  tests/test_ops.py).
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Optional, Tuple

import numpy as np

import jax.numpy as jnp


def _source_coords(out_size: int, in_size: int, align_corners: bool) -> jnp.ndarray:
    i = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners:
        if out_size == 1:
            return jnp.zeros((1,), jnp.float32)
        return i * (in_size - 1) / (out_size - 1)
    scale = in_size / out_size
    return jnp.clip((i + 0.5) * scale - 0.5, 0.0, float(in_size - 1))


def _lerp_axis(x: jnp.ndarray, out_size: int, axis: int, align_corners: bool) -> jnp.ndarray:
    in_size = x.shape[axis]
    if in_size == out_size:
        return x
    src = _source_coords(out_size, in_size, align_corners)
    lo = jnp.floor(src).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_size - 1)
    w = (src - lo).astype(x.dtype)
    xl = jnp.take(x, lo, axis=axis)
    xh = jnp.take(x, hi, axis=axis)
    shape = [1] * x.ndim
    shape[axis] = out_size
    w = w.reshape(shape)
    return xl * (1 - w) + xh * w


def resize_bilinear(
    x: jnp.ndarray,
    size,
    align_corners: bool = False,
) -> jnp.ndarray:
    """Resize the last two axes of ``x`` (..., H, W) to ``size`` = (H', W')."""
    H, W = size
    x = _lerp_axis(x, H, x.ndim - 2, align_corners)
    x = _lerp_axis(x, W, x.ndim - 1, align_corners)
    return x


# --- PIL-semantics resample matrices (--preprocess device) -----------------

def _pil_filter_weight(method: str, x: float) -> float:
    """PIL filter kernels: 'bilinear' = triangle (support 1), 'bicubic' =
    Keys cubic a=-0.5 (support 2) — the two kernels the reference's
    preprocess chains use (torchvision Resize / pip-clip preprocess)."""
    x = abs(x)
    if method == "bicubic":
        a = -0.5
        if x < 1.0:
            return ((a + 2.0) * x - (a + 3.0)) * x * x + 1.0
        if x < 2.0:
            return (((x - 5.0) * x + 8.0) * x - 4.0) * a
        return 0.0
    return 1.0 - x if x < 1.0 else 0.0


_SUPPORT = {"bilinear": 1.0, "bicubic": 2.0}


def resample_matrix(
    in_size: int, out_size: int, method: str = "bicubic"
) -> np.ndarray:
    """Dense (out_size, in_size) float32 matrix of PIL's antialiased
    convolution resample along one axis: half-pixel centers, support
    scaled by the downsampling ratio, edge taps truncated + renormalized.
    ``matrix @ column`` == PIL's per-axis pass (minus its intermediate
    uint8 quantization). At scale 1 the interpolating kernels reduce to
    the identity."""
    if method not in _SUPPORT:
        raise ValueError(f"unknown resample method: {method!r}")
    scale = in_size / out_size
    fscale = max(scale, 1.0)
    support = _SUPPORT[method] * fscale
    m = np.zeros((out_size, in_size), np.float64)
    for i in range(out_size):
        center = (i + 0.5) * scale
        lo = max(int(math.floor(center - support + 0.5)), 0)
        hi = min(int(math.floor(center + support + 0.5)), in_size)
        w = np.array(
            [_pil_filter_weight(method, (j + 0.5 - center) / fscale)
             for j in range(lo, hi)],
            np.float64,
        )
        total = w.sum()
        if total != 0.0:
            w /= total
        m[i, lo:hi] = w
    return m.astype(np.float32)


def resize_pil(
    x: jnp.ndarray, size: Tuple[int, int], method: str = "bicubic"
) -> jnp.ndarray:
    """Resize the trailing (H, W) axes of ``x`` with PIL's antialiased
    half-pixel semantics (``Image.resize``). Matrices enter the graph as
    constants — fine for a handful of shapes; the extractor fast path
    passes them as inputs instead (``fused_resize_crop_matrices``)."""
    H, W = int(size[0]), int(size[1])
    wy = jnp.asarray(resample_matrix(x.shape[-2], H, method))
    wx = jnp.asarray(resample_matrix(x.shape[-1], W, method))
    # (..., H, W): contract H with wy, W with wx, in float32
    y = jnp.einsum(
        "ph,qw,...hw->...pq", wy, wx, x.astype(jnp.float32),
        precision="highest",
    )
    return y


def resize_bicubic(x: jnp.ndarray, size) -> jnp.ndarray:
    """Device bicubic resize of the trailing (H, W) axes with
    PIL/torchvision (antialiased, half-pixel) semantics."""
    return resize_pil(x, size, method="bicubic")


def resized_hw(
    h: int, w: int, size: int, smaller_edge: bool = True
) -> Tuple[int, int]:
    """The (oh, ow) PIL's aspect-keeping resize produces, mirroring
    ops/preprocess.py::pil_resize exactly — including the early return
    when the smaller edge already equals ``size`` (no resize at all, even
    if the larger edge differs; the quirk fires in both edge modes).
    ``smaller_edge=False`` matches ``resize_to_smaller_edge=False`` (the
    flow extractors' ``--side_size`` larger-edge mode)."""
    if (w <= h and w == size) or (h <= w and h == size):
        return h, w
    if (w < h) == smaller_edge:
        return int(size * h / w), size
    return size, int(size * w / h)


@lru_cache(maxsize=128)
def fused_resize_crop_matrices(
    h: int,
    w: int,
    resize_to: int,
    crop: int,
    method: str = "bicubic",
    pad_h: Optional[int] = None,
    pad_w: Optional[int] = None,
    crop_offset: str = "round",
) -> Tuple[np.ndarray, np.ndarray]:
    """(Wy (crop, pad_h or h), Wx (crop, pad_w or w)) float32 matrices
    composing PIL smaller-edge resize to ``resize_to`` with torchvision
    CenterCrop(``crop``) — the whole spatial half of the CLIP/ResNet
    preprocess chains as two matmuls: ``out = Wy @ frame @ Wx.T``.

    Crop rows/cols outside the resized image carry zero weight (matching
    ``pil_center_crop``'s zero padding), and source columns beyond
    (h, w) — the ``spatial_bucket`` padding — carry zero weight too, so
    bucket pad pixels cannot bleed into the output. Cached per source
    resolution: a corpus re-uses each (h, w)'s matrices across videos.

    ``crop_offset`` picks the center-offset convention: ``"round"`` is
    torchvision CenterCrop (round half to even), ``"floor"`` is the I3D
    chain's tensor crop (``(size - crop) // 2``,
    models/i3d/extract_i3d.py::center_crop) — they differ by one source
    row/col whenever the resized edge parity is odd."""
    oh, ow = resized_hw(h, w, resize_to)
    ry = resample_matrix(h, oh, method)
    rx = resample_matrix(w, ow, method)
    # torchvision CenterCrop offsets (round half to even) or the I3D
    # tensor-crop floor; when the resized image is smaller than the crop,
    # pil_center_crop zero-pads with a floor-divided top/left margin
    # BEFORE cropping — mirror that as a negative offset so the zero rows
    # land where PIL's pad does
    if crop_offset not in ("round", "floor"):
        raise ValueError(f"unknown crop_offset policy: {crop_offset!r}")

    def _offset(size_: int) -> int:
        if size_ < crop:
            return -((crop - size_) // 2)
        if crop_offset == "floor":
            return (size_ - crop) // 2
        return int(round((size_ - crop) / 2.0))

    top = _offset(oh)
    left = _offset(ow)
    wy = np.zeros((crop, pad_h or h), np.float32)
    wx = np.zeros((crop, pad_w or w), np.float32)
    for out_r in range(crop):
        r = top + out_r
        if 0 <= r < oh:
            wy[out_r, :h] = ry[r]
    for out_c in range(crop):
        c = left + out_c
        if 0 <= c < ow:
            wx[out_c, :w] = rx[c]
    wy.setflags(write=False)
    wx.setflags(write=False)
    return wy, wx


def banded(matrix: np.ndarray, k: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Compress a resample matrix to banded form: (weights (out, K),
    indices (out, K)) with K the widest row band (PIL taps are contiguous,
    so per-row nonzeros always fit one band). Rows narrower than K repeat
    their last index under zero weight; all-zero rows (crop padding) point
    at column 0 under zero weight. Dense matmul over a bucket-padded axis
    pays the full axis length per output pixel where PIL's separable loop
    pays ~2*support*scale taps — on the MXU that's free, on a CPU core
    it's a ~50x FLOP tax, so the extractors ship THIS form and
    ops/preprocess.py::device_preprocess_frames accumulates the K gathered
    slices instead (also PIL's own tap order, keeping the ≤1/255 parity)."""
    widths = (matrix != 0).sum(axis=1)
    k_actual = int(widths.max()) if matrix.size else 0
    k = max(k or 0, k_actual, 1)
    wt = np.zeros((matrix.shape[0], k), np.float32)
    idx = np.zeros((matrix.shape[0], k), np.int32)
    for q, row in enumerate(matrix):
        nz = np.nonzero(row)[0]
        if len(nz):
            n = len(nz)
            idx[q, :n] = nz
            idx[q, n:] = nz[-1]
            wt[q, :n] = row[nz]
    wt.setflags(write=False)
    idx.setflags(write=False)
    return wt, idx


@lru_cache(maxsize=128)
def fused_resize_crop_banded(
    h: int,
    w: int,
    resize_to: int,
    crop: int,
    method: str = "bicubic",
    pad_h: Optional[int] = None,
    pad_w: Optional[int] = None,
    crop_offset: str = "round",
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """``fused_resize_crop_matrices`` in banded form: (wt_y, idx_y, wt_x,
    idx_x). K is computed at the BUCKET resolution (pad_h, pad_w), not the
    source (h, w): band width grows with the resample scale, and the scale
    (min-edge/resize_to) is maximal at the bucket corner, so every source
    resolution sharing a bucket pads up to one static K — mixed-resolution
    ``--video_batch`` groups can stack their taps, and one executable
    serves the whole bucket.

    Sharding contract (--sharding mesh): the taps are per-PIXEL geometry,
    identical for every frame, so they replicate (PartitionSpec()) while
    the frame batch axis of the uint8 input shards over 'data' — with the
    bucket pad applied BEFORE the split so every shard sees the same
    static (pad_h, pad_w, K) shapes. parallel.sharding.place_raw_payload
    implements the placement; GC502 statically checks that every fused
    jit entry reachable under mesh pins it via in/out_shardings."""
    wy, wx = fused_resize_crop_matrices(
        h, w, resize_to, crop, method, pad_h, pad_w, crop_offset
    )
    bh, bw = pad_h or h, pad_w or w
    # analytic K bound from the bucket's worst-case scale: a resample row
    # holds hi-lo taps with hi-lo <= floor(2*support*fscale)+1, and within
    # a bucket fscale (= min-edge/resize_to when downsampling, 1 when
    # upsampling) is maximal at the bucket corner. +1 absorbs resized_hw's
    # int() rounding nudging a member's scale past the corner's. Derived
    # from the bucket alone — NOT the source — so every resolution in a
    # bucket pads to one K and their tap arrays stack for --video_batch.
    # (The corner's own matrices can't serve as the bound: a corner whose
    # min-edge lands exactly on resize_to takes pil_resize's no-op early
    # return, K=1, while its neighbors still resize.)
    smax = max(min(bh, bw) / float(resize_to), 1.0)
    k = int(2 * _SUPPORT[method] * smax) + 2
    wt_y, idx_y = banded(wy, k)
    wt_x, idx_x = banded(wx, k)
    if wt_y.shape[1] != k or wt_x.shape[1] != k:
        raise AssertionError(
            f"band width escaped its bucket bound: {wt_y.shape[1]}/"
            f"{wt_x.shape[1]} vs {k} for {(h, w)} in {(bh, bw)}"
        )
    return wt_y, idx_y, wt_x, idx_x


# --- shape-contracted outputs (flow + I3D device preprocess) ---------------

@lru_cache(maxsize=256)
def shape_contract_matrices(
    h: int,
    w: int,
    resize_to: int,
    out_h: int,
    out_w: int,
    top: int = 0,
    left: int = 0,
    method: str = "bilinear",
    pad_h: Optional[int] = None,
    pad_w: Optional[int] = None,
    pad_mode: str = "edge",
    smaller_edge: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """The crop-free generalization of ``fused_resize_crop_matrices``:
    (Wy (out_h, pad_h or h), Wx (out_w, pad_w or w)) matrices that resize
    a source frame onto an agreed **output contract** — a fixed
    (out_h, out_w) grid with the resized (oh, ow) image placed at
    (top, left). That is exactly the geometry the flow models and I3D
    need: their host chains resize to a shape that VARIES with the source
    (min-edge-256 for I3D, ``--side_size`` or no resize for RAFT/PWC) and
    then replicate-pad to the model's /8 or /64 grid; here pad and resize
    collapse into one tap set per source resolution.

    ``resize_to`` = 0 skips the resize (identity taps — the no
    ``--side_size`` flow case); otherwise it is PIL's aspect-keeping edge
    resize (``smaller_edge`` as in ``pil_resize``). ``pad_mode`` places
    the out-of-image rows/cols: ``"edge"`` repeats the nearest image
    row/col's taps — composing the resize with ``np.pad(mode="edge")``
    (InputPadder's replicate pad) into the same matrix, exact because the
    pad copies already-quantized pixels; ``"zero"`` leaves them at zero
    weight. Source columns beyond (h, w) — input ``spatial_bucket``
    padding — always carry zero weight."""
    if pad_mode not in ("edge", "zero"):
        raise ValueError(f"unknown pad_mode: {pad_mode!r}")
    oh, ow = resized_hw(h, w, resize_to, smaller_edge) if resize_to else (h, w)
    if not (0 <= top and top + oh <= out_h and 0 <= left and left + ow <= out_w):
        raise ValueError(
            f"resized image {(oh, ow)} at offset {(top, left)} does not fit "
            f"the {(out_h, out_w)} output contract"
        )
    ry = resample_matrix(h, oh, method)
    rx = resample_matrix(w, ow, method)
    wy = np.zeros((out_h, pad_h or h), np.float32)
    wx = np.zeros((out_w, pad_w or w), np.float32)
    for out_r in range(out_h):
        r = out_r - top
        if pad_mode == "edge":
            r = min(max(r, 0), oh - 1)
        if 0 <= r < oh:
            wy[out_r, :h] = ry[r]
    for out_c in range(out_w):
        c = out_c - left
        if pad_mode == "edge":
            c = min(max(c, 0), ow - 1)
        if 0 <= c < ow:
            wx[out_c, :w] = rx[c]
    wy.setflags(write=False)
    wx.setflags(write=False)
    return wy, wx


@lru_cache(maxsize=256)
def shape_contract_banded(
    h: int,
    w: int,
    resize_to: int,
    out_h: int,
    out_w: int,
    top: int = 0,
    left: int = 0,
    method: str = "bilinear",
    pad_h: Optional[int] = None,
    pad_w: Optional[int] = None,
    pad_mode: str = "edge",
    smaller_edge: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """``shape_contract_matrices`` in banded form (wt_y, idx_y, wt_x,
    idx_x), with K bounded analytically from the input bucket exactly as
    ``fused_resize_crop_banded`` does — every source resolution sharing
    an (input bucket, output contract) pair pads to one K, so taps stack
    across a ``--video_batch`` group and one executable serves the pair.
    With ``resize_to`` = 0 the taps are the identity band (K covers it
    trivially), which makes the no-resize flow contract a pure gather —
    bit-exact against host ``np.pad(mode="edge")``."""
    wy, wx = shape_contract_matrices(
        h, w, resize_to, out_h, out_w, top, left,
        method, pad_h, pad_w, pad_mode, smaller_edge,
    )
    bh, bw = pad_h or h, pad_w or w
    if resize_to:
        edge = min(bh, bw) if smaller_edge else max(bh, bw)
        smax = max(edge / float(resize_to), 1.0)
    else:
        smax = 1.0
    k = int(2 * _SUPPORT[method] * smax) + 2
    wt_y, idx_y = banded(wy, k)
    wt_x, idx_x = banded(wx, k)
    if wt_y.shape[1] != k or wt_x.shape[1] != k:
        raise AssertionError(
            f"band width escaped its bucket bound: {wt_y.shape[1]}/"
            f"{wt_x.shape[1]} vs {k} for {(h, w)} in {(bh, bw)}"
        )
    return wt_y, idx_y, wt_x, idx_x
