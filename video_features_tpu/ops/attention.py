"""Attention cores: fused, blockwise (online-softmax), and masked.

The reference's only attention is inside OpenAI's pip ``clip`` package
(torch ``nn.MultiheadAttention``, consumed at ref
models/CLIP/extract_clip.py:46-63); it materializes the full (L, L) score
matrix. These cores are the TPU-native replacements and the building
blocks for the framework's long-context story:

- ``attention``            — the fused two-einsum core (softmax fp32).
  Right answer for short sequences (ViT's 50/197 patch tokens): XLA fuses
  it and the whole score matrix fits in VMEM.
- ``blockwise_attention``  — FlashAttention-style ``lax.scan`` over KV
  blocks with a running (max, sum, acc) accumulator. O(L_q * B) live
  scores instead of O(L_q * L_kv): the long-sequence core, and the exact
  per-step update ring attention replays across chips
  (parallel/ring_attention.py).

Both take (N, H, L, d) tensors, return (N, H, L_q, d), accumulate softmax
statistics in fp32 regardless of input dtype, and accept ``kv_len`` to
mask right-padding on the KV axis (needed whenever a token axis is padded
up to a mesh-divisible length).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

HIGHEST = lax.Precision.HIGHEST

# Scores at masked KV positions are set to this (not -inf: an all-masked
# block would give exp(-inf - (-inf)) = nan in the online update).
_MASK_VALUE = -1e30


def _check_kv_len(kv_len) -> None:
    """Static-value guard: a concrete kv_len < 1 is a caller bug (the
    all-masked softmax is mean-of-padding, not zeros — see _finalize).
    Only host values are checked (a positive isinstance guard — no
    jax.core introspection, no device round-trip); traced/device values
    are the caller's contract."""
    if kv_len is None:
        return
    import numpy as _np

    if isinstance(kv_len, (int, _np.integer, _np.ndarray)):
        val = _np.asarray(kv_len)
        if val.size and int(val.min()) < 1:
            raise ValueError(f"kv_len must be >= 1, got {val.min()}")


def _scores(q: jnp.ndarray, k: jnp.ndarray, scale: float) -> jnp.ndarray:
    """(N,H,Lq,d) x (N,H,Lk,d) -> fp32 (N,H,Lq,Lk) scaled scores."""
    s = jnp.einsum("nhqd,nhkd->nhqk", q, k, precision=HIGHEST)
    return s.astype(jnp.float32) * scale


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_len: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Fused core: full score matrix, fp32 softmax, output in q.dtype.

    ``kv_len`` (when given) must be >= 1: with every position masked the
    softmax degenerates to a uniform average of the padding values (see
    ``_finalize``); the static check below catches concrete zeros, traced
    values are the caller's contract."""
    _check_kv_len(kv_len)
    scale = q.shape[-1] ** -0.5
    s = _scores(q, k, scale)
    if kv_len is not None:
        mask = jnp.arange(k.shape[2]) < kv_len
        s = jnp.where(mask[None, None, None, :], s, _MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("nhqk,nhkd->nhqd", p, v, precision=HIGHEST)


def online_softmax_step(
    q: jnp.ndarray,
    k_blk: jnp.ndarray,
    v_blk: jnp.ndarray,
    m: jnp.ndarray,
    l: jnp.ndarray,
    acc: jnp.ndarray,
    scale: float,
    kv_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One numerically-stable softmax accumulation step over a KV block.

    Carries (all fp32): ``m`` (N,H,Lq) running max, ``l`` (N,H,Lq) running
    sum of exp, ``acc`` (N,H,Lq,d) running weighted-value sum. ``kv_mask``
    is (..., Lk) True at valid KV positions. This is the exact update both
    ``blockwise_attention`` (over local blocks) and ring attention (over
    chips) iterate.
    """
    s = _scores(q, k_blk, scale)  # (N,H,Lq,Lk)
    if kv_mask is not None:
        s = jnp.where(kv_mask, s, _MASK_VALUE)
    m_new = jnp.maximum(m, s.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum(
        "nhqk,nhkd->nhqd", p.astype(v_blk.dtype), v_blk, precision=HIGHEST
    ).astype(jnp.float32)
    acc_new = acc * corr[..., None] + pv
    return m_new, l_new, acc_new


def _finalize(m, l, acc, dtype):
    # Precondition (public entry points document it): >= 1 valid KV
    # position. With zero valid positions l is NOT 0 — each all-masked
    # block contributes exp(_MASK_VALUE - _MASK_VALUE) = 1 per position,
    # so (l, acc) hold count and sum(v) over masked rows and the output
    # is mean(v-padding), not zeros. Correctness when masked blocks
    # PRECEDE valid ones relies on the correction factor underflowing:
    # the first valid block raises m from _MASK_VALUE to a real score,
    # and corr = exp(_MASK_VALUE - m_new) is exactly 0.0 in fp32, zeroing
    # the polluted carry (pinned by test_all_masked_prefix_is_cancelled).
    # The epsilon below only guards the division when l underflows.
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(dtype)


def init_carry(q: jnp.ndarray):
    """Fresh (m, l, acc) for the online-softmax recurrence."""
    N, H, Lq, d = q.shape
    m = jnp.full((N, H, Lq), _MASK_VALUE, dtype=jnp.float32)
    l = jnp.zeros((N, H, Lq), dtype=jnp.float32)
    acc = jnp.zeros((N, H, Lq, d), dtype=jnp.float32)
    return m, l, acc


def accumulate_blockwise(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    carry,
    scale: float,
    block_size: int,
    offset=0,
    limit: Optional[jnp.ndarray] = None,
):
    """Fold ``k``/``v`` into an online-softmax ``(m, l, acc)`` carry in
    ``block_size`` chunks. Positions are ``offset + i`` globally; those
    ``>= limit`` are masked (None = only the divisibility padding added
    here is masked). Shared by ``blockwise_attention`` (one local scan)
    and ring attention (one call per arriving KV shard)."""
    N, H, Lk, d = k.shape
    # a span shorter than the block must not pad UP to it — that would
    # burn masked FLOPs every call (ring hops call this per shard)
    block_size = min(block_size, Lk)
    nb = -(-Lk // block_size)
    pad = nb * block_size - Lk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # the divisibility padding is ALWAYS masked: even when the caller's
    # global limit lies beyond this span (a ring shard mid-sequence),
    # positions past offset+Lk are fabricated here, not real tokens
    end = offset + Lk
    limit = jnp.asarray(end if limit is None else jnp.minimum(limit, end))
    kb = k.reshape(N, H, nb, block_size, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(N, H, nb, block_size, d).transpose(2, 0, 1, 3, 4)
    offs = offset + jnp.arange(nb) * block_size

    def step(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, off = blk
        mask = (off + jnp.arange(block_size)) < limit
        m, l, acc = online_softmax_step(
            q, k_blk, v_blk, m, l, acc, scale, kv_mask=mask[None, None, None, :]
        )
        return (m, l, acc), None

    carry, _ = lax.scan(step, carry, (kb, vb, offs))
    return carry


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_size: int = 512,
    kv_len: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """FlashAttention-style scan over KV blocks; exact vs ``attention``.

    KV is right-padded to a multiple of ``block_size`` (padding is masked,
    composing with the caller's own ``kv_len`` mask), then scanned with
    ``online_softmax_step``. Peak live score memory is O(Lq * block_size).
    ``kv_len`` (when given) must be >= 1 — see ``attention``/``_finalize``.
    """
    _check_kv_len(kv_len)
    scale = q.shape[-1] ** -0.5
    m, l, acc = accumulate_blockwise(
        q, k, v, init_carry(q), scale, block_size, limit=kv_len
    )
    return _finalize(m, l, acc, q.dtype)
