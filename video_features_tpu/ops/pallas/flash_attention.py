"""Pallas TPU flash-attention kernel: the single-chip long-sequence core.

The reference's attention (torch ``nn.MultiheadAttention`` inside the pip
``clip`` package, ref models/CLIP/extract_clip.py:46-63) materializes the
full (L, L) score matrix in HBM. This kernel never does: for each Q tile
resident in VMEM it streams KV tiles through VMEM, maintaining the
FlashAttention online-softmax accumulator (running max / sum / weighted
value) in fp32 VMEM scratch, and writes each output tile exactly once.
Peak memory is O(block_q * block_k) scores, so sequence length is bounded
by HBM for K/V storage only — the same recurrence
ops/attention.py::blockwise_attention runs as an XLA scan and
parallel/ring_attention.py runs across chips; this is its MXU form:

- grid (N*H, Lq/block_q, Lkv/block_k), KV innermost — TPU grids run
  sequentially, so the fp32 scratch carries across KV steps and resets
  when the KV index wraps to 0.
- both matmuls (`q @ k^T`, `p @ v`) hit the MXU with
  ``preferred_element_type=float32``; q/k/v may be bf16.
- right-padding on the KV axis (to a block multiple, or a caller's
  ``kv_len``) is masked to -1e30 before the row-max, mirroring
  ops/attention.py::_MASK_VALUE semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASK_VALUE = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
            *, scale: float, block_k: int, kv_len: int):
    kv_i = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kv_i == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK_VALUE)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0]  # (block_q, d)
    k = k_ref[0]  # (block_k, d)
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (block_q, block_k) fp32 on the MXU
    pos = kv_i * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < kv_len, s, _MASK_VALUE)

    m_prev = m_scr[...]  # (block_q, 1)
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)  # (block_q, block_k)
    l_scr[...] = l_scr[...] * corr + p.sum(axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[...] = acc_scr[...] * corr + pv
    m_scr[...] = m_new

    @pl.when(kv_i == nk - 1)
    def _finalize():
        out = acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "kv_len", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_q: int = 256,
    block_k: int = 512,
    kv_len: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """(N, H, L, d) q/k/v -> (N, H, Lq, d); fp32-exact vs the fused core.

    ``kv_len`` masks KV positions >= kv_len (the ragged-token case);
    Q/KV axes are right-padded to block multiples internally and pad
    query rows are sliced off the result.
    """
    N, H, Lq, d = q.shape
    Lk = k.shape[2]
    scale = d ** -0.5
    # shrink blocks to short sequences, keeping the 8-sublane alignment
    # Mosaic tiling wants (the pad rows a rounded-up block adds are sliced
    # off / masked anyway)
    block_q = min(block_q, -(-Lq // 8) * 8)
    block_k = min(block_k, -(-Lk // 8) * 8)
    nq = pl.cdiv(Lq, block_q)
    nk = pl.cdiv(Lk, block_k)
    limit = Lk if kv_len is None else kv_len

    qp = q.reshape(N * H, Lq, d)
    kp = k.reshape(N * H, Lk, d)
    vp = v.reshape(N * H, Lk, d)
    if nq * block_q != Lq:
        qp = jnp.pad(qp, ((0, 0), (0, nq * block_q - Lq), (0, 0)))
    if nk * block_k != Lk:
        pad = ((0, 0), (0, nk * block_k - Lk), (0, 0))
        kp = jnp.pad(kp, pad)
        vp = jnp.pad(vp, pad)

    kernel = functools.partial(
        _kernel, scale=scale, block_k=block_k, kv_len=limit
    )
    out = pl.pallas_call(
        kernel,
        grid=(N * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((N * H, nq * block_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :Lq].reshape(N, H, Lq, d)
