"""Pallas TPU kernel for PWC's 81-channel cost volume.

The native equivalent of the reference's four CUDA-C kernels embedded as
strings and JIT-compiled per device through CuPy
(ref models/pwc/pwc_src/correlation.py:17-242): output channel
``(dy+4)*9 + (dx+4)`` holds ``mean_c f1[c,y,x] * f2[c,y+dy,x+dx]`` with
zero padding outside f2 (ref kernel_Correlation_updateOutput :44-112).

Mapping to TPU (the CUDA kernel's shared-memory patch staging becomes
VMEM tiling, SURVEY.md §7 hard part #3):

- Layout (N, C, H, W): W rides the 128-lane axis, the C-reduction runs
  over leading dims on the VPU, and each displacement's (TH, W) plane is
  one contiguous store.
- Grid (N, H/TH). f1's row tile auto-DMAs into VMEM; f2 (pre-padded by
  the 4-px halo) stays in HBM (`pl.ANY`) and the kernel DMAs the
  (C, TH+8, W+8) halo'd row tile into VMEM scratch ONCE per program —
  all 81 shifted windows then read from VMEM, so each input byte crosses
  HBM exactly once regardless of the 81-fold reuse.
- Python-level loop over the 81 displacements unrolls into a fused
  multiply-reduce chain on the VPU.

Forward only: the framework is an inference pipeline (SURVEY.md §0), so
the reference's two backward kernels have no call sites; anything that
needs `jax.grad` through this op must call the XLA formulation in
ops/correlation.py (method='xla'), which XLA differentiates itself.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(f1_ref, f2p_ref, out_ref, f2_tile, sem, *, disp: int, tile_h: int):
    n = pl.program_id(0)
    ty = pl.program_id(1)
    C = f1_ref.shape[1]
    W = f1_ref.shape[3]

    # stage the halo'd f2 row tile HBM -> VMEM once; 81 windows reuse it.
    # The copy slices only the (8-aligned) H axis — full lane width, since
    # Mosaic requires DMA slices 128-aligned along the last dim.
    copy = pltpu.make_async_copy(
        f2p_ref.at[n, :, pl.ds(ty * tile_h, tile_h + disp - 1), :],
        f2_tile,
        sem,
    )
    copy.start()
    copy.wait()

    f1 = f1_ref[0]  # (C, TH, W)
    planes = []
    for dy in range(disp):
        for dx in range(disp):
            f2 = f2_tile[:, dy : dy + tile_h, dx : dx + W]  # (C, TH, W)
            # fp32 accumulation pin (GC805): the C-wide sum must not
            # round per-step when the fmaps arrive bf16; /C: exact mean
            planes.append(jnp.sum(f1 * f2, axis=0, dtype=jnp.float32) / C)
    out_ref[0] = jnp.stack(planes, axis=0).astype(out_ref.dtype)  # (disp^2, TH, W)


@functools.partial(
    jax.jit, static_argnames=("max_displacement", "tile_h", "interpret")
)
def local_correlation_pallas(
    fmap1: jnp.ndarray,
    fmap2: jnp.ndarray,
    max_displacement: int = 4,
    tile_h: int = 8,
    interpret: bool = False,
) -> jnp.ndarray:
    """(N, C, H, W) x2 -> (N, (2d+1)^2, H, W), matching
    ops.correlation.local_correlation bit-for-bit in fp32."""
    N, C, H, W = fmap1.shape
    d = max_displacement
    disp = 2 * d + 1
    if tile_h % 8:
        raise ValueError(f"tile_h must be a multiple of 8 (sublane), got {tile_h}")
    n_tiles = pl.cdiv(H, tile_h)
    hp = n_tiles * tile_h
    # halo pad: d low + (d + tile remainder) high in H so the last tile's
    # DMA stays in bounds; W padded out to a 128-lane multiple because the
    # row-tile DMA must span the full (tile-aligned) lane dimension
    w_tot = ((W + 2 * d + 127) // 128) * 128
    f2p = jnp.pad(
        fmap2, ((0, 0), (0, 0), (d, d + hp - H), (d, w_tot - W - d))
    )

    kernel = functools.partial(_kernel, disp=disp, tile_h=tile_h)
    out = pl.pallas_call(
        kernel,
        grid=(N, n_tiles),
        in_specs=[
            pl.BlockSpec(
                (1, C, tile_h, W),
                lambda n, ty: (n, 0, ty, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, disp * disp, tile_h, W),
            lambda n, ty: (n, 0, ty, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((N, disp * disp, H, W), fmap1.dtype),
        scratch_shapes=[
            pltpu.VMEM((C, tile_h + disp - 1, w_tot), fmap1.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(fmap1, f2p)
    return out
