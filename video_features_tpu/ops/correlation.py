"""Cost-volume correlation ops — the reference's only custom CUDA territory.

Two flavors:

- :func:`all_pairs_correlation` — RAFT's global (H·W)² correlation,
  a single big matmul (ref raft_src/corr.py:19-27). On TPU this IS the
  idiomatic form: one MXU einsum, no custom kernel needed.
- :func:`local_correlation` — PWC's 81-channel (9×9 displacement) cost
  volume, the op the reference implements as four embedded CUDA-C kernels
  JIT-compiled via CuPy (ref pwc_src/correlation.py:17-242). Semantics
  (from kernel_Correlation_updateOutput, ref :44-112): channel
  ``tc = (dy+4)*9 + (dx+4)`` holds ``mean_c f1[c,y,x] * f2[c,y+dy,x+dx]``
  with zero padding outside f2. Here it is expressed as 81 shifted
  multiply-reduces XLA fuses on the VPU; a Pallas VMEM-tiled kernel
  (ops/pallas/correlation_kernel.py) is the native equivalent for the
  hot path.
"""

from __future__ import annotations

import functools
import json
import os

import jax
import jax.numpy as jnp


def all_pairs_correlation(fmap1: jnp.ndarray, fmap2: jnp.ndarray) -> jnp.ndarray:
    """RAFT global correlation: (N, C, H, W) x2 -> (N, H, W, H, W) / sqrt(C).

    Full fp32 MXU precision: the correlation volume feeds 20 GRU refinement
    iterations, so reduced-precision matmul drift compounds (the ≤1e-3 L2
    parity budget of BASELINE.md).
    """
    N, C, H, W = fmap1.shape
    corr = jnp.einsum(
        "nchw,ncij->nhwij", fmap1, fmap2, precision=jax.lax.Precision.HIGHEST
    )
    return corr / jnp.sqrt(jnp.array(C, fmap1.dtype))


# H*W above which 'auto' routes to the Pallas kernel on TPU backends.
# Design-derived default; a measured override wins (see _auto_threshold).
DEFAULT_PALLAS_MIN_HW = 4096


@functools.lru_cache(maxsize=1)
def _auto_threshold() -> int:
    """The measured routing threshold when one exists, else the default.

    scripts/validate_corr_tpu.py writes ``corr_routing.json``
    ({"pallas_min_hw": N, "evidence": ...}) next to this package's root
    from its compiled on-chip pallas-vs-xla tier timings; the
    VFT_CORR_ROUTING env var points at an alternative file. Malformed or
    absent files fall back silently to the design default — routing must
    never take down an extraction."""
    path = os.environ.get("VFT_CORR_ROUTING") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "corr_routing.json",
    )
    try:
        with open(path) as f:
            data = json.load(f)
        kind = data.get("device_kind")
        if kind is not None and kind != jax.devices()[0].device_kind:
            # measured on different hardware — its tradeoffs don't apply
            return DEFAULT_PALLAS_MIN_HW
        n = data["pallas_min_hw"]
        if isinstance(n, bool) or not isinstance(n, int) or n < 1:
            return DEFAULT_PALLAS_MIN_HW
        return n
    except Exception:  # noqa: BLE001 - absent/malformed -> default
        return DEFAULT_PALLAS_MIN_HW


def local_correlation(
    fmap1: jnp.ndarray,
    fmap2: jnp.ndarray,
    max_displacement: int = 4,
    method: str = "auto",
) -> jnp.ndarray:
    """PWC local correlation: (N, C, H, W) x2 -> (N, (2d+1)^2, H, W).

    Output channel ``(dy+d)*(2d+1) + (dx+d)`` = mean over C of
    ``f1[y, x] * f2[y+dy, x+dx]``, zero-padded — matching the reference
    CUDA kernel including its 1/C normalization (ref
    pwc_src/correlation.py:106-108).

    ``method``: 'auto' picks per shape on TPU backends — the Pallas
    VMEM-tiled kernel for large spatial extents (default threshold
    H*W >= 4096, e.g. PWC's hottest level-2 volume, where it measures
    ~1.7x over XLA on v5e), the XLA shifted-reduce formulation for the
    small pyramid levels where the kernel's per-tile DMA + 128-lane
    padding overhead dominates (bench.py's microbench records both).
    The threshold is data-driven where data exists: a
    ``corr_routing.json`` (written by scripts/validate_corr_tpu.py from
    COMPILED on-chip timings, or pointed at via VFT_CORR_ROUTING)
    overrides the built-in heuristic — VERDICT r4 next #3's "thresholds
    re-derived from measured data", mechanized. 'pallas'/'xla' force
    one. The Pallas kernel is forward-only — anything needing
    ``jax.grad`` through this op must pass method='xla'.
    """
    if method == "auto":
        big = fmap1.shape[2] * fmap1.shape[3] >= _auto_threshold()
        method = "pallas" if (big and jax.default_backend() == "tpu") else "xla"
    if method == "pallas":
        from video_features_tpu.ops.pallas.correlation_kernel import (
            local_correlation_pallas,
        )

        return local_correlation_pallas(fmap1, fmap2, max_displacement)
    if method != "xla":
        raise ValueError(f"method must be auto|pallas|xla, got {method!r}")

    N, C, H, W = fmap1.shape
    d = max_displacement
    f2p = jnp.pad(fmap2, ((0, 0), (0, 0), (d, d), (d, d)))
    planes = []
    for dy in range(-d, d + 1):
        for dx in range(-d, d + 1):
            shifted = f2p[:, :, d + dy : d + dy + H, d + dx : d + dx + W]
            # fp32 accumulation pin (GC802): the C-wide mean must not
            # round per-step under bf16 fmaps; cast back once at the end
            # so both correlation methods return the input dtype.
            planes.append(jnp.mean(fmap1 * shifted, axis=1, dtype=jnp.float32))
    return jnp.stack(planes, axis=1).astype(fmap1.dtype)
