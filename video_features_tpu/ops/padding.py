"""Padding utilities: RAFT's /8 InputPadder and TF-style asymmetric SAME.

``InputPadder`` mirrors ref models/raft/raft_src/utils/utils.py:7-24
(replicate-pad H and W up to multiples of 8, split half-and-half in
'sintel' mode). ``same_padding_3d`` reproduces the TF SAME convention the
I3D port needs — when total padding is odd TF puts the extra cell on the
*end* (bottom/right), which torch convs can't express and the reference
emulates with explicit ConstantPad3d (ref
models/i3d/i3d_src/i3d_net.py:8-25,108-120).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp


class InputPadder:
    """Pads NCHW images so H and W are divisible by ``factor``."""

    def __init__(self, dims: Sequence[int], mode: str = "sintel", factor: int = 8):
        self.ht, self.wd = dims[-2:]
        pad_ht = (((self.ht // factor) + 1) * factor - self.ht) % factor
        pad_wd = (((self.wd // factor) + 1) * factor - self.wd) % factor
        if mode == "sintel":
            # (left, right, top, bottom)
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, pad_ht // 2, pad_ht - pad_ht // 2]
        else:
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    def pad(self, *inputs: jnp.ndarray) -> List[jnp.ndarray]:
        l, r, t, b = self._pad
        cfg = [(0, 0)] * (inputs[0].ndim - 2) + [(t, b), (l, r)]
        return [jnp.pad(x, cfg, mode="edge") for x in inputs]

    def unpad(self, x: jnp.ndarray) -> jnp.ndarray:
        ht, wd = x.shape[-2:]
        l, r, t, b = self._pad
        return x[..., t : ht - b, l : wd - r]


def tf_same_pads(size: int, kernel: int, stride: int) -> Tuple[int, int]:
    """(before, after) padding for one dim under TF SAME semantics."""
    if size % stride == 0:
        total = max(kernel - stride, 0)
    else:
        total = max(kernel - (size % stride), 0)
    return total // 2, total - total // 2


def same_padding_3d(
    shape_tdhw: Sequence[int],
    kernel: Sequence[int],
    stride: Sequence[int],
) -> List[Tuple[int, int]]:
    """Per-dim (before, after) pads for (T, H, W) under TF SAME. The 'after'
    side gets the extra cell when total padding is odd — the asymmetry the
    reference reproduces with ConstantPad3d (ref i3d_net.py:8-25)."""
    return [
        tf_same_pads(s, k, st)
        for s, k, st in zip(shape_tdhw, kernel, stride)
    ]
