"""Shared preprocessing: host-side PIL/numpy image prep + device-side
tensor transforms.

Host side reproduces the reference's PIL-based chains byte-for-byte
(torchvision's Resize/CenterCrop both bottom out in PIL —
ref models/resnet/extract_resnet.py:33-38, and the improved min/max-edge
resize of ref models/i3d/transforms/transforms.py:87-137). Device side
carries the tensor-space transforms: center crop, [-1,1] scaling, flow
clamp→uint8 quantization (ref i3d/transforms/transforms.py:7-51).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np
from PIL import Image

import jax.numpy as jnp

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)
CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_STD = (0.26862954, 0.26130258, 0.27577711)
KINETICS_MEAN = (0.43216, 0.394666, 0.37645)
KINETICS_STD = (0.22803, 0.22145, 0.216989)


# --- host side (PIL / numpy) ----------------------------------------------

def pil_resize(
    img: np.ndarray,
    size,
    resize_to_smaller_edge: bool = True,
    interpolation=Image.BILINEAR,
) -> np.ndarray:
    """torchvision-style resize of an RGB uint8 HWC array via PIL.

    int size -> matched to the smaller (or larger) edge, keeping aspect
    (ref i3d/transforms/transforms.py:87-129); (h, w) -> exact.
    """
    pim = Image.fromarray(img)
    if isinstance(size, int):
        w, h = pim.size
        if (w <= h and w == size) or (h <= w and h == size):
            return img
        if (w < h) == resize_to_smaller_edge:
            ow, oh = size, int(size * h / w)
        else:
            oh, ow = size, int(size * w / h)
        pim = pim.resize((ow, oh), interpolation)
    else:
        h, w = size
        pim = pim.resize((w, h), interpolation)
    return np.asarray(pim)


def pil_center_crop(img: np.ndarray, crop: int) -> np.ndarray:
    """torchvision CenterCrop on HWC (pads with zeros if smaller)."""
    h, w = img.shape[:2]
    if h < crop or w < crop:
        pt = max((crop - h) // 2, 0)
        pl = max((crop - w) // 2, 0)
        img = np.pad(
            img,
            ((pt, max(crop - h - pt, 0)), (pl, max(crop - w - pl, 0)), (0, 0)),
        )
        h, w = img.shape[:2]
    top = int(round((h - crop) / 2.0))
    left = int(round((w - crop) / 2.0))
    return img[top : top + crop, left : left + crop]


# graftcheck: fp32-island — torchvision ToTensor parity reference: the
# production wire ships uint8 and casts on device (--preprocess device);
# this host float path exists to pin that device graph bit-for-bit.
def to_float_chw(img: np.ndarray) -> np.ndarray:
    """HWC uint8 -> CHW float32 in [0, 1] (torchvision ToTensor)."""
    return np.transpose(img, (2, 0, 1)).astype(np.float32) / 255.0


def normalize_chw(
    img: np.ndarray, mean: Sequence[float], std: Sequence[float]
) -> np.ndarray:
    mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
    std = np.asarray(std, np.float32).reshape(-1, 1, 1)
    return (img - mean) / std


def imagenet_preprocess(
    img: np.ndarray,
    resize_size: int = 256,
    crop_size: int = 224,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    interpolation=Image.BILINEAR,
) -> np.ndarray:
    """The full Resize->CenterCrop->ToTensor->Normalize chain
    (ref extract_resnet.py:33-38) -> CHW float32."""
    img = pil_resize(img, resize_size, interpolation=interpolation)
    img = pil_center_crop(img, crop_size)
    return normalize_chw(to_float_chw(img), mean, std)


# --- device side (jnp) ----------------------------------------------------

def _banded_resample(x, wt, idx, axis: int):
    """One separable resample pass as a K-tap banded accumulation:
    ``sum_k take(x, idx[..., k], axis) * wt[..., k]``. K is static (band
    width of the bucket corner, ops/resize.py::fused_resize_crop_banded),
    so the python loop unrolls into one XLA fusion; the uint8 gathers
    convert to float inside the fused multiply-add, never materializing
    the full-resolution frames as float32. This is also PIL's own
    accumulation order (ascending tap index), which is what keeps the
    ≤1/255 parity that a dense-matmul reduction order loses."""
    shared = wt.ndim == 2  # one tap set for the whole stack (solo layout)
    y = 0.0
    for k in range(wt.shape[-1]):
        if shared:
            g = jnp.take(x, idx[:, k], axis=axis)
            bshape = [1] * x.ndim
            bshape[axis] = -1
        else:
            # leading axis of wt/idx is the stack axis (N videos / R rows):
            # (N, out) broadcasts to (N, 1, ..., out, ..., 1) against x
            bshape = [1] * x.ndim
            bshape[0] = idx.shape[0]
            bshape[axis] = idx.shape[1]
            g = jnp.take_along_axis(x, idx[:, :, k].reshape(bshape), axis=axis)
        w = wt[..., k].reshape(bshape)
        y = y + g.astype(jnp.float32) * w
    return y


def device_resize_frames(
    frames: jnp.ndarray,
    wy: Tuple[jnp.ndarray, jnp.ndarray],
    wx: Tuple[jnp.ndarray, jnp.ndarray],
) -> jnp.ndarray:
    """The resample core of ``--preprocess device``, without the
    normalize/transpose tail: raw uint8 HWC frames -> two banded separable
    passes against host-built PIL-semantics taps -> float32 HWC in
    [0, 255]. This is the piece every shape-contracted consumer shares —
    the flow models want [0, 255] channels-last input (RAFT/PWC apply
    their own scaling in-model) and I3D's chains start from [0, 255] —
    while CLIP/ResNet layer the mean/std normalize on top
    (:func:`device_preprocess_frames`).

    PIL runs horizontal-first and rounds+clips to uint8 between the
    passes and after the last one; that quantization is replayed here
    (load-bearing under bicubic overshoot, and the identity on the
    integer-valued outputs of identity taps, so no-resize contracts stay
    bit-exact). Tap layouts as documented on
    :func:`device_preprocess_frames`."""
    wt_y, idx_y = wy
    wt_x, idx_x = wx

    def quant8(v):  # PIL's inter-pass uint8 round+clamp, kept as float
        return jnp.clip(jnp.round(v), 0.0, 255.0)

    # horizontal first (W axis), then vertical (H axis) — PIL's order
    w_axis = frames.ndim - 2
    y = quant8(_banded_resample(frames, wt_x, idx_x, axis=w_axis))
    return quant8(_banded_resample(y, wt_y, idx_y, axis=w_axis - 1))


def device_preprocess_frames(
    frames: jnp.ndarray,
    wy: Tuple[jnp.ndarray, jnp.ndarray],
    wx: Tuple[jnp.ndarray, jnp.ndarray],
    mean: Sequence[float],
    std: Sequence[float],
    out_dtype=jnp.float32,
) -> jnp.ndarray:
    """The fused on-chip half of ``--preprocess device``: raw uint8 HWC
    frames (padded to a spatial bucket) -> resize+crop (two banded
    separable passes against the host-built PIL-semantics taps, see
    ops/resize.py::fused_resize_crop_banded) -> /255 -> mean/std
    normalize -> CHW in the compute dtype. One XLA fusion, no host
    float32 blow-up, 4x less H2D than shipping preprocessed floats.

    ``wy``/``wx`` are (weights, indices) pairs: K-tap bands instead of
    dense matrices, so each output pixel pays ~K multiply-adds rather
    than the full bucket-padded axis — the difference between the device
    path beating the host PIL chain on a bare CPU core and losing to it
    (dense matmuls are only free where an MXU does them).

    PIL runs the two separable passes horizontal-first and rounds+clips
    to uint8 between them and after the last one — with bicubic's
    negative lobes the clipping is visible wherever the overshoot hits 0
    or 255, so parity requires quantizing exactly where PIL does (the
    same lesson native/preprocess.cpp::quant8 encodes). The residual vs
    PIL is its 8-bit fixed-point coefficient table, ~1/255 per pixel
    (tolerance-pinned in tests/test_ops.py).

    Three tap layouts, matching the extractor dispatch shapes:
      frames (T, H, W, C)    + wt (P, K)    -> (T, C, P, Q)   solo video
      frames (N, T, H, W, C) + wt (N, P, K) -> (N, T, C, P, Q) per-video
        taps for a fused --video_batch group (mixed resolutions in one
        bucket)
      frames (R, H, W, C)    + wt (R, P, K) -> (R, C, P, Q)   per-row
        taps (rows from different videos concatenated, ResNet
        aggregation)
    """
    y = device_resize_frames(frames, wy, wx)
    # (..., P, Q, C) -> (..., C, P, Q)
    perm = tuple(range(y.ndim - 3)) + (y.ndim - 1, y.ndim - 3, y.ndim - 2)
    y = jnp.transpose(y, perm)
    mean_a = jnp.asarray(mean, jnp.float32).reshape(-1, 1, 1)
    std_a = jnp.asarray(std, jnp.float32).reshape(-1, 1, 1)
    y = (y / 255.0 - mean_a) / std_a
    return y.astype(out_dtype)


def tensor_center_crop(x: jnp.ndarray, crop: int) -> jnp.ndarray:
    """Center crop on the trailing (H, W) axes (ref transforms.py:7-18)."""
    H, W = x.shape[-2], x.shape[-1]
    fh = (H - crop) // 2
    fw = (W - crop) // 2
    return x[..., fh : fh + crop, fw : fw + crop]


def dynamic_center_crop(x: jnp.ndarray, top, left, crop: int) -> jnp.ndarray:
    """Crop ``crop`` x ``crop`` out of the (..., H, W, C) axes at a
    TRACED (top, left) offset. Under the shape-contracted I3D flow path
    the crop window's position inside the padded output bucket varies per
    source resolution while the executable is shared per bucket, so the
    offsets ship as jit inputs (int32 scalars) and the slice is a
    ``dynamic_slice`` — one compile per bucket instead of one per
    source shape."""
    import jax.lax

    x = jax.lax.dynamic_slice_in_dim(x, top, crop, axis=x.ndim - 3)
    return jax.lax.dynamic_slice_in_dim(x, left, crop, axis=x.ndim - 2)


def scale_to_1_1(x: jnp.ndarray) -> jnp.ndarray:
    """[0, 255] -> [-1, 1] (ref transforms.py:21-24)."""
    return 2.0 * x / 255.0 - 1.0


def flow_to_uint8(flow: jnp.ndarray, bound: float = 20.0) -> jnp.ndarray:
    """Clamp flow to [-bound, bound] and quantize to the uint8 grid kept as
    float — the Clamp -> ToUInt8 chain (ref transforms.py:33-51). NB the
    reference's formula yields 256.0 (not 255) at exactly +bound and keeps
    it as float; preserved here for parity. Anything that must actually
    STORE uint8 goes through :func:`flow_quantize_uint8_np`."""
    clamped = jnp.clip(flow, -bound, bound)
    return jnp.round(128.0 + 255.0 / (2 * bound) * clamped)


def flow_quantize_uint8_np(flow, bound: float = 20.0):
    """NumPy storage variant of :func:`flow_to_uint8` for the save_jpg
    sink: same map, then clipped to 0..255 BEFORE the uint8 cast — at
    exactly +bound the reference formula hits 256.0, which a bare
    ``astype(uint8)`` would wrap to 0 (max-positive flow read back as
    max-negative)."""
    import numpy as np

    q = np.round(128.0 + 255.0 / (2 * bound) * np.clip(flow, -bound, bound))
    return np.clip(q, 0.0, 255.0).astype(np.uint8)
