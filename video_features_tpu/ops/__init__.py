from video_features_tpu.ops.correlation import all_pairs_correlation, local_correlation  # noqa: F401
from video_features_tpu.ops.padding import InputPadder, same_padding_3d  # noqa: F401
from video_features_tpu.ops.resize import resize_bilinear  # noqa: F401
from video_features_tpu.ops.sampler import bilinear_sampler, grid_sample  # noqa: F401
