"""GC401 — enforced recompilation budgets.

PR 1/2's load-bearing invariant is that executables are shared per
(spatial bucket, output grid): per-video geometry enters jitted programs
as INPUTS, so a million-video corpus compiles a handful of programs, not
one per source resolution. Until now that guarantee lived in comments.
Here it is a regression-tested budget: :class:`CompileCounter` counts
XLA executable builds per jitted-function name during the existing
device-preprocess extraction scenarios, and ``compile_budget.json``
commits the ceiling per scenario. Inflating the executable count for any
device-preprocess extractor (e.g. breaking bucket sharing so each source
resolution compiles its own ``encode_raw``) fails a tier-1 test
(tests/test_compile_budget.py).

The counter hooks ``jax_log_compiles``: with the flag up, jax logs one
``Compiling <fn-name> with global shapes and types ...`` record per
executable build through the ``jax._src.interpreters.pxla`` logger.
Counting log records instead of wrapping internals keeps the tracer
version-tolerant (the jax.monitoring duration events carry no function
name); internal jit names (``convert_element_type`` et al.) show up in
``counts`` but only names listed in a scenario's budget are enforced.
"""

from __future__ import annotations

import json
import logging
import os
import re
from collections import Counter
from typing import Dict, List, Optional

from video_features_tpu.analysis.core import Rule

BUDGET_RULE = Rule(
    "GC401", "compile-budget",
    "executable count per extractor exceeds the committed budget",
)

BUDGET_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "compile_budget.json")

# "Compiling encode_raw with global shapes and types [...]" — emitted
# once per executable BUILD (retraces included, cache hits of the same
# trace excluded), which is exactly the fragmentation metric the budget
# bounds.
_COMPILING_RE = re.compile(r"^Compiling (\S+) with global shapes")
_LOGGER_NAMES = ("jax._src.interpreters.pxla", "jax.interpreters.pxla")


class CompileCounter(logging.Handler):
    """Context manager counting executable builds per jitted-fn name.

    >>> with CompileCounter() as cc:
    ...     run_extraction()
    >>> cc.counts["encode_raw"]
    2
    """

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.counts: Counter = Counter()
        self._prev_flag: Optional[bool] = None

    # logging.Handler interface
    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILING_RE.match(record.getMessage())
        except Exception:  # noqa: BLE001 - a broken record must not kill the run
            return
        if m:
            self.counts[m.group(1)] += 1

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def __enter__(self) -> "CompileCounter":
        import jax

        self._prev_flag = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        for name in _LOGGER_NAMES:
            logging.getLogger(name).addHandler(self)
        return self

    def __exit__(self, *exc) -> None:
        import jax

        for name in _LOGGER_NAMES:
            logging.getLogger(name).removeHandler(self)
        jax.config.update("jax_log_compiles", bool(self._prev_flag))


def load_budget(path: Optional[str] = None) -> Dict[str, dict]:
    with open(path or BUDGET_PATH, "r", encoding="utf-8") as f:
        doc = json.load(f)
    return doc["scenarios"]


def check_counts(
    scenario: str, counts: Dict[str, int], path: Optional[str] = None
) -> List[str]:
    """Violation strings (empty = within budget) for ``counts`` measured
    under the named scenario. Budgets are ceilings; a count of zero for a
    budgeted name is ALSO a violation — it means the scenario no longer
    exercises the executable it claims to pin, so the budget is dead."""
    scenarios = load_budget(path)
    if scenario not in scenarios:
        return [
            f"unknown compile-budget scenario {scenario!r} "
            f"(known: {', '.join(sorted(scenarios))})"
        ]
    spec = scenarios[scenario]
    out: List[str] = []
    for name, ceiling in spec["max_compiles"].items():
        got = counts.get(name, 0)
        if got > ceiling:
            out.append(
                f"[GC401 compile-budget] {scenario}: {name!r} built {got} "
                f"executables, budget is {ceiling} — per-video state is "
                f"leaking into trace-time (bucket sharing broken?)"
            )
        elif got == 0:
            out.append(
                f"[GC401 compile-budget] {scenario}: {name!r} compiled 0 times "
                f"— the scenario no longer exercises this executable; update "
                f"compile_budget.json"
            )
    return out


def assert_within_budget(
    scenario: str, counter: CompileCounter, path: Optional[str] = None
) -> None:
    violations = check_counts(scenario, dict(counter.counts), path)
    if violations:
        raise AssertionError("\n".join(violations))
