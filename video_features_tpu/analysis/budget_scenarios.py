"""Runnable GC401 budget scenarios + the ``--update-budgets`` writer.

Each scenario in ``compile_budget.json`` names a real extraction this
module can reproduce: a deterministic synthetic corpus (utils/synth.py —
no network, no ffmpeg) driven through the same extractor configuration
the tests use, traced by :class:`~video_features_tpu.analysis.
compile_budget.CompileCounter`. ``python -m video_features_tpu.analysis
--update-budgets [--scenario NAME]`` re-runs the scenarios and rewrites
the committed ceilings from the measured counts — the ONLY sanctioned
way to raise a budget, so the diff that raises one carries the
regenerated number, not a hand edit.

Only the **named jitted entries** of each scenario are budgeted (the
fused ``encode_raw``/``forward_raw``/``rgb_fn``/``flow_fn`` programs);
the op-by-op executables JAX builds outside jit (``add``, ``multiply``,
param-init noise) are deliberately untracked — they scale with model
depth, not with the bucket-sharing invariant the budget protects.

Import cost: this module imports nothing heavy at module scope; each
runner imports jax/extractors lazily because ``--update-budgets`` is the
one analysis mode that executes code.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from video_features_tpu.analysis.compile_budget import BUDGET_PATH


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One budgeted extraction: what it runs and which jitted-entry
    names its ceiling tracks."""

    description: str
    tracked: Tuple[str, ...]
    runner: Callable[[str], Dict[str, int]]  # tmp dir -> raw counts


def _mixed_videos(tmp: str) -> List[str]:
    """The tests/test_device_preprocess.py mixed_videos corpus: three
    resolutions, TWO spatial buckets (426x240 and 420x232 share
    (256, 448); 320x240 gets its own)."""
    from video_features_tpu.utils.synth import synth_video

    return [
        synth_video(os.path.join(tmp, "a.mp4"), n_frames=24, width=426,
                    height=240, seed=0),
        synth_video(os.path.join(tmp, "b.mp4"), n_frames=32, width=420,
                    height=232, seed=1),
        synth_video(os.path.join(tmp, "c.mp4"), n_frames=28, width=320,
                    height=240, seed=2),
    ]


def _tiny_flow_videos(tmp: str) -> List[str]:
    """The e2e tiny flow corpus: both land on RAFT's (128, 128) padder
    grid, so the fused entry compiles ONCE for the pair."""
    from video_features_tpu.utils.synth import synth_video

    return [
        synth_video(os.path.join(tmp, "f1.mp4"), n_frames=8, width=100,
                    height=96, seed=3),
        synth_video(os.path.join(tmp, "f2.mp4"), n_frames=8, width=100,
                    height=96, seed=4),
    ]


def _counted(run: Callable[[], object]) -> Dict[str, int]:
    from video_features_tpu.analysis.compile_budget import CompileCounter

    with CompileCounter() as cc:
        run()
    return dict(cc.counts)


def _clip_run(
    tmp: str, video_batch: int, dtype: str = "float32"
) -> Dict[str, int]:
    from video_features_tpu.config import ExtractionConfig, sanity_check
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = sanity_check(
        ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            extract_method="uni_4",
            preprocess="device",
            video_batch=video_batch,
            dtype=dtype,
            video_paths=_mixed_videos(tmp),
            tmp_path=os.path.join(tmp, "tmp"),
            output_path=os.path.join(tmp, "out"),
            cpu=True,
        )
    )
    return _counted(lambda: ExtractCLIP(cfg, external_call=True)())


def _mesh_device():
    import jax

    from video_features_tpu.parallel.sharding import make_mesh

    return make_mesh(jax.devices(), model=1)


def _flow_run(
    tmp: str, ft: str, mesh: bool = False, dtype: str = "float32"
) -> Dict[str, int]:
    from video_features_tpu.config import ExtractionConfig, sanity_check

    if ft == "raft":
        from video_features_tpu.models.raft.extract_raft import (
            ExtractRAFT as cls,
        )
    else:
        from video_features_tpu.models.pwc.extract_pwc import (
            ExtractPWC as cls,
        )
    cfg = sanity_check(
        ExtractionConfig(
            allow_random_init=True,
            feature_type=ft,
            video_paths=_tiny_flow_videos(tmp),
            batch_size=4,
            preprocess="device",
            sharding="mesh" if mesh else "queue",
            dtype=dtype,
            tmp_path=os.path.join(tmp, "tmp"),
            output_path=os.path.join(tmp, "out"),
            cpu=True,
        )
    )
    if mesh:
        dev = _mesh_device()
        return _counted(lambda: cls(cfg, external_call=True)(device=dev))
    return _counted(lambda: cls(cfg, external_call=True)())


def _i3d_run(tmp: str, mesh: bool = False) -> Dict[str, int]:
    from video_features_tpu.config import ExtractionConfig, sanity_check
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D
    from video_features_tpu.utils.synth import synth_video

    video = synth_video(os.path.join(tmp, "synth.mp4"))  # 60f 320x240
    cfg = sanity_check(
        ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            video_paths=[video],
            flow_type="pwc",
            extraction_fps=5.0,
            stack_size=10,
            step_size=10,
            preprocess="device",
            sharding="mesh" if mesh else "queue",
            tmp_path=os.path.join(tmp, "tmp"),
            output_path=os.path.join(tmp, "out"),
            cpu=True,
        )
    )
    if mesh:
        dev = _mesh_device()
        return _counted(
            lambda: ExtractI3D(cfg, external_call=True)([0], device=dev)
        )
    return _counted(lambda: ExtractI3D(cfg, external_call=True)([0]))


SCENARIOS: Dict[str, Scenario] = {
    "clip_device_mixed": Scenario(
        description=(
            "ExtractCLIP --preprocess device over the mixed_videos fixture "
            "(tests/test_device_preprocess.py): 3 videos, 2 spatial buckets "
            "(426x240 and 420x232 share (256,448); 320x240 gets its own), "
            "video_batch=1."
        ),
        tracked=("encode_raw",),
        runner=lambda tmp: _clip_run(tmp, video_batch=1),
    ),
    "clip_device_grouped": Scenario(
        description=(
            "Same fixture with video_batch=2: the shared-bucket pair "
            "dispatches as one group, the odd video solo - grouped and solo "
            "input layouts are one executable each."
        ),
        tracked=("encode_raw",),
        runner=lambda tmp: _clip_run(tmp, video_batch=2),
    ),
    "raft_device_tiny": Scenario(
        description=(
            "ExtractRAFT --preprocess device over two 100x96 8-frame clips "
            "(tests/test_device_preprocess_e2e.py tiny_flow_videos): both "
            "land on the (128,128) padder grid, so the fused forward_raw "
            "compiles once for the whole corpus."
        ),
        tracked=("forward_raw",),
        runner=lambda tmp: _flow_run(tmp, "raft"),
    ),
    "pwc_device_tiny": Scenario(
        description=(
            "ExtractPWC --preprocess device over the same tiny corpus: one "
            "(128,128) fused forward_raw executable; PWC's pyramid adds no "
            "per-video shapes."
        ),
        tracked=("forward_raw",),
        runner=lambda tmp: _flow_run(tmp, "pwc"),
    ),
    "i3d_device_two_stream": Scenario(
        description=(
            "Two-stream ExtractI3D --preprocess device (flow_type=pwc, "
            "extraction_fps=5, stack 10/10) on the 320x240 synth clip: one "
            "rgb_fn and one flow_fn executable for the single stack shape."
        ),
        tracked=("rgb_fn", "flow_fn"),
        runner=lambda tmp: _i3d_run(tmp),
    ),
    # --- dtype axis: the bf16 variants of the single-device scenarios.
    # The invariant is the same bucket sharing as fp32 — switching dtype
    # swaps which executable compiles, it must not ADD executables, so
    # the bf16 ceilings match their fp32 twins (tests/test_compile_budget
    # pins the equality).
    "clip_device_mixed_bf16": Scenario(
        description=(
            "clip_device_mixed with --dtype bfloat16: the mixed-precision "
            "encode_raw still compiles once per spatial bucket — bf16 "
            "swaps the executable, it must not multiply them."
        ),
        tracked=("encode_raw",),
        runner=lambda tmp: _clip_run(tmp, video_batch=1, dtype="bfloat16"),
    ),
    "raft_device_tiny_bf16": Scenario(
        description=(
            "raft_device_tiny with --dtype bfloat16: RAFT's mixed-precision "
            "graph (convs bf16, GRU carry/softmax/corr pyramid fp32) keeps "
            "the one-executable-per-padder-grid contract."
        ),
        tracked=("forward_raw",),
        runner=lambda tmp: _flow_run(tmp, "raft", dtype="bfloat16"),
    ),
    "pwc_device_tiny_bf16": Scenario(
        description=(
            "pwc_device_tiny with --dtype bfloat16: the bf16 pyramid "
            "compiles one fused forward_raw, same as fp32."
        ),
        tracked=("forward_raw",),
        runner=lambda tmp: _flow_run(tmp, "pwc", dtype="bfloat16"),
    ),
    "raft_mesh_device_tiny": Scenario(
        description=(
            "ExtractRAFT --sharding mesh --preprocess device on the tiny "
            "flow corpus over the 8-virtual-device data mesh: the fused "
            "forward_raw (frame axis sharded over 'data', taps replicated) "
            "still compiles once — mesh placement must not add shapes."
        ),
        tracked=("forward_raw",),
        runner=lambda tmp: _flow_run(tmp, "raft", mesh=True),
    ),
    "pwc_mesh_device_tiny": Scenario(
        description=(
            "ExtractPWC --sharding mesh --preprocess device on the same "
            "tiny corpus: one fused forward_raw executable under the "
            "GC504-checked payload sharding contract."
        ),
        tracked=("forward_raw",),
        runner=lambda tmp: _flow_run(tmp, "pwc", mesh=True),
    ),
    "i3d_mesh_device_two_stream": Scenario(
        description=(
            "Two-stream ExtractI3D --sharding mesh --preprocess device "
            "(flow_type=pwc) on the 320x240 synth clip: the per-stack "
            "fused rgb_fn/flow_fn with in-body sharding constraints "
            "compile once each for the single stack shape."
        ),
        tracked=("rgb_fn", "flow_fn"),
        runner=lambda tmp: _i3d_run(tmp, mesh=True),
    ),
}


def measure(name: str) -> Dict[str, int]:
    """Run one scenario in a throwaway dir; return {tracked name: count}.
    A tracked entry the run never compiled reports 0 (check_counts treats
    that as a dead budget, which is the point — the scenario must really
    exercise the entry it budgets)."""
    sc = SCENARIOS[name]
    with tempfile.TemporaryDirectory(prefix=f"graftcheck_{name}_") as tmp:
        raw = sc.runner(tmp)
    return {entry: int(raw.get(entry, 0)) for entry in sc.tracked}


def update_budgets(names: Optional[Sequence[str]] = None) -> int:
    """Re-measure ``names`` (default: every compile scenario) and rewrite
    ``compile_budget.json`` with the observed counts as the new ceilings.
    ``parity_*`` names route to the numerics twin
    (:func:`analysis.parity.update_parity_budgets`), which rewrites the
    ``measured`` drift column of ``parity_budget.json`` instead — parity
    scenarios only run when explicitly named, they are not part of the
    default sweep. Returns a process exit code (0 ok, 2 on unknown
    scenario)."""
    chosen = list(names) if names else sorted(SCENARIOS)
    parity = [n for n in chosen if n.startswith("parity_")]
    chosen = [n for n in chosen if not n.startswith("parity_")]
    if parity:
        from video_features_tpu.analysis.parity import update_parity_budgets

        rc = update_parity_budgets(parity)
        if rc or not chosen:
            return rc
    unknown = [n for n in chosen if n not in SCENARIOS]
    if unknown:
        print(
            f"graftcheck: unknown scenario(s): {', '.join(unknown)} "
            f"(have: {', '.join(sorted(SCENARIOS))} + "
            "parity_<family> drift scenarios)",
            file=sys.stderr,
        )
        return 2
    with open(BUDGET_PATH, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    doc.setdefault("scenarios", {})
    for name in chosen:
        counts = measure(name)
        doc["scenarios"][name] = {
            "description": SCENARIOS[name].description,
            "max_compiles": counts,
        }
        pretty = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
        print(f"graftcheck: {name}: {pretty}")
    with open(BUDGET_PATH, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    print(f"graftcheck: wrote {BUDGET_PATH}")
    return 0
