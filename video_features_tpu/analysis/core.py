"""graftcheck core: findings, waivers, the source-file model, the runner.

The suite is AST-based and import-free: every checker works on parsed
source (``ast`` + ``tokenize``), so ``python -m video_features_tpu.analysis``
never executes the code it audits and runs in well under the 5 s budget
bench.py's ``analysis_overhead`` part enforces (docs/analysis.md).

Waiver contract: a ``# graftcheck: <token>[, <token>...] — reason``
comment on the offending line (or on a standalone comment line directly
above it) suppresses matching findings. A token matches a rule when it
equals the rule id (``GC301``) or is a prefix of the rule name
(``unlocked`` waives ``unlocked-global``; ``host-sync`` waives the whole
GC10x family). ``git grep 'graftcheck:'`` audits every waiver in one
sweep — that greppability is the reason waivers are inline comments and
not a config file.

File-level markers ride the same comment syntax (they declare facts,
they never waive findings — no marker token prefix-matches a rule name):

- ``# graftcheck: hot-module`` — opt a file into the host-sync lint's
  hot set beyond the built-in path patterns (used by test fixtures).
- ``# graftcheck: thread-root`` — declare a file a thread-spawning root
  for the thread-safety reachability walk.
- ``# graftcheck: pallas-kernel`` — opt a file into the GC805 Pallas
  hygiene sweep beyond the built-in ``ops/pallas/`` path.
- ``# graftcheck: bf16-entry`` — declare every def in the file (or, on
  a def line, that one def) a bf16-polymorphic entry for GC802.

The GC80x numerics family additionally reads the line/def-scoped
``# graftcheck: fp32-island — <why>`` declaration (docs/analysis.md).
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import os
import tokenize
from typing import Dict, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str  # "GC101"
    name: str  # "host-sync-item"
    summary: str

    def matches_token(self, token: str) -> bool:
        t = token.strip().lower()
        if not t:
            return False
        return t == self.id.lower() or self.name.startswith(t)


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: Rule
    message: str
    hint: str = ""
    # interprocedural provenance: "path:line: description" steps from the
    # origin (device creation, lock-free entry) to this finding's line.
    # ``--explain`` prints it; ``--json`` always carries it (may be []).
    trace: List[str] = dataclasses.field(default_factory=list)

    def format(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.rule.id} {self.rule.name}: {self.message}"
        if self.hint:
            s += f"\n    fix: {self.hint}"
        return s

    def format_trace(self) -> str:
        lines = [self.format()]
        for step in self.trace:
            lines.append(f"    via: {step}")
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, object]:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule.id,
            "name": self.rule.name,
            "message": self.message,
            "hint": self.hint,
            "trace": list(self.trace),
        }


# Paths (relative to the package root) the host-sync lint treats as the
# per-video hot loop: a device->host sync here stalls the dispatch
# pipeline once per video (or worse, once per frame batch).
HOT_MODULE_PATTERNS = (
    "extract/*.py",
    "ops/*.py",
    "ops/*/*.py",
    "models/*/model.py",
    # telemetry records inside the per-video loops; a device sync or
    # unguarded global here would tax every video (ISSUE 6)
    "runtime/telemetry.py",
    # the daemon's per-request path: admission, dispatch glue, lifecycle
    # writes — all on the serving fast path (ISSUE 7)
    "serve/*.py",
    # preflight probe runs once per admitted request/ingested video —
    # on the fast path by construction, budgeted <1% of per-video time
    # (ISSUE 9); zero waivers allowed here
    "io/probe.py",
)

# Thread-spawning roots for the thread-safety reachability walk: the
# modules that create or run on worker threads (ISSUE 4 tentpole set).
THREAD_ROOT_PATTERNS = (
    "parallel/scheduler.py",
    "extract/base.py",
    "runtime/faults.py",
    "runtime/telemetry.py",
    "io/sink.py",
    "native/__init__.py",
    "utils/profiling.py",
    # the serve daemon: batcher dispatcher thread, HTTP handler threads,
    # spool watcher thread all mutate shared admission/lifecycle state
    "serve/*.py",
    # the probe runs on HTTP handler threads (serve admission) and the
    # batch main thread concurrently; it must hold no mutable globals
    "io/probe.py",
    # the content-addressed store's hash memo is shared by every serve
    # handler thread, and the shared frame cache's LRU + in-flight
    # latches are mutated from concurrent extractor/decode threads
    "extract/cache.py",
    "extract/plan.py",
)


class SourceFile:
    """One parsed module: AST + waiver map + file-level markers."""

    def __init__(self, path: str, text: str, rel: Optional[str] = None) -> None:
        self.path = path
        self.text = text
        # rel: package-relative posix path ("extract/base.py") used for
        # hot/root pattern matching; falls back to the basename.
        self.rel = rel if rel is not None else os.path.basename(path)
        self.tree = ast.parse(text, filename=path)
        # line -> waiver tokens on that line; a standalone waiver comment
        # also registers for the next line.
        self.waivers: Dict[int, Set[str]] = {}
        self.markers: Set[str] = set()
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                body = tok.string.lstrip("#").strip()
                if not body.lower().startswith("graftcheck:"):
                    continue
                spec = body[len("graftcheck:"):].strip()
                # strip a trailing "— reason" / "- reason" clause
                for dash in ("—", " - ", " -- "):
                    if dash in spec:
                        spec = spec.split(dash, 1)[0]
                tokens_ = {t.strip().lower() for t in spec.split(",") if t.strip()}
                if not tokens_:
                    continue
                self.markers |= {
                    t
                    for t in tokens_
                    if t in ("hot-module", "thread-root", "pallas-kernel",
                             "bf16-entry")
                }
                line = tok.start[0]
                self.waivers.setdefault(line, set()).update(tokens_)
                # a comment-only line waives the statement it precedes:
                # the reason clause may wrap onto further comment lines,
                # so carry the waiver to the first following code line
                lines = self.text.splitlines()
                prefix = lines[line - 1][: tok.start[1]]
                if not prefix.strip():
                    nxt = line  # 0-based index of the line after the comment
                    while nxt < len(lines) and (
                        not lines[nxt].strip() or lines[nxt].lstrip().startswith("#")
                    ):
                        nxt += 1
                    self.waivers.setdefault(nxt + 1, set()).update(tokens_)
        except tokenize.TokenError:
            pass

    def waived(self, line: int, rule: Rule) -> bool:
        return any(rule.matches_token(t) for t in self.waivers.get(line, ()))

    @property
    def is_hot(self) -> bool:
        if "hot-module" in self.markers:
            return True
        return any(fnmatch.fnmatch(self.rel, pat) for pat in HOT_MODULE_PATTERNS)

    @property
    def is_thread_root(self) -> bool:
        if "thread-root" in self.markers:
            return True
        return any(fnmatch.fnmatch(self.rel, pat) for pat in THREAD_ROOT_PATTERNS)

    @property
    def module_name(self) -> str:
        return self.rel[:-3].replace("/", ".") if self.rel.endswith(".py") else self.rel


def package_root() -> str:
    """The installed video_features_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_sources(paths: Optional[Sequence[str]] = None) -> List[SourceFile]:
    """Load every .py under ``paths`` (default: the package itself) into
    SourceFiles with package-relative names for pattern matching."""
    roots = [package_root()] if not paths else [os.path.abspath(p) for p in paths]
    out: List[SourceFile] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(_load(root, _pattern_rel(root, os.path.basename(root))))
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in ("__pycache__", "_build")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                out.append(_load(full, _pattern_rel(full, rel)))
    return out


def _pattern_rel(full: str, fallback: str) -> str:
    # explicit file/dir args may point INSIDE the package
    # (``graftcheck video_features_tpu/extract/base.py``): the hot/root
    # patterns are package-relative, so recover the tail from the full
    # path whenever it names the package dir
    posix = full.replace(os.sep, "/")
    return posix if "video_features_tpu/" in posix else fallback


def _load(path: str, rel: str) -> SourceFile:
    # checks run equally from the package dir or the repo root: pattern
    # matching always sees the package-relative tail
    if "video_features_tpu/" in rel:
        rel = rel.rsplit("video_features_tpu/", 1)[1]
    with open(path, "r", encoding="utf-8") as f:
        return SourceFile(path, f.read(), rel)


# --- shared AST helpers -----------------------------------------------------

def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """name -> dotted module/attr it refers to, from every import in the
    tree (module- and function-level): ``import numpy as np`` -> np:
    numpy; ``from jax import numpy as jnp`` -> jnp: jax.numpy."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); None for anything
    not a plain dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Dotted name with the import-alias head expanded: ``_np.asarray``
    -> ``numpy.asarray`` when ``import numpy as _np``."""
    dn = dotted_name(node)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    base = aliases.get(head, head)
    return f"{base}.{rest}" if rest else base


def is_jax_jit(node: ast.AST, aliases: Dict[str, str]) -> bool:
    rd = resolve_dotted(node, aliases)
    return rd in ("jax.jit", "jax.api.jit") or (
        rd is not None and rd.endswith(".jit") and rd.startswith("jax")
    )


@dataclasses.dataclass
class JitSite:
    """One ``jax.jit`` application: the node it decorates/wraps plus the
    static-argument declarations attached at the site."""

    node: ast.AST  # the jit call/decorator expression (for line info)
    func: Optional[ast.FunctionDef]  # the jitted def, when resolvable
    static_argnames: List[str]
    static_argnums: List[int]
    has_unknown_kwargs: bool  # **kwargs at the site: skip static checks


def _static_decls(call: ast.Call) -> Tuple[List[str], List[int], bool]:
    names: List[str] = []
    nums: List[int] = []
    unknown = False
    for kw in call.keywords:
        if kw.arg is None:
            unknown = True
        elif kw.arg == "static_argnames":
            names.extend(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            nums.extend(_const_ints(kw.value))
    return names, nums, unknown


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return out
    return []


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            el.value
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, int)
        ]
    return []


def jit_decoration(
    fn: ast.FunctionDef, aliases: Dict[str, str]
) -> Optional[JitSite]:
    """The JitSite for a ``@jax.jit`` / ``@partial(jax.jit, ...)``
    decorated def, or None."""
    for dec in fn.decorator_list:
        if is_jax_jit(dec, aliases):
            return JitSite(dec, fn, [], [], False)
        if isinstance(dec, ast.Call):
            callee = resolve_dotted(dec.func, aliases)
            if callee in ("functools.partial", "partial") and dec.args:
                if is_jax_jit(dec.args[0], aliases):
                    names, nums, unknown = _static_decls(dec)
                    return JitSite(dec, fn, names, nums, unknown)
            elif is_jax_jit(dec.func, aliases):
                # @jax.jit(static_argnames=...) direct-call form
                names, nums, unknown = _static_decls(dec)
                return JitSite(dec, fn, names, nums, unknown)
    return None


def param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


# --- runner -----------------------------------------------------------------

def run_checks(
    paths: Optional[Sequence[str]] = None,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run every static checker over ``paths`` (default: the installed
    package), drop waived findings, return the rest sorted by location.
    ``rules`` filters to findings whose rule id/name matches any token."""
    from video_features_tpu.analysis import (
        concurrency,
        durability,
        hostsync,
        jit_hygiene,
        numerics,
        obs_contract,
        sharding_contract,
        thread_safety,
    )
    from video_features_tpu.analysis.callgraph import CallGraph
    from video_features_tpu.analysis.taint import ProjectTaint

    sources = collect_sources(paths)
    # one call graph + taint context per sweep, shared by the
    # interprocedural passes (GC10x, GC301, GC31x, GC50x)
    graph = CallGraph(sources)
    project = ProjectTaint(sources, graph)
    findings: List[Finding] = []
    for src in sources:
        if src.is_hot:
            findings.extend(hostsync.check(src, project))
        findings.extend(jit_hygiene.check(src))
    findings.extend(thread_safety.check(sources, graph))
    findings.extend(concurrency.check(sources, graph, project))
    findings.extend(sharding_contract.check(sources, graph))
    findings.extend(durability.check(sources, graph, project))
    findings.extend(obs_contract.check(sources))
    findings.extend(numerics.check(sources, graph, project))

    kept = []
    for f in findings:
        src = next((s for s in sources if s.path == f.path), None)
        if src is not None and src.waived(f.line, f.rule):
            continue
        if rules and not any(f.rule.matches_token(t) for t in rules):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule.id))
    return kept


def all_rules() -> List[Rule]:
    from video_features_tpu.analysis import (
        concurrency,
        durability,
        hostsync,
        jit_hygiene,
        numerics,
        obs_contract,
        sharding_contract,
        thread_safety,
    )
    from video_features_tpu.analysis.compile_budget import BUDGET_RULE

    return [
        *hostsync.RULES.values(),
        *jit_hygiene.RULES.values(),
        thread_safety.RULE,
        *concurrency.RULES.values(),
        BUDGET_RULE,
        *sharding_contract.RULES.values(),
        *durability.RULES.values(),
        *obs_contract.RULES.values(),
        *numerics.RULES.values(),
    ]
