"""GC20x — jit-hygiene lint.

VirtualFlow's core argument (PAPERS.md) is that retracing/recompilation
cost dominates when model shapes leak into executables; this package's
answer is bucketed static shapes with everything per-video entering as
jit INPUTS. Three bug classes silently break that contract:

- **GC201 jit-mutable-closure** — a jitted function closing over a
  mutable value (list/dict/set, or a name rebound after the def) bakes
  trace-time state into the executable: later mutations are invisible,
  or worse, force retraces that fragment the executable cache.
- **GC202 jit-traced-branch** — Python ``if``/``while`` on a traced
  parameter either raises a ``TracerBoolConversionError`` at runtime or,
  with the parameter later made static, compiles one executable per
  VALUE — the per-resolution fragmentation the recompilation budget
  (analysis/compile_budget.py) exists to catch. Shape/dtype attribute
  branches (``x.ndim``, ``x.shape``, ``x.dtype``) are trace-time static
  and allowed; so are ``is None`` sentinels.
- **GC203 jit-static-args** — ``static_argnames`` naming a parameter
  that does not exist (or ``static_argnums`` out of range) silently
  declares nothing static; the call then traces the argument it was
  supposed to specialize on.

Sites covered: ``@jax.jit``, ``@partial(jax.jit, ...)`` decorators and
``jax.jit(fn, ...)`` call forms where ``fn`` resolves to a def in the
same module. Sites with ``**kwargs`` skip the static-decl checks (the
declaration is not statically visible).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from video_features_tpu.analysis.core import (
    Finding,
    JitSite,
    Rule,
    SourceFile,
    _static_decls,
    import_aliases,
    is_jax_jit,
    jit_decoration,
    param_names,
)

RULES = {
    "GC201": Rule(
        "GC201", "jit-mutable-closure",
        "jitted function captures a mutable/rebound value",
    ),
    "GC202": Rule(
        "GC202", "jit-traced-branch",
        "Python if/while branches on a traced parameter",
    ),
    "GC203": Rule(
        "GC203", "jit-static-args",
        "static_argnums/argnames must name real parameters",
    ),
}

# attributes of a traced array that are static at trace time — branching
# on them selects an executable, it does not trace a value
_STATIC_ATTRS = frozenset(
    {"shape", "ndim", "dtype", "size", "sharding", "aval", "weak_type", "itemsize"}
)
_MUTATING_METHODS = frozenset(
    {"append", "extend", "insert", "update", "add", "setdefault", "pop",
     "popitem", "clear", "remove", "discard"}
)


def check(src: SourceFile) -> List[Finding]:
    aliases = import_aliases(src.tree)
    findings: List[Finding] = []

    # walk with an explicit enclosing-function stack so closure captures
    # can be resolved against the scopes that actually bind them; each
    # scope is flattened through its compound statements (defs commonly
    # live under ``if``/``with`` blocks) without entering nested defs
    def visit(body: List[ast.stmt], scopes: List[ast.FunctionDef]) -> None:
        local_defs: Dict[str, ast.FunctionDef] = {}
        defs: List[ast.FunctionDef] = []
        stmts: List[ast.stmt] = []

        def flatten(b):
            for st in b:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.append(st)
                    local_defs[st.name] = st
                    continue
                if isinstance(st, ast.ClassDef):
                    flatten(st.body)  # methods close over the same scopes
                    continue
                stmts.append(st)
                for field in ("body", "orelse", "finalbody"):
                    flatten(getattr(st, field, []) or [])
                for h in getattr(st, "handlers", []) or []:
                    flatten(h.body)
                for case in getattr(st, "cases", []) or []:
                    flatten(case.body)

        flatten(body)
        for st in stmts:
            for child in ast.iter_child_nodes(st):
                if isinstance(
                    child,
                    (ast.stmt, ast.excepthandler, ast.FunctionDef,
                     ast.AsyncFunctionDef),
                ) or type(child).__name__ == "match_case":
                    continue
                for node in ast.walk(child):
                    if isinstance(node, ast.Call) and is_jax_jit(node.func, aliases):
                        site = _call_site(node, local_defs)
                        if site is not None:
                            check_site(site, scopes)
        for d in defs:
            site = jit_decoration(d, aliases)
            if site is not None:
                check_site(site, scopes)
            visit(d.body, scopes + [d])

    def _call_site(
        node: ast.Call, local_defs: Dict[str, ast.FunctionDef]
    ) -> Optional[JitSite]:
        names, nums, unknown = _static_decls(node)
        fn = None
        if node.args and isinstance(node.args[0], ast.Name):
            fn = local_defs.get(node.args[0].id)
        if fn is None and not names and not nums:
            return None  # nothing checkable: unknown target, no decls
        return JitSite(node, fn, names, nums, unknown)

    def check_site(site: JitSite, scopes: List[ast.FunctionDef]) -> None:
        fn = site.func
        if fn is not None and not site.has_unknown_kwargs:
            params = param_names(fn)
            for name in site.static_argnames:
                if name not in params:
                    findings.append(
                        Finding(
                            src.path, site.node.lineno, site.node.col_offset,
                            RULES["GC203"],
                            f"static_argnames names {name!r} which is not a "
                            f"parameter of {fn.name!r} (has: {', '.join(params)})",
                            "rename the entry to an actual parameter, or drop it",
                        )
                    )
            n_pos = len(fn.args.posonlyargs) + len(fn.args.args)
            for num in site.static_argnums:
                if num >= n_pos or num < -n_pos:
                    findings.append(
                        Finding(
                            src.path, site.node.lineno, site.node.col_offset,
                            RULES["GC203"],
                            f"static_argnums {num} is out of range for "
                            f"{fn.name!r} ({n_pos} positional parameter(s))",
                            "point static_argnums at a real positional parameter",
                        )
                    )
        if fn is None:
            return
        _check_traced_branches(fn, site)
        if scopes:
            _check_mutable_closure(fn, scopes)

    def _check_traced_branches(fn: ast.FunctionDef, site: JitSite) -> None:
        static: Set[str] = set(site.static_argnames)
        pos = fn.args.posonlyargs + fn.args.args
        n_pos = len(pos)
        for num in site.static_argnums:
            if -n_pos <= num < n_pos:
                static.add(pos[num].arg)
        traced = [p for p in param_names(fn) if p not in static]
        if not traced:
            return
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            else:
                continue
            bad = _traced_name_in_test(test, traced)
            if bad is not None:
                kind = type(node).__name__.lower()
                findings.append(
                    Finding(
                        src.path, test.lineno, test.col_offset, RULES["GC202"],
                        f"{kind} test reads traced parameter {bad!r} inside "
                        f"jitted {fn.name!r}",
                        "use jnp.where/lax.cond/lax.while_loop, or declare the "
                        "parameter static (and accept one executable per value)",
                    )
                )

    def _check_mutable_closure(
        fn: ast.FunctionDef, scopes: List[ast.FunctionDef]
    ) -> None:
        captured = _free_names(fn)
        if not captured:
            return
        for scope in reversed(scopes):
            binds, reasons = _scope_bindings(scope, fn)
            for name in sorted(captured & set(binds)):
                reason = reasons.get(name)
                if reason is not None:
                    findings.append(
                        Finding(
                            src.path, fn.lineno, fn.col_offset, RULES["GC201"],
                            f"jitted {fn.name!r} captures {name!r} from "
                            f"{scope.name!r}, which {reason}",
                            "pass the value as a (static_*) argument, or bind "
                            "an immutable snapshot before the def",
                        )
                    )
            captured -= set(binds)

    visit(src.tree.body, [])
    return findings


def _traced_name_in_test(test: ast.AST, traced: List[str]) -> Optional[str]:
    """The first traced parameter whose VALUE the test converts to a
    Python bool; None when every occurrence is trace-time static."""
    ok_nodes: Set[int] = set()
    for node in ast.walk(test):
        # x.ndim / x.shape / x.dtype ... : static under tracing
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            for sub in ast.walk(node.value):
                ok_nodes.add(id(sub))
        # len(x) raises on tracers already caught elsewhere; isinstance()
        # and `x is None` / `x is not None` are identity, not value
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            for sub in ast.walk(node):
                ok_nodes.add(id(sub))
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            if fname in ("isinstance", "len", "getattr", "hasattr", "callable"):
                for sub in ast.walk(node):
                    ok_nodes.add(id(sub))
    for node in ast.walk(test):
        if (
            isinstance(node, ast.Name)
            and node.id in traced
            and id(node) not in ok_nodes
        ):
            return node.id
    return None


def _free_names(fn: ast.FunctionDef) -> Set[str]:
    """Names ``fn`` loads but does not bind itself (params, locals,
    imports, nested defs all bind)."""
    bound: Set[str] = set(param_names(fn))
    loads: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            bound.add(node.name)
        elif isinstance(node, ast.Name):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                bound.add(node.id)
            else:
                loads.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                bound.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, (ast.comprehension,)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    bound.add(n.id)
    return loads - bound


def _scope_bindings(
    scope: ast.FunctionDef, jitted: ast.FunctionDef
) -> Tuple[Set[str], Dict[str, str]]:
    """Names bound in ``scope`` (params + assigned locals), and for each
    a reason string when capturing it from a jitted def is unsafe."""
    binds: Set[str] = set(param_names(scope))
    reasons: Dict[str, str] = {}

    def note(name: str, reason: str) -> None:
        reasons.setdefault(name, reason)

    for node in ast.walk(scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not scope:
            binds.add(node.name)
            if node is jitted:
                continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in _names_of(t):
                    binds.add(n)
                    if _is_mutable_literal(node.value):
                        note(n, "is bound to a mutable literal")
                    if (
                        node.lineno > jitted.lineno
                        and n != jitted.name
                        and _reaches(scope, jitted, node)
                    ):
                        note(n, f"is rebound after the def (line {node.lineno})")
        elif isinstance(node, ast.AugAssign):
            for n in _names_of(node.target):
                binds.add(n)
                note(n, "is mutated with an augmented assignment")
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            for n in _names_of(node.target):
                binds.add(n)
                if _is_mutable_literal(node.value):
                    note(n, "is bound to a mutable literal")
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATING_METHODS and isinstance(
                node.func.value, ast.Name
            ):
                note(node.func.value.id, f"is mutated via .{node.func.attr}()")
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Store):
            if isinstance(node.value, ast.Name):
                note(node.value.id, "is mutated via item assignment")
        elif isinstance(node, ast.For):
            for n in _names_of(node.target):
                binds.add(n)
                if node.lineno < jitted.lineno:
                    # a def INSIDE a for loop capturing the loop variable
                    # is the classic late-binding bug; only flag when the
                    # jitted def is lexically inside the loop body
                    if _contains(node, jitted):
                        note(n, "is a loop variable (late binding)")
    return binds, reasons


def _names_of(t: ast.AST) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in t.elts:
            out.extend(_names_of(el))
        return out
    if isinstance(t, ast.Starred):
        return _names_of(t.value)
    return []


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                         ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("list", "dict", "set", "defaultdict", "deque",
                                "Counter", "OrderedDict", "bytearray")
    return False


def _contains(outer: ast.AST, inner: ast.AST) -> bool:
    return any(n is inner for n in ast.walk(outer))


def _suites_of(st: ast.stmt) -> List[List[ast.stmt]]:
    out: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        suite = getattr(st, field, None)
        if suite:
            out.append(suite)
    for h in getattr(st, "handlers", []) or []:
        out.append(h.body)
    for case in getattr(st, "cases", []) or []:
        out.append(case.body)
    return out


def _suite_path(
    scope: ast.FunctionDef, jitted: ast.FunctionDef
) -> List[Tuple[List[ast.stmt], int]]:
    """(suite, index) chain from ``scope.body`` down to the suite holding
    ``jitted`` directly; empty when the def isn't lexically in scope."""

    def search(suite: List[ast.stmt]) -> Optional[List[Tuple[List[ast.stmt], int]]]:
        for i, st in enumerate(suite):
            if st is jitted:
                return [(suite, i)]
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scope: its suites are not this flow
            if _contains(st, jitted):
                for sub in _suites_of(st):
                    hit = search(sub)
                    if hit is not None:
                        return [(suite, i)] + hit
                return None
        return None

    return search(scope.body) or []


def _reaches(scope: ast.FunctionDef, jitted: ast.FunctionDef,
             rebind: ast.AST) -> bool:
    """Whether control can flow from the ``jitted`` def to ``rebind``.

    Walks each enclosing suite outward from the def; a bare
    ``return``/``raise`` met before the rebind means everything after it
    (in this suite and all outer ones) is unreachable from that branch —
    the mutually-exclusive-branch pattern (mesh vs single-device fn
    factories ending in ``return fns``) is not a capture hazard.
    Conditional terminals (``if ...: return``) fall through, keeping the
    check conservative."""
    path = _suite_path(scope, jitted)
    if not path:
        return True  # couldn't place the def: assume reachable
    for suite, idx in reversed(path):
        for st in suite[idx + 1:]:
            if st is rebind or _contains(st, rebind):
                return True
            if isinstance(st, (ast.Return, ast.Raise)):
                return False
    return False
