"""GC60x — durability contracts for the crash-consistency layer.

PRs 13–16 built the fleet's survival story on a handful of filesystem
idioms: stage-under-``.tmp``-then-one-``os.replace`` publication
(io/sink.py, telemetry/ledger.py, serve/costmodel.py), claim-by-rename
work distribution (extract/cache.py, serve/sources.py), O_EXCL skip
claims (runtime/faults.py), and mtime-heartbeat lease files
(serve/sources.py). The chaos drills prove these protocols work *today*;
nothing stops a refactor from quietly replacing an atomic publish with a
bare ``json.dump`` — the torn-file bug only reappears under SIGKILL, far
from CI. GC60x makes the idioms themselves machine-checked:

- **GC601 durable-write-atomicity** — a raw write (``open(..., 'w')``,
  ``np.save``) whose target path mentions a durable root (``_manifest/``,
  ``_requests/``, ``_replicas/``, ``_telemetry/``, the cache or
  compile-cache neighborhoods, the spool) must stage under a temp sibling
  and publish with a single ``os.replace``/``os.rename`` in the same
  function — or go through a helper that does (interprocedural: a helper
  that renames satisfies its callers; a helper that raw-writes a
  parameter path is flagged at the caller passing the durable path, with
  the write site in the trace).
- **GC602 claim-protocol** — claim sites must branch on the failure
  outcome instead of assuming victory: ``os.open(..., O_CREAT|O_EXCL)``
  and rename-claims (dest mentions ``claim``/``lease``) need an enclosing
  ``try`` catching ``FileExistsError``/``OSError``; and a module that
  acquires lease/claim files by rename must heartbeat them — an
  ``os.utime`` reachable (exact-callee walk) from the module's poll loop,
  so a wedged-but-alive replica's leases go stale honestly.
- **GC603 rename-semantics** — a bare ``os.rename`` outside any
  ``try``/``except OSError`` is wrong on both of its legitimate readings:
  a *publish* wants ``os.replace`` (atomic overwrite, same semantics on
  every platform), a *claim* wants the loser branch GC602 enforces. Also
  flags ``tempfile`` staging without ``dir=`` whose product feeds a
  rename/replace: a temp file from the default tmpdir can sit on a
  different filesystem, where rename is not atomic (EXDEV).

Resolution is exact-only (concurrency.py semantics) and helper summaries
are depth-1: a caller is satisfied by the helper it calls directly, not
by a rename three frames down — the fix GC601 pushes toward is one
shared ``atomic_write_json``, not deep plumbing. Findings carry the
write/rename provenance in ``trace`` (``--explain GC601``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.callgraph import CallGraph, FunctionInfo
from video_features_tpu.analysis.concurrency import _exact_callees, _own_nodes
from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    import_aliases,
    resolve_dotted,
)
from video_features_tpu.analysis.taint import ProjectTaint

RULES = {
    "GC601": Rule(
        "GC601", "durable-write-atomicity",
        "a durable file (manifest/requests/telemetry/cache roots) is "
        "written in place — a kill mid-write leaves a torn file a reader "
        "will trust",
    ),
    "GC602": Rule(
        "GC602", "claim-protocol",
        "a claim/lease site assumes victory (no failure branch) or a "
        "lease module has no heartbeat reachable from its poll loop",
    ),
    "GC603": Rule(
        "GC603", "rename-semantics",
        "os.rename without a failure branch (publishes need os.replace), "
        "or tempfile staging outside the destination directory",
    ),
}

# Substrings of a write target's resolved text that mark it durable:
# shared-filesystem state another process (or the next run) will read
# back and trust. Matches both path constants ("_manifest/") and the
# identifier names flowing into the path (self._manifest_path, spool_dir).
_DURABLE_TOKENS = (
    "_manifest", "_requests", "_replicas", "_telemetry", "_skip_claims",
    "cache_dir", "compile_cache", "compilation_cache", "cost_model",
    "spool", "ledger_path",
)
_CLAIM_TOKENS = ("claim", "lease")
_WRITE_MODES = ("w", "x", "a")  # "a" handled separately (append is safe)
_FAILURE_HANDLERS = frozenset(
    {"OSError", "FileExistsError", "IOError", "EnvironmentError",
     "PermissionError", "Exception", "BaseException"}
)
_TEMPFILE_CTORS = frozenset(
    {"tempfile.mkstemp", "tempfile.mktemp", "tempfile.NamedTemporaryFile",
     "tempfile.TemporaryFile"}
)


def _const_text(expr: Optional[ast.AST]) -> List[str]:
    """Every string constant + identifier appearing in ``expr`` — the
    searchable text of a path expression."""
    out: List[str] = []
    if expr is None:
        return out
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append(node.value)
        elif isinstance(node, ast.Name):
            out.append(node.id)
        elif isinstance(node, ast.Attribute):
            out.append(node.attr)
    return out


def _names_of(expr: Optional[ast.AST]) -> Set[str]:
    """Local names a path expression is built from (for pairing a write's
    target with a later rename's source)."""
    if expr is None:
        return set()
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


@dataclasses.dataclass
class _WriteSite:
    node: ast.AST  # anchor (the open/np.save call)
    path: ast.AST  # the target path expression


@dataclasses.dataclass
class _RenameSite:
    node: ast.Call
    src_expr: Optional[ast.AST]
    dst_expr: Optional[ast.AST]
    op: str  # "os.rename" | "os.replace"
    guarded: bool  # inside try/except catching OSError-ish


@dataclasses.dataclass
class _FnScan:
    """One function's durability-relevant facts."""

    writes: List[_WriteSite] = dataclasses.field(default_factory=list)
    renames: List[_RenameSite] = dataclasses.field(default_factory=list)
    excl_opens: List[Tuple[ast.Call, bool]] = dataclasses.field(
        default_factory=list
    )  # (os.open O_EXCL site, guarded)
    utime_lines: List[int] = dataclasses.field(default_factory=list)
    tempfiles: List[Tuple[ast.Call, bool, Set[str]]] = dataclasses.field(
        default_factory=list
    )  # (call, has dir=, names bound to its result)
    assigns: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)


def _handler_covers_failure(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:  # bare except
        return True
    names = []
    for sub in ast.walk(handler.type):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.append(sub.attr)
    return any(n in _FAILURE_HANDLERS for n in names)


def _is_write_mode(call: ast.Call) -> Optional[str]:
    """The constant mode string of an ``open`` call, when write-ish."""
    mode: Optional[ast.AST] = call.args[1] if len(call.args) > 1 else None
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return None
    m = mode.value
    return m if any(c in m for c in _WRITE_MODES) else None


def _scan_fn(fn: ast.AST, src: SourceFile, aliases: Dict[str, str]) -> _FnScan:
    scan = _FnScan()
    handle_names: Set[str] = set()  # with open(p, 'w') as fh -> fh

    def walk(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, ast.Try):
            covers = any(_handler_covers_failure(h) for h in node.handlers)
            for st in node.body:
                walk(st, guarded or covers)
            for part in (node.handlers, node.orelse, node.finalbody):
                for st in part:
                    walk(st, guarded)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ce = item.context_expr
                if (
                    isinstance(ce, ast.Call)
                    and resolve_dotted(ce.func, aliases) == "open"
                    and isinstance(item.optional_vars, ast.Name)
                ):
                    handle_names.add(item.optional_vars.id)
        if isinstance(node, ast.Assign):
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                scan.assigns[node.targets[0].id] = node.value
            if isinstance(node.value, ast.Call):
                rd = resolve_dotted(node.value.func, aliases)
                if rd in _TEMPFILE_CTORS:
                    names: Set[str] = set()
                    for t in node.targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name):
                                names.add(sub.id)
                    has_dir = any(kw.arg == "dir" for kw in node.value.keywords)
                    scan.tempfiles.append((node.value, has_dir, names))
        if isinstance(node, ast.Call):
            rd = resolve_dotted(node.func, aliases)
            if rd == "open" and node.args:
                mode = _is_write_mode(node)
                if mode and "a" not in mode:  # appends tear a line, not a file
                    scan.writes.append(_WriteSite(node, node.args[0]))
            elif rd in ("numpy.save", "numpy.savez", "numpy.savez_compressed", "np.save"):
                if node.args and not (
                    isinstance(node.args[0], ast.Name)
                    and node.args[0].id in handle_names
                ):
                    scan.writes.append(_WriteSite(node, node.args[0]))
            elif rd in ("os.rename", "os.replace"):
                scan.renames.append(
                    _RenameSite(
                        node,
                        node.args[0] if node.args else None,
                        node.args[1] if len(node.args) > 1 else None,
                        rd, guarded,
                    )
                )
            elif rd == "os.open":
                flags_text = " ".join(
                    t for a in node.args[1:] for t in _const_text(a)
                )
                if "O_EXCL" in flags_text:
                    scan.excl_opens.append((node, guarded))
            elif rd == "os.utime":
                scan.utime_lines.append(node.lineno)
        for child in ast.iter_child_nodes(node):
            walk(child, guarded)

    walk(fn, False)
    return scan


def _resolved_text(expr: Optional[ast.AST], scan: _FnScan) -> str:
    """Path-expression text with one hop of local-assignment resolution:
    ``tmp = f"{path}.tmp"`` makes the text of ``tmp`` include ``path``'s
    constants and names."""
    parts = _const_text(expr)
    seen: Set[str] = set()
    frontier = [n for n in _names_of(expr)]
    for _ in range(3):  # bounded chain: tmp -> path -> self.attr
        nxt: List[str] = []
        for name in frontier:
            if name in seen:
                continue
            seen.add(name)
            sub = scan.assigns.get(name)
            if sub is not None:
                parts.extend(_const_text(sub))
                nxt.extend(_names_of(sub))
        frontier = nxt
    return "\x00".join(parts)


def _expr_names_resolved(expr: Optional[ast.AST], scan: _FnScan) -> Set[str]:
    names = set(_names_of(expr))
    for name in list(names):
        sub = scan.assigns.get(name)
        if sub is not None:
            names |= _names_of(sub)
    return names


def _is_durable(text: str) -> Optional[str]:
    for tok in _DURABLE_TOKENS:
        if tok in text:
            return tok
    return None


def _write_is_atomic(site: _WriteSite, scan: _FnScan) -> bool:
    wnames = _expr_names_resolved(site.path, scan)
    for rn in scan.renames:
        if wnames & _expr_names_resolved(rn.src_expr, scan):
            return True
    # fallback: the target is visibly a temp sibling and the function
    # publishes *something* — the pairing is by convention, not by name
    text = _resolved_text(site.path, scan).lower()
    return bool(scan.renames) and (".tmp" in text or ".part" in text)


def _fn_params(info: FunctionInfo) -> List[str]:
    a = info.node.args
    return [p.arg for p in a.posonlyargs + a.args]


def check(
    sources: Sequence[SourceFile], graph: CallGraph, project: ProjectTaint
) -> List[Finding]:
    findings: List[Finding] = []
    scans: Dict[str, _FnScan] = {}
    # helper summaries: fn key -> [(param name, positional index, write line)]
    raw_param_writes: Dict[str, List[Tuple[str, int, int]]] = {}
    # rel -> (first claiming function, its claim sites): heartbeat check
    # runs after every function is scanned, one finding per module
    module_claims: Dict[str, Tuple[FunctionInfo, List[_RenameSite]]] = {}

    for key in sorted(graph.functions):
        info = graph.functions[key]
        if info.src.rel.startswith("analysis/"):
            continue
        aliases = graph._aliases[info.src.rel]
        scan = _scan_fn(info.node, info.src, aliases)
        scans[key] = scan
        params = _fn_params(info)
        for site in scan.writes:
            if _write_is_atomic(site, scan):
                continue
            text = _resolved_text(site.path, scan)
            tok = _is_durable(text)
            if tok is not None:
                findings.append(
                    Finding(
                        info.src.path, site.node.lineno, site.node.col_offset,
                        RULES["GC601"],
                        f"durable path (mentions {tok!r}) written in place in "
                        f"{info.name!r} with no staged rename — a kill "
                        "mid-write leaves a torn file",
                        "write to a same-directory .tmp sibling and publish "
                        "with one os.replace — io/sink.py atomic_write_json "
                        "is the shared shape",
                        trace=[
                            f"{info.src.path}:{site.node.lineno}: raw write "
                            f"in {info.name}() with no os.replace pairing "
                            "its target",
                        ],
                    )
                )
                continue
            # a helper writing straight through a parameter path: judged
            # at the call sites that pass durable paths in
            pnames = _names_of(site.path) & set(params)
            for p in pnames:
                raw_param_writes.setdefault(key, []).append(
                    (p, params.index(p), site.node.lineno)
                )

        # -- GC602: claim sites must branch on losing ------------------------
        for call, guarded in scan.excl_opens:
            if not guarded:
                findings.append(
                    Finding(
                        info.src.path, call.lineno, call.col_offset,
                        RULES["GC602"],
                        f"O_EXCL claim in {info.name!r} has no failure "
                        "branch — losing the race raises FileExistsError "
                        "into the caller",
                        "wrap the claim in try/except FileExistsError (the "
                        "loser path) and except OSError (claim-side I/O "
                        "failure) — runtime/faults.py claim_skip_record is "
                        "the shape",
                    )
                )
        claim_sites: List[_RenameSite] = []
        for rn in scan.renames:
            dst_text = _resolved_text(rn.dst_expr, scan).lower()
            if any(t in dst_text for t in _CLAIM_TOKENS):
                claim_sites.append(rn)
                if not rn.guarded:
                    findings.append(
                        Finding(
                            info.src.path, rn.node.lineno,
                            rn.node.col_offset, RULES["GC602"],
                            f"rename-claim in {info.name!r} assumes victory "
                            "— the losing replica's rename raises OSError "
                            "uncaught",
                            "branch on the loser: try/except OSError around "
                            "the claim rename (serve/sources.py poll_once is "
                            "the shape)",
                        )
                    )
            elif rn.op == "os.rename" and not rn.guarded:
                # -- GC603: bare rename, neither publish nor claim shape ------
                findings.append(
                    Finding(
                        info.src.path, rn.node.lineno, rn.node.col_offset,
                        RULES["GC603"],
                        f"bare os.rename in {info.name!r}: a publish wants "
                        "os.replace (atomic overwrite everywhere), a claim "
                        "wants a try/except OSError loser branch",
                        "use os.replace for last-write-wins publication, or "
                        "guard the rename and treat OSError as losing the "
                        "claim race",
                    )
                )
        if claim_sites:
            module_claims.setdefault(info.src.rel, (info, claim_sites))

        # -- GC603: tempfile staging outside the destination dir -------------
        rename_src_names: Set[str] = set()
        for rn in scan.renames:
            rename_src_names |= _expr_names_resolved(rn.src_expr, scan)
        for call, has_dir, names in scan.tempfiles:
            if not has_dir and names & rename_src_names:
                findings.append(
                    Finding(
                        info.src.path, call.lineno, call.col_offset,
                        RULES["GC603"],
                        f"tempfile staged in the default tmpdir feeds a "
                        f"rename in {info.name!r} — across filesystems the "
                        "rename is not atomic (EXDEV)",
                        "create the temp file next to its destination: "
                        "tempfile.mkstemp(dir=os.path.dirname(dest)), or a "
                        "f'{dest}.…tmp' sibling",
                    )
                )

    for info, claim_sites in module_claims.values():
        _lease_heartbeat(info, claim_sites, graph, scans, findings)

    # -- GC601 interprocedural: durable paths handed to raw-writing helpers --
    for key in sorted(graph.functions):
        info = graph.functions[key]
        if info.src.rel.startswith("analysis/"):
            continue
        caller_scan = scans.get(key)
        if caller_scan is None:
            continue
        caller_rename_names: Set[str] = set()
        for rn in caller_scan.renames:
            caller_rename_names |= _expr_names_resolved(rn.src_expr, caller_scan)
        for node in _own_nodes(info.node):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            for ck in _exact_callees(node.func, info.src, info, graph):
                for pname, pidx, wline in raw_param_writes.get(ck, ()):
                    callee = graph.functions[ck]
                    # method calls drop the explicit self argument
                    argidx = pidx - (1 if _fn_params(callee)[:1] == ["self"] else 0)
                    if not 0 <= argidx < len(node.args):
                        continue
                    arg = node.args[argidx]
                    tok = _is_durable(_resolved_text(arg, caller_scan))
                    if tok is None:
                        continue
                    if _names_of(arg) & caller_rename_names:
                        continue  # the caller stages + renames it itself
                    findings.append(
                        Finding(
                            info.src.path, node.lineno, node.col_offset,
                            RULES["GC601"],
                            f"durable path (mentions {tok!r}) passed to "
                            f"{callee.name!r}, which writes it in place "
                            "with no staged rename",
                            "make the helper atomic (stage under .tmp, one "
                            "os.replace — io/sink.py atomic_write_json), or "
                            "stage in the caller",
                            trace=[
                                f"{info.src.path}:{node.lineno}: durable "
                                f"path built in {info.name}() flows into "
                                f"parameter {pname!r}",
                                f"{callee.src.path}:{wline}: raw write "
                                f"through {pname!r} in {callee.name}()",
                            ],
                        )
                    )
    return findings


def _lease_heartbeat(
    info: FunctionInfo,
    claim_sites: List[_RenameSite],
    graph: CallGraph,
    scans: Dict[str, _FnScan],
    findings: List[Finding],
) -> None:
    """A module acquiring claim/lease files by rename must refresh their
    mtime: ``os.utime`` somewhere in the module, reachable through exact
    callees from the module's poll loop when it has one."""
    src = info.src
    module_keys = [k for k, f in graph.functions.items() if f.src is src]
    utime_keys = {
        k for k in module_keys if scans.get(k) and scans[k].utime_lines
    }
    if utime_keys:
        poll_keys = [
            k for k in module_keys
            if "poll" in graph.functions[k].name or graph.functions[k].name == "run"
        ]
        if not poll_keys:
            return  # heartbeat exists; no poll loop in view to anchor on
        reachable: Set[str] = set(poll_keys)
        frontier = list(poll_keys)
        for _ in range(4):
            nxt: List[str] = []
            for k in frontier:
                fi = graph.functions[k]
                for node in _own_nodes(fi.node):
                    if isinstance(node, ast.Call):
                        for ck in _exact_callees(node.func, fi.src, fi, graph):
                            if ck not in reachable:
                                reachable.add(ck)
                                nxt.append(ck)
            frontier = nxt
        if utime_keys & reachable:
            return
        reason = (
            "an os.utime exists in the module but is not reachable from "
            "the poll loop — leases never refresh while polling"
        )
    else:
        reason = (
            "no os.utime anywhere in the module — held leases look stale "
            "to every peer and get stolen while this owner still works"
        )
    site = claim_sites[0]
    findings.append(
        Finding(
            src.path, site.node.lineno, site.node.col_offset, RULES["GC602"],
            f"claim/lease files acquired in {info.name!r} are never "
            f"heartbeat: {reason}",
            "pair acquisition with an os.utime refresh in the owner's poll "
            "pass (serve/sources.py _lease_pass is the shape)",
        )
    )
