"""Interprocedural device-taint for the GC10x host-sync lint (v2).

v1's taint was intra-function: a device array returned through a helper
and ``.item()``'d in the caller was invisible (ROADMAP residual). v2
computes per-function *taint summaries* over the project call graph and
propagates device-ness in both directions:

- **returns**: a helper whose return value is device-tainted taints the
  call expression in every caller (``h = helper(x); float(h)`` flags in
  the caller);
- **parameters**: a device value passed into a helper taints the matching
  parameter inside the helper, and a helper that returns one of its
  parameters propagates the argument's taint back to the call site.

Every device fact carries a provenance chain — (path, line, description)
steps from the origin to the sync site — surfaced as ``Finding.trace``
and printed by the CLI's ``--explain``.

Call resolution for taint is *exact-only* (module functions, imported
project functions, ``self.method`` on the caller's own class): the
thread-safety walk wants conservative fan-out, but taint powering a lint
on hot files must not let one project function named ``get`` taint every
``obj.get()`` in the tree. Unresolvable calls fall back to v1 semantics:
the call is tainted iff an argument is.

Summaries are a fixpoint over the call graph (taint only grows, so
recursion converges), then a second fixpoint pushes caller-argument
taint into callees. The project graph is a few hundred functions; the
whole pass stays inside bench.py's ``analysis_overhead`` budget.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.callgraph import CallGraph, FunctionInfo
from video_features_tpu.analysis.core import (
    SourceFile,
    import_aliases,
    jit_decoration,
    param_names,
    resolve_dotted,
)

# jax calls whose results are HOST values (never taint). Includes the
# multihost collectives whose JOB is a host-level agreement: PR 4 waived
# ``broadcast_one_to_all`` at its one call site; v2 encodes the fact
# instead — the result is a host-side numpy value every process agrees
# on, and flagging the ``bool()`` around it taught nothing.
_HOST_RESULTS = frozenset(
    {
        "jax.device_get",
        "jax.process_index",
        "jax.process_count",
        "jax.device_count",
        "jax.local_device_count",
        "jax.devices",
        "jax.local_devices",
        "jax.default_backend",
        "jax.eval_shape",
        "jax.experimental.multihost_utils.broadcast_one_to_all",
        "jax.experimental.multihost_utils.process_allgather",
    }
)
_FETCHERS = frozenset({"numpy.asarray", "numpy.array", "jax.device_get"})
_DEVICE_HEADS = ("jax", "lax", "flax")
# array metadata lives on the HOST even for device arrays: geometry
# derived from .shape/.ndim/.dtype never syncs (jit_hygiene GC202 makes
# the same trace-time-static call for branch conditions)
_HOST_ATTRS = frozenset(
    {"shape", "ndim", "dtype", "size", "nbytes", "itemsize", "sharding"}
)

Step = Tuple[str, int, str]  # (path, line, description)


@dataclasses.dataclass(frozen=True)
class Taint:
    """Taint of one value: device-ness (with provenance) plus which of
    the enclosing function's parameters flow into it (for summaries)."""

    device: bool = False
    params: frozenset = frozenset()
    chain: Tuple[Step, ...] = ()

    def __or__(self, other: "Taint") -> "Taint":
        return Taint(
            device=self.device or other.device,
            params=self.params | other.params,
            chain=self.chain if self.device else other.chain,
        )


EMPTY = Taint()


def _device(chain: Tuple[Step, ...]) -> Taint:
    return Taint(device=True, chain=chain)


@dataclasses.dataclass
class Summary:
    """What a function's RETURN value carries: device taint (with the
    chain back to its origin) and/or parameter indices that flow out."""

    returns: Taint = EMPTY


class ProjectTaint:
    """Shared taint state over one ``run_checks`` source set."""

    def __init__(self, sources: Sequence[SourceFile], graph: CallGraph) -> None:
        self.sources = list(sources)
        self.graph = graph
        self.summaries: Dict[str, Summary] = {}
        # externally induced param taint: key -> {param index: chain}
        self.param_taint: Dict[str, Dict[int, Tuple[Step, ...]]] = {}
        # post-fixpoint name envs (closures inherit; hostsync flags from)
        self._env: Dict[str, Dict[str, Taint]] = {}
        self._module_env: Dict[str, Dict[str, Taint]] = {}
        self._aliases = {s.rel: import_aliases(s.tree) for s in sources}
        self._compute()

    # --- public API ---------------------------------------------------------

    def env_for(self, key: str) -> Dict[str, Taint]:
        return self._env.get(key, {})

    def module_env(self, src: SourceFile) -> Dict[str, Taint]:
        return self._module_env.get(src.rel, {})

    def expr_taint(
        self,
        node: ast.AST,
        env: Dict[str, Taint],
        src: SourceFile,
        info: Optional[FunctionInfo],
    ) -> Taint:
        return self._expr(node, env, src, info)

    # --- fixpoints ----------------------------------------------------------

    def _compute(self) -> None:
        order = self._definition_order()
        for _ in range(5):  # summary fixpoint
            self._scan_modules()
            changed = False
            for info in order:
                taints, ret = self._scan(info)
                self._env[info.key] = taints
                old = self.summaries.get(info.key)
                if old is None or old.returns != ret:
                    self.summaries[info.key] = Summary(ret)
                    changed = True
            if not changed:
                break
        for _ in range(5):  # caller-arg -> callee-param fixpoint
            pushed = False
            for info in order:
                if self._push_args(info, self._env[info.key]):
                    pushed = True
            if not pushed:
                break
            self._scan_modules()
            for info in order:
                taints, ret = self._scan(info)
                self._env[info.key] = taints
                self.summaries[info.key] = Summary(ret)

    def _definition_order(self) -> List[FunctionInfo]:
        # outer before inner, so closure envs exist when nested defs scan
        return sorted(
            self.graph.functions.values(),
            key=lambda f: (f.src.rel, f.node.lineno, f.node.col_offset),
        )

    def _scan_modules(self) -> None:
        for src in self.sources:
            env = self._module_env.setdefault(src.rel, {})
            flat = flatten_body(src.tree.body)
            for _ in range(2):
                if not self._assign_pass(flat, env, src, None):
                    break

    def _push_args(self, info: FunctionInfo, taints: Dict[str, Taint]) -> bool:
        changed = False
        for site in self.graph.calls.get(info.key, ()):
            callee = self.graph.functions.get(site.callee)
            if callee is None:
                continue
            pnames = param_names(callee.node)
            skip = 1 if callee.cls and pnames and pnames[0] in ("self", "cls") else 0
            for i, arg in enumerate(site.node.args):
                t = self._expr(arg, taints, info.src, info)
                if not t.device:
                    continue
                idx = i + skip
                if idx >= len(pnames):
                    break
                slot = self.param_taint.setdefault(callee.key, {})
                if idx not in slot:
                    slot[idx] = t.chain + (
                        (
                            info.src.path,
                            site.node.lineno,
                            f"passed to {callee.name}() as {pnames[idx]!r}",
                        ),
                    )
                    changed = True
        return changed

    # --- per-function scan --------------------------------------------------

    def initial_taints(self, info: FunctionInfo) -> Dict[str, Taint]:
        taints: Dict[str, Taint] = {}
        names = param_names(info.node)
        site = jit_decoration(info.node, self._aliases[info.src.rel])
        static = set(site.static_argnames) if site else set()
        for i, p in enumerate(names):
            t = Taint(params=frozenset({i}))
            if site is not None and p not in static:
                t = t | _device(
                    ((info.src.path, info.node.lineno,
                      f"parameter {p!r} of jitted {info.name!r}"),)
                )
            ext = self.param_taint.get(info.key, {}).get(i)
            if ext is not None:
                t = t | _device(ext)
            taints[p] = t
        # closure inheritance: enclosing scope's device taints flow in,
        # minus names this function binds itself (params / assignments)
        outer = (
            self._env.get(info.parent)
            if info.parent
            else self._module_env.get(info.src.rel)
        )
        if outer:
            bound = set(names) | _assigned_names(info.node)
            for n, t in outer.items():
                if n not in bound and t.device:
                    taints[n] = Taint(device=True, chain=t.chain)
        return taints

    def _scan(self, info: FunctionInfo) -> Tuple[Dict[str, Taint], Taint]:
        taints = self.initial_taints(info)
        flat = flatten_body(info.node.body)
        for _ in range(4):
            if not self._assign_pass(flat, taints, info.src, info):
                break
        ret = EMPTY
        for st in flat:
            if isinstance(st, ast.Return) and st.value is not None:
                ret = ret | self._expr(st.value, taints, info.src, info)
        return taints, ret

    def _assign_pass(
        self,
        flat: List[ast.stmt],
        taints: Dict[str, Taint],
        src: SourceFile,
        info: Optional[FunctionInfo],
    ) -> bool:
        changed = False
        for st in flat:
            if not isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                continue
            value = st.value
            if value is None:
                continue
            t = self._expr(value, taints, src, info)
            if not t.device and not t.params:
                continue
            targets = st.targets if isinstance(st, ast.Assign) else [st.target]
            for tgt in targets:
                for n in _target_names(tgt):
                    old = taints.get(n, EMPTY)
                    new = old | (
                        Taint(
                            device=True,
                            params=t.params,
                            chain=t.chain
                            + ((src.path, st.lineno, f"assigned to {n!r}"),),
                        )
                        if t.device
                        else t
                    )
                    if new != old:
                        taints[n] = new
                        changed = True
        return changed

    # --- expression taint ---------------------------------------------------

    def _taint_callees(
        self, func: ast.AST, src: SourceFile, info: Optional[FunctionInfo]
    ) -> List[str]:
        """Exact-only callee resolution (no by-name fan-out): module and
        imported project functions, nested defs, ``self.method`` on the
        caller's own class."""
        graph = self.graph
        if isinstance(func, ast.Name):
            keys, _ = graph.resolve_call(func, src, info)
            return keys
        if isinstance(func, ast.Attribute):
            aliases = self._aliases[src.rel]
            rd = resolve_dotted(func.value, aliases)
            if rd is not None:
                m = graph.resolve_module(rd)
                if m is not None:
                    hit = graph.module_function(m, func.attr)
                    if hit:
                        return [hit]
            if (
                isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls")
                and info is not None
                and info.cls is not None
            ):
                own = graph.methods_of.get((src.rel, info.cls, func.attr))
                if own:
                    return [own]
            return []
        if isinstance(func, ast.Call):
            rd = resolve_dotted(func.func, self._aliases[src.rel])
            if rd in ("functools.partial", "partial") and func.args:
                return self._taint_callees(func.args[0], src, info)
        return []

    def _expr(
        self,
        node: ast.AST,
        taints: Dict[str, Taint],
        src: SourceFile,
        info: Optional[FunctionInfo],
    ) -> Taint:
        """Taint of evaluating ``node``: device origin + param flow."""
        aliases = self._aliases[src.rel]

        if isinstance(node, ast.Name):
            return taints.get(node.id, EMPTY)
        if isinstance(node, ast.Attribute) and node.attr in _HOST_ATTRS:
            return EMPTY  # metadata access: host-side even on device arrays
        if isinstance(node, ast.Call):
            rd = resolve_dotted(node.func, aliases)
            if rd is not None:
                if rd in _HOST_RESULTS or rd in _FETCHERS:
                    return EMPTY  # the result lives on the host
                if rd.split(".")[0] in _DEVICE_HEADS:
                    return _device(
                        ((src.path, node.lineno,
                          f"{rd}(...) creates a device value"),)
                    )
            callees = [
                c
                for c in self._taint_callees(node.func, src, info)
                if c in self.summaries
            ]
            if callees:
                out = EMPTY
                for ck in callees:
                    summ = self.summaries[ck].returns
                    callee = self.graph.functions[ck]
                    if summ.device:
                        out = out | _device(
                            summ.chain + (
                                (src.path, node.lineno,
                                 f"device value returned by {callee.name}()"),
                            )
                        )
                    pnames = param_names(callee.node)
                    skip = (
                        1 if callee.cls and pnames
                        and pnames[0] in ("self", "cls") else 0
                    )
                    for idx in summ.params:
                        a = idx - skip
                        if 0 <= a < len(node.args):
                            t = self._expr(node.args[a], taints, src, info)
                            if t.device:
                                out = out | _device(
                                    t.chain + (
                                        (src.path, node.lineno,
                                         f"flows through {callee.name}() "
                                         "back to the caller"),
                                    )
                                )
                            out = out | Taint(params=t.params)
                # a resolved project call: the summary IS the answer
                return out
        # default: union over child expressions (method calls on tainted
        # objects, binops, subscripts, f-strings ... all propagate)
        out = EMPTY
        for child in ast.iter_child_nodes(node):
            out = out | self._expr(child, taints, src, info)
        return out


# --- shared AST plumbing ----------------------------------------------------

def flatten_body(body: List[ast.stmt]) -> List[ast.stmt]:
    """Every statement in ``body`` transitively, EXCLUDING nested defs
    (separate call-graph nodes with closure-inherited envs). Class bodies
    stay in the enclosing scope, as in v1."""
    flat: List[ast.stmt] = []

    def go(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            flat.append(st)
            for field in ("body", "orelse", "finalbody"):
                go(getattr(st, field, []) or [])
            for h in getattr(st, "handlers", []) or []:
                go(h.body)
            for case in getattr(st, "cases", []) or []:
                go(case.body)

    go(body)
    return flat


def _target_names(t: ast.AST) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in t.elts:
            out.extend(_target_names(el))
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return []


def _assigned_names(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for st in flatten_body(fn.body):
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = st.targets if isinstance(st, ast.Assign) else [st.target]
            for t in targets:
                out.update(_target_names(t))
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            out.update(_target_names(st.target))
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                if item.optional_vars is not None:
                    out.update(_target_names(item.optional_vars))
    return out


def format_chain(chain: Tuple[Step, ...]) -> List[str]:
    return [f"{path}:{line}: {desc}" for path, line, desc in chain]
