"""CLI: ``python -m video_features_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/parse error. Findings print as
``file:line:col: GC### rule-name: message`` plus a fix hint — the format
scripts/check.sh and CI grep. ``--json`` emits a machine-readable list.

No jax import, no package import side effects beyond the analysis
subpackage itself: the suite parses source, it never executes it (the
GC401 runtime budget runs under pytest, not here — see
``pytest -m analysis``).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m video_features_tpu.analysis",
        description="graftcheck: JAX/TPU static-analysis suite "
        "(host-sync, jit-hygiene, thread-safety lints)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to check (default: the installed package)",
    )
    parser.add_argument(
        "--rule", action="append", default=None, metavar="TOKEN",
        help="only report rules matching TOKEN (id like GC301, or a "
        "name prefix like host-sync); repeatable",
    )
    parser.add_argument("--json", action="store_true", help="JSON findings")
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    args = parser.parse_args(argv)

    from video_features_tpu.analysis.core import all_rules, run_checks

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:<20} {rule.summary}")
        return 0

    try:
        findings = run_checks(args.paths or None, rules=args.rule)
    except (OSError, SyntaxError) as e:
        print(f"graftcheck: cannot analyze: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        print(
            f"graftcheck: {n} finding(s)"
            if n
            else "graftcheck: clean (waivers audited via `git grep graftcheck:`)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
