"""CLI: ``python -m video_features_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/parse error. Findings print as
``file:line:col: GC### rule-name: message`` plus a fix hint — the format
scripts/check.sh and CI grep. ``--json`` emits a machine-readable list
(schema: ``analysis/findings_schema.json``).

Modes beyond the sweep:

- ``--rule GC301,host-sync`` — filter by rule id / name prefix; both the
  repeatable flag and comma-separated lists work.
- ``--diff BASE`` — only report findings on lines changed vs the git ref
  (``--diff origin/main`` is the incremental CI mode).
- ``--explain GC10x[:pathsub]`` — print matching findings WITH their
  interprocedural propagation chain (device-taint path, thread
  reachability), one ``via:`` line per hop.
- ``--update-budgets [--scenario NAME]`` — re-measure the GC401 compile
  budgets by running the registered extraction scenarios and rewrite
  ``compile_budget.json``. This mode executes code (imports jax); the
  lint modes never do.

No jax import in the lint modes, no package import side effects beyond
the analysis subpackage itself: the suite parses source, it never
executes it (the GC401 runtime budget runs under pytest, not here — see
``pytest -m analysis``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Set, Tuple


def _split_rule_tokens(raw: Optional[List[str]]) -> Optional[List[str]]:
    if not raw:
        return None
    out: List[str] = []
    for item in raw:
        out.extend(t.strip() for t in item.split(",") if t.strip())
    return out or None


def _changed_lines(base: str) -> Optional[Dict[str, Set[int]]]:
    """abs path -> set of (new-side) line numbers changed vs ``base``,
    parsed from ``git diff --unified=0``. None on git failure."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--unified=0", base, "--", "*.py"],
            capture_output=True, text=True, check=True, cwd=top,
        ).stdout
    except (subprocess.CalledProcessError, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        print(f"graftcheck: --diff {base} failed: {detail.strip()}",
              file=sys.stderr)
        return None
    changed: Dict[str, Set[int]] = {}
    current: Optional[str] = None
    for line in diff.splitlines():
        if line.startswith("+++ "):
            name = line[4:].strip()
            if name == "/dev/null":
                current = None
            else:
                current = os.path.abspath(
                    os.path.join(top, name[2:] if name.startswith("b/") else name)
                )
        elif line.startswith("@@") and current is not None:
            # @@ -l,c +start[,count] @@
            try:
                new = line.split("+", 1)[1].split(" ", 1)[0]
                start, _, count = new.partition(",")
                first = int(start)
                n = int(count) if count else 1
            except (IndexError, ValueError):
                continue
            if n > 0:
                changed.setdefault(current, set()).update(
                    range(first, first + n)
                )
    return changed


def _parse_explain(spec: str) -> Tuple[str, Optional[str]]:
    rule, _, pathsub = spec.partition(":")
    return rule.strip(), (pathsub.strip() or None)


def _repo_relative(path: str) -> str:
    """SARIF artifact URIs are repo-relative so GitHub code scanning can
    anchor annotations; fall back to the cwd when not in a git checkout."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
    except (subprocess.CalledProcessError, OSError):
        top = os.getcwd()
    rel = os.path.relpath(os.path.abspath(path), top)
    return rel.replace(os.sep, "/")


def _sarif(findings, rules) -> Dict[str, object]:
    """SARIF 2.1.0 log: one run, the full rule catalogue in the driver
    (so suppressed-to-zero runs still upload a valid ruleset), findings
    as level=error results with the fix hint folded into the message."""
    results = []
    for f in findings:
        message = f.message if not f.hint else f"{f.message} (fix: {f.hint})"
        results.append(
            {
                "ruleId": f.rule.id,
                "level": "error",
                "message": {"text": message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": _repo_relative(f.path),
                                "uriBaseId": "%SRCROOT%",
                            },
                            "region": {
                                "startLine": f.line,
                                # SARIF columns are 1-based; Finding.col
                                # is the 0-based AST col_offset
                                "startColumn": f.col + 1,
                            },
                        }
                    }
                ],
            }
        )
    return {
        "version": "2.1.0",
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftcheck",
                        "informationUri": (
                            "https://github.com/video-features-tpu/"
                            "video-features-tpu/blob/main/docs/analysis.md"
                        ),
                        "rules": [
                            {
                                "id": r.id,
                                "name": r.name,
                                "shortDescription": {"text": r.summary},
                            }
                            for r in rules
                        ],
                    }
                },
                "results": results,
            }
        ],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m video_features_tpu.analysis",
        description="graftcheck: JAX/TPU static-analysis suite "
        "(host-sync, jit-hygiene, thread-safety, sharding-contract lints)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to check (default: the installed package)",
    )
    parser.add_argument(
        "--rule", action="append", default=None, metavar="TOKEN[,TOKEN...]",
        help="only report rules matching TOKEN (id like GC301, or a "
        "name prefix like host-sync); repeatable and comma-separable",
    )
    parser.add_argument(
        "--diff", default=None, metavar="BASE",
        help="only report findings on lines changed vs the git ref BASE "
        "(e.g. --diff origin/main for incremental CI)",
    )
    parser.add_argument(
        "--explain", default=None, metavar="RULE[:PATHSUB]",
        help="print matching findings with their propagation chain "
        "(e.g. --explain GC102:extract_clip)",
    )
    parser.add_argument("--json", action="store_true", help="JSON findings")
    parser.add_argument(
        "--sarif", action="store_true",
        help="SARIF 2.1.0 findings (GitHub code-scanning upload format)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    parser.add_argument(
        "--update-budgets", action="store_true",
        help="re-measure GC401 compile budgets by running the registered "
        "scenarios and rewrite compile_budget.json (executes code!)",
    )
    parser.add_argument(
        "--scenario", action="append", default=None, metavar="NAME",
        help="with --update-budgets: only these scenarios (repeatable)",
    )
    args = parser.parse_args(argv)

    from video_features_tpu.analysis.core import all_rules, run_checks

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:<24} {rule.summary}")
        return 0

    if args.update_budgets:
        from video_features_tpu.analysis.budget_scenarios import update_budgets

        try:
            return update_budgets(args.scenario)
        except Exception as e:  # noqa: BLE001 - surface scenario failures as exit 2
            print(f"graftcheck: --update-budgets failed: {e}", file=sys.stderr)
            return 2

    rule_tokens = _split_rule_tokens(args.rule)
    explain_rule: Optional[str] = None
    explain_path: Optional[str] = None
    if args.explain:
        explain_rule, explain_path = _parse_explain(args.explain)
        rule_tokens = (rule_tokens or []) + [explain_rule]

    try:
        findings = run_checks(args.paths or None, rules=rule_tokens)
    except (OSError, SyntaxError) as e:
        print(f"graftcheck: cannot analyze: {e}", file=sys.stderr)
        return 2

    if args.diff is not None:
        changed = _changed_lines(args.diff)
        if changed is None:
            return 2
        findings = [
            f for f in findings
            if f.line in changed.get(os.path.abspath(f.path), ())
        ]

    if args.explain:
        if explain_path:
            findings = [f for f in findings if explain_path in f.path]
        for f in findings:
            print(f.format_trace())
        print(
            f"graftcheck: {len(findings)} finding(s) for {args.explain}"
            if findings
            else f"graftcheck: nothing to explain for {args.explain}"
        )
        return 1 if findings else 0

    if args.sarif:
        print(json.dumps(_sarif(findings, all_rules()), indent=2))
    elif args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        print(
            f"graftcheck: {n} finding(s)"
            if n
            else "graftcheck: clean (waivers audited via `git grep graftcheck:`)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
