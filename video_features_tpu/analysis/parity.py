"""Parity budgets — the committed (family, dtype) max-drift table.

``parity_budget.json`` is ``compile_budget.json``'s numerics twin: for
every low-precision (model family, dtype) pair that
``config.LOW_PRECISION_MODEL_FAMILIES`` admits, it commits a ceiling on
relative-L2 feature drift versus the fp32 graph. GC804
(analysis/numerics.py) cross-checks the two tables and requires an e2e
test to assert each pair through :func:`assert_drift_within` /
:func:`max_rel_drift` — so an admission with no committed bound, a
bound with no test, or an orphan budget entry all fail
``python -m video_features_tpu.analysis``.

The ``measured`` column is regenerated, never hand-edited:
``python -m video_features_tpu.analysis --update-budgets --scenario
parity_<family>`` re-runs the family's drift scenarios (random init,
CPU, deterministic seeds — the same regime the tier-1 tests pin) and
rewrites ``measured`` in place. ``max_rel`` is the committed contract:
the writer only fills it when absent (1.5x headroom over measured);
raising an existing ceiling is a reviewed diff, exactly like GC401.

Budget document shape::

    {"_meta": {...},
     "<family>": {"<dtype>": {"<kind>": {"max_rel": 0.03,
                                         "measured": 0.0104}}}}

``kind`` names the measurement surface: ``model`` (one forward pass at
full channel width), ``e2e`` (the extractor pipeline end to end),
``e2e_flow`` (the I3D flow stream with RAFT in the loop).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile
from typing import Callable, Dict, Optional, Sequence

PARITY_BUDGET_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "parity_budget.json"
)

# headroom multiplier used ONLY when --update-budgets fills a ceiling
# that was never committed; existing max_rel values are never touched
_FILL_HEADROOM = 1.5


def load_parity_budget(path: Optional[str] = None) -> Dict:
    with open(path or PARITY_BUDGET_PATH, "r", encoding="utf-8") as fh:
        return json.load(fh)


def max_rel_drift(
    family: str, dtype: str, kind: str, path: Optional[str] = None
) -> float:
    """The committed drift ceiling, or a KeyError that tells you how to
    commit one (the GC804 contract: no budget, no admission)."""
    doc = load_parity_budget(path)
    try:
        spec = doc[family][dtype][kind]
        return float(spec["max_rel"])
    except (KeyError, TypeError):
        raise KeyError(
            f"no parity budget for ({family!r}, {dtype!r}, {kind!r}) in "
            f"{PARITY_BUDGET_PATH}: commit a max_rel ceiling (regenerate "
            f"measured drift with --update-budgets --scenario "
            f"parity_{family})"
        ) from None


def rel_drift(low, ref) -> float:
    """Relative L2: ||low - ref|| / ||ref||, in float64."""
    import numpy as np

    low = np.asarray(low, np.float64)
    ref = np.asarray(ref, np.float64)
    return float(np.linalg.norm(low - ref) / max(np.linalg.norm(ref), 1e-12))


def assert_drift_within(
    family: str,
    dtype: str,
    kind: str,
    low,
    ref,
    path: Optional[str] = None,
) -> float:
    """Assert ``rel_drift(low, ref)`` stays under the committed ceiling;
    returns the measured drift so tests can also pin a nonzero floor
    (identical outputs would mean the low-precision graph never ran)."""
    ceiling = max_rel_drift(family, dtype, kind, path=path)
    measured = rel_drift(low, ref)
    assert measured <= ceiling, (
        f"({family}, {dtype}, {kind}) drift {measured:.5f} exceeds the "
        f"committed parity budget {ceiling} — if the numerics change is "
        f"intentional, regenerate with --update-budgets --scenario "
        f"parity_{family} and commit the new ceiling"
    )
    return measured


# --- measurement scenarios ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ParityScenario:
    """One family's drift measurements: runner returns {kind: rel_drift}."""

    family: str
    dtype: str
    description: str
    runner: Callable[[str], Dict[str, float]]  # tmp dir -> measured drift


def _model_drift_clip() -> float:
    import numpy as np
    import jax.numpy as jnp

    from video_features_tpu.models.clip.model import (
        CLIP_VIT_B32,
        VisionTransformer,
        init_params,
    )
    from video_features_tpu.models.common.weights import (
        cast_floats_for_compute,
    )

    params = init_params(CLIP_VIT_B32)
    x = jnp.asarray(
        np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32)
    )
    ref = VisionTransformer(CLIP_VIT_B32).apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("proj",))
    out = VisionTransformer(CLIP_VIT_B32, dtype=jnp.bfloat16).apply(
        {"params": p16}, x
    )
    return rel_drift(out, ref)


def _model_drift_resnet() -> float:
    import numpy as np
    import jax.numpy as jnp

    from video_features_tpu.models.common.weights import (
        cast_floats_for_compute,
    )
    from video_features_tpu.models.resnet.model import build, init_params

    params = init_params("resnet50")
    x = jnp.asarray(
        np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32)
    )
    ref, _ = build("resnet50").apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("fc",))
    out, _ = build("resnet50", dtype=jnp.bfloat16).apply({"params": p16}, x)
    return rel_drift(out, ref)


def _model_drift_r21d() -> float:
    import numpy as np
    import jax.numpy as jnp

    from video_features_tpu.models.common.weights import (
        cast_floats_for_compute,
    )
    from video_features_tpu.models.r21d.model import build, init_params

    params = init_params()
    x = jnp.asarray(
        np.random.RandomState(0).randn(1, 8, 112, 112, 3).astype(np.float32)
    )
    ref, _ = build().apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("fc",))
    out, _ = build(dtype=jnp.bfloat16).apply({"params": p16}, x)
    return rel_drift(out, ref)


def _model_drift_i3d() -> float:
    import numpy as np
    import jax.numpy as jnp

    from video_features_tpu.models.common.weights import (
        cast_floats_for_compute,
    )
    from video_features_tpu.models.i3d.model import build, init_params

    params = init_params("rgb")
    x = jnp.asarray(
        np.random.RandomState(0)
        .uniform(-1, 1, (1, 16, 224, 224, 3))
        .astype(np.float32)
    )
    ref, _ = build().apply({"params": params}, x)
    p16 = cast_floats_for_compute(
        params, jnp.bfloat16, exclude=("conv3d_0c_1x1",)
    )
    out, _ = build(dtype=jnp.bfloat16).apply({"params": p16}, x)
    return rel_drift(out, ref)


def _flow_frames():
    """The tests' coherent-motion pair: frame 2 is frame 1 shifted
    (3, 2) px, 128x128, grayscale replicated to RGB."""
    import numpy as np
    import jax.numpy as jnp

    H = W = 128
    rng = np.random.RandomState(0)
    base = rng.uniform(0, 255, size=(H + 8, W + 8)).astype(np.float32)
    f1 = base[4 : 4 + H, 4 : 4 + W]
    f2 = base[1 : 1 + H, 2 : 2 + W]
    return jnp.asarray(
        np.stack([np.stack([f1] * 3, -1), np.stack([f2] * 3, -1)])
    )


def _model_drift_flow(ft: str) -> float:
    import numpy as np
    import jax.numpy as jnp

    if ft == "raft":
        from video_features_tpu.models.raft.model import build, init_params
    else:
        from video_features_tpu.models.pwc.model import build, init_params

    frames = _flow_frames()
    params = init_params()
    f32 = np.asarray(build(dtype=jnp.float32).apply({"params": params}, frames))
    f16 = np.asarray(
        build(dtype=jnp.bfloat16).apply({"params": params}, frames)
    )
    return rel_drift(f16, f32)


def _e2e_features(tmp: str, ft: str, dtype: str, **overrides):
    from video_features_tpu.config import ExtractionConfig, sanity_check
    from video_features_tpu.extract.registry import build_extractor

    cfg = sanity_check(
        ExtractionConfig(
            allow_random_init=True,
            feature_type=ft,
            dtype=dtype,
            tmp_path=os.path.join(tmp, f"tmp_{dtype}"),
            output_path=os.path.join(tmp, f"out_{dtype}"),
            cpu=True,
            **overrides,
        )
    )
    ex = build_extractor(cfg, external_call=True)
    ex.progress.disable = True
    return ex([0])[0]


def _e2e_drift_clip(tmp: str) -> float:
    from video_features_tpu.utils.synth import synth_video

    video = synth_video(os.path.join(tmp, "clip.mp4"), n_frames=24,
                        width=320, height=240, seed=0)
    kw = dict(
        video_paths=[video], extract_method="uni_4", preprocess="device"
    )
    f32 = _e2e_features(tmp, "CLIP-ViT-B/32", "float32", **kw)
    bf16 = _e2e_features(tmp, "CLIP-ViT-B/32", "bfloat16", **kw)
    return rel_drift(bf16["CLIP-ViT-B/32"], f32["CLIP-ViT-B/32"])


def _e2e_drift_flow(tmp: str, ft: str) -> float:
    from video_features_tpu.utils.synth import synth_video

    video = synth_video(os.path.join(tmp, f"{ft}.mp4"), n_frames=8,
                        width=100, height=96, seed=3)
    kw = dict(video_paths=[video], batch_size=4, preprocess="device")
    f32 = _e2e_features(tmp, ft, "float32", **kw)
    bf16 = _e2e_features(tmp, ft, "bfloat16", **kw)
    return rel_drift(bf16[ft], f32[ft])


def _e2e_drift_i3d_flow(tmp: str) -> float:
    from video_features_tpu.utils.synth import synth_video

    video = synth_video(os.path.join(tmp, "i3d.mp4"))  # 60f 320x240
    kw = dict(
        video_paths=[video],
        streams=["flow"],
        flow_type="raft",
        extraction_fps=5.0,
        stack_size=10,
        step_size=10,
    )
    f32 = _e2e_features(tmp, "i3d", "float32", **kw)
    bf16 = _e2e_features(tmp, "i3d", "bfloat16", **kw)
    return rel_drift(bf16["flow"], f32["flow"])


PARITY_SCENARIOS: Dict[str, ParityScenario] = {
    "parity_clip": ParityScenario(
        family="clip", dtype="bfloat16",
        description=(
            "CLIP ViT-B/32 bf16 vs f32: one full-width forward (model) + "
            "the uni_4 device-preprocess extraction (e2e), random init."
        ),
        runner=lambda tmp: {
            "model": _model_drift_clip(),
            "e2e": _e2e_drift_clip(tmp),
        },
    ),
    "parity_resnet": ParityScenario(
        family="resnet", dtype="bfloat16",
        description="ResNet-50 bf16 vs f32 full-width forward, random init.",
        runner=lambda tmp: {"model": _model_drift_resnet()},
    ),
    "parity_r21d": ParityScenario(
        family="r21d", dtype="bfloat16",
        description="R(2+1)D bf16 vs f32 full-width forward, random init.",
        runner=lambda tmp: {"model": _model_drift_r21d()},
    ),
    "parity_i3d": ParityScenario(
        family="i3d", dtype="bfloat16",
        description=(
            "I3D bf16 vs f32: RGB forward (model) + the RAFT flow-stream "
            "extraction with both nets bf16 (e2e_flow), random init."
        ),
        runner=lambda tmp: {
            "model": _model_drift_i3d(),
            "e2e_flow": _e2e_drift_i3d_flow(tmp),
        },
    ),
    "parity_raft": ParityScenario(
        family="raft", dtype="bfloat16",
        description=(
            "RAFT bf16 vs f32: coherent-motion forward at 128x128 (model) "
            "+ the standalone flow extraction on the tiny corpus (e2e)."
        ),
        runner=lambda tmp: {
            "model": _model_drift_flow("raft"),
            "e2e": _e2e_drift_flow(tmp, "raft"),
        },
    ),
    "parity_pwc": ParityScenario(
        family="pwc", dtype="bfloat16",
        description=(
            "PWC-Net bf16 vs f32: coherent-motion forward at 128x128 "
            "(model) + the standalone flow extraction (e2e)."
        ),
        runner=lambda tmp: {
            "model": _model_drift_flow("pwc"),
            "e2e": _e2e_drift_flow(tmp, "pwc"),
        },
    ),
}


def measure_parity(name: str) -> Dict[str, float]:
    sc = PARITY_SCENARIOS[name]
    with tempfile.TemporaryDirectory(prefix=f"graftcheck_{name}_") as tmp:
        return {k: float(v) for k, v in sc.runner(tmp).items()}


def update_parity_budgets(names: Optional[Sequence[str]] = None) -> int:
    """Re-measure drift and rewrite the ``measured`` column of
    ``parity_budget.json``. Committed ``max_rel`` ceilings are preserved;
    a ceiling is only filled in (with ``_FILL_HEADROOM`` headroom) when
    the entry never had one. Returns a process exit code."""
    chosen = list(names) if names else sorted(PARITY_SCENARIOS)
    unknown = [n for n in chosen if n not in PARITY_SCENARIOS]
    if unknown:
        print(
            f"graftcheck: unknown parity scenario(s): {', '.join(unknown)} "
            f"(have: {', '.join(sorted(PARITY_SCENARIOS))})",
            file=sys.stderr,
        )
        return 2
    try:
        doc = load_parity_budget()
    except OSError:
        doc = {}
    for name in chosen:
        sc = PARITY_SCENARIOS[name]
        drifts = measure_parity(name)
        slot = doc.setdefault(sc.family, {}).setdefault(sc.dtype, {})
        for kind, measured in sorted(drifts.items()):
            entry = slot.setdefault(kind, {})
            entry["measured"] = round(measured, 6)
            if "max_rel" not in entry:
                entry["max_rel"] = round(measured * _FILL_HEADROOM, 4)
        pretty = ", ".join(f"{k}={v:.5f}" for k, v in sorted(drifts.items()))
        print(f"graftcheck: {name}: {pretty}")
    with open(PARITY_BUDGET_PATH, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"graftcheck: wrote {PARITY_BUDGET_PATH}")
    return 0
