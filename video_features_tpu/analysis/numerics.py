"""GC80x — numerics & dtype-flow contracts for the low-precision path.

The ROADMAP's remaining "saturate the chip" lever is dropping precision
in the FastCLIP spirit — and until now every piece of that story was
convention: the fp32 islands inside the bf16 model graphs (LayerNorm
statistics, softmax, GRU carries), the ``preferred_element_type`` pins
on the MXU matmuls, the uint8-to-the-wire H2D contract, the flash
kernel's fp32 VMEM accumulators. Nothing stopped a refactor from
silently dropping a pin; the drift only shows up as a slightly worse
feature vector, far from any assert. GC80x makes the numerics contract
machine-checked, riding the PR-5 call graph + taint fixpoint:

- **GC801 implicit-promotion** — float64 constructs (``np.float64``,
  ``astype(float)``, ``dtype="float64"``, f64-default numpy creators)
  inside jit-reachable code. f64 doubles HBM pressure and is
  unsupported on TPU without x64. Interprocedural: a helper whose
  *return value* carries an f64 construct is flagged at its jit-side
  caller, with the construct site in the ``via:`` trace.
- **GC802 accum-dtype** — matmul-family ops (dot/einsum/conv) and
  numerically-sensitive reductions (softmax, mean/var, exp, cumsum,
  norm) reachable under a *bf16-polymorphic entry* (a def with a
  ``dtype`` parameter, a method of a class with a ``dtype`` field, or a
  ``# graftcheck: bf16-entry`` declaration) must pin accumulation:
  ``preferred_element_type=jnp.float32`` / ``dtype=jnp.float32`` /
  ``precision=HIGHEST``, a visible ``.astype(jnp.float32)`` on an
  operand, or an explicit ``# graftcheck: fp32-island — <why>``
  declaration on the def or the line. Stripping a pin fails tier-1.
- **GC803 cast-discipline** — host-side ``astype(float32)`` on frame
  payloads in hot modules: a float32 frame ships 4x the bytes of the
  uint8 wire format PRs 1/14 standardized. Flagged with the device-side
  fix; host-only parity paths carry an ``fp32-island`` declaration.
- **GC804 parity-pin-coverage** — config.py's
  ``LOW_PRECISION_MODEL_FAMILIES`` admission table and the committed
  ``analysis/parity_budget.json`` max-drift table must cover each other
  exactly, and every admitted (family, dtype) pair must be asserted by
  an e2e parity test (``assert_drift_within``/``max_rel_drift`` in
  tests/). ``--update-budgets --scenario parity_<family>`` regenerates
  measured drift; ceilings are the committed contract.
- **GC805 pallas-hygiene** — over ``ops/pallas/`` (or files marked
  ``# graftcheck: pallas-kernel``): cross-grid-step accumulation must
  land in float32 VMEM scratch (staging tiles that are only read are
  exempt), kernel-body dots/reductions pin their accumulation dtype,
  ``//``-built grids need a divisibility guard (``cdiv`` grids need a
  pad or guard), and every kernel wrapper exposes ``interpret=`` and
  has an interpret-mode parity test under tests/.

Three declaration tokens ride the ``# graftcheck:`` comment syntax but
are NOT waivers — none of them prefix-matches a rule name, so the
zero-waiver policy is preserved; they are typed facts the checkers read:

- ``fp32-island — <why>`` (def or line): the values flowing through
  here are already fp32 by an upstream contract the AST cannot see
  (e.g. RAFT's GRU carry pins); the reason clause is mandatory prose.
- ``bf16-entry`` (def or file): this code runs under bf16 inputs even
  though no ``dtype`` parameter/field names it (e.g. the attention
  cores that receive whatever dtype the caller's activations carry) —
  it WIDENS GC802 coverage, never narrows it.
- ``pallas-kernel`` (file): opt a file into the GC805 sweep beyond the
  built-in ``ops/pallas/`` path (test-fixture contract).

Resolution is exact-only (taint.py semantics) for both the jit
reachability walk (GC801) and the bf16 entry closure (GC802); findings
carry the reachability chain in ``trace`` (``--explain GC80``).
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.callgraph import CallGraph, FunctionInfo
from video_features_tpu.analysis.concurrency import _exact_callees, _own_nodes
from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    import_aliases,
    is_jax_jit,
    jit_decoration,
    package_root,
    param_names,
    resolve_dotted,
)
from video_features_tpu.analysis.taint import ProjectTaint, _target_names

RULES = {
    "GC801": Rule(
        "GC801", "implicit-promotion",
        "float64 construct inside jit-reachable code promotes traced values",
    ),
    "GC802": Rule(
        "GC802", "accum-dtype",
        "matmul/reduction under a bf16-polymorphic entry lacks an fp32 "
        "accumulation pin",
    ),
    "GC803": Rule(
        "GC803", "cast-discipline",
        "host-side float32 cast on a frame payload quadruples H2D bytes",
    ),
    "GC804": Rule(
        "GC804", "parity-pin-coverage",
        "config-admitted (family, dtype) lacks a committed parity budget "
        "or its e2e assertion",
    ),
    "GC805": Rule(
        "GC805", "pallas-hygiene",
        "Pallas kernel accumulator/grid/parity-test hygiene violation",
    ),
}

ISLAND_TOKEN = "fp32-island"
BF16_ENTRY_TOKEN = "bf16-entry"
PALLAS_MARKER = "pallas-kernel"

_HINT_801 = (
    "stay in float32/bfloat16 (jnp.float32 literals, dtype=np.float32): "
    "f64 doubles HBM and needs x64 mode the TPU path never enables"
)
_HINT_802 = (
    "pin the accumulation: preferred_element_type=jnp.float32 / "
    "dtype=jnp.float32 / precision='highest', cast an operand "
    ".astype(jnp.float32), or declare `# graftcheck: fp32-island — <why>` "
    "when an upstream contract already keeps these values fp32"
)
_HINT_803 = (
    "ship uint8 to the wire and cast on device inside the jitted consumer "
    "(--preprocess device contract, docs/tpu.md 'Precision contract'); a "
    "host-only parity path declares `# graftcheck: fp32-island — <why>`"
)
_HINT_804 = (
    "commit the drift ceiling in analysis/parity_budget.json (regenerate "
    "measured drift via --update-budgets --scenario parity_<family>) and "
    "assert it end-to-end with analysis.parity.assert_drift_within in tests/"
)
_HINT_805 = (
    "accumulate in float32 VMEM scratch (store once at the end), pin kernel "
    "dots/reductions with preferred_element_type/dtype=jnp.float32, guard "
    "//-grids with a `% -> raise`, and keep an interpret=True parity test "
    "per kernel wrapper"
)


# --- shared dtype / token predicates ----------------------------------------

_F64_NAMES = frozenset(
    {
        "float",
        "builtins.float",
        "numpy.float64",
        "numpy.double",
        "numpy.float_",
        "jax.numpy.float64",
        "jax.numpy.double",
    }
)
_F64_DEFAULT_CREATORS = frozenset(
    {
        "numpy.zeros",
        "numpy.ones",
        "numpy.empty",
        "numpy.full",
        "numpy.linspace",
        "numpy.eye",
        "numpy.identity",
    }
)
_MATMUL = frozenset(
    {
        "jax.numpy.dot",
        "jax.numpy.vdot",
        "jax.numpy.inner",
        "jax.numpy.matmul",
        "jax.numpy.tensordot",
        "jax.numpy.einsum",
        "jax.lax.dot",
        "jax.lax.dot_general",
        "jax.lax.conv",
        "jax.lax.conv_general_dilated",
        "jax.experimental.pallas.dot",
    }
)
_SENSITIVE = frozenset(
    {
        "jax.nn.softmax",
        "jax.nn.log_softmax",
        "jax.nn.logsumexp",
        "jax.scipy.special.logsumexp",
        "jax.numpy.mean",
        "jax.numpy.var",
        "jax.numpy.std",
        "jax.numpy.cumsum",
        "jax.numpy.exp",
        "jax.numpy.linalg.norm",
    }
)
_SENSITIVE_METHODS = frozenset({"mean", "var", "std", "cumsum"})
_KERNEL_REDUCTIONS = frozenset(
    {"jax.numpy.sum", "jax.numpy.mean", "jax.numpy.cumsum", "jax.numpy.prod"}
)
_KERNEL_REDUCTION_METHODS = frozenset({"sum", "mean", "cumsum", "prod"})


def _is_f64_dtype(node: ast.AST, aliases: Dict[str, str]) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in ("float64", "double", "f8", "<f8", ">f8")
    rd = resolve_dotted(node, aliases)
    if rd in _F64_NAMES:
        return True
    if isinstance(node, ast.Call):
        rd = resolve_dotted(node.func, aliases)
        if rd in ("numpy.dtype", "jax.numpy.dtype") and node.args:
            return _is_f64_dtype(node.args[0], aliases)
    return False


def _is_f32_dtype(node: ast.AST, aliases: Dict[str, str]) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in ("float32", "f4", "<f4", ">f4")
    rd = resolve_dotted(node, aliases)
    return rd is not None and (rd == "float32" or rd.endswith(".float32"))


def _is_highest(
    node: ast.AST, aliases: Dict[str, str], highs: Set[str] = frozenset()
) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.lower() == "highest"
    if isinstance(node, (ast.Tuple, ast.List)):
        return bool(node.elts) and all(
            _is_highest(e, aliases, highs) for e in node.elts
        )
    if isinstance(node, ast.Name) and node.id in highs:
        return True
    rd = resolve_dotted(node, aliases)
    return rd is not None and rd.endswith("HIGHEST")


def _call_has_pin(
    call: ast.Call, aliases: Dict[str, str], highs: Set[str] = frozenset()
) -> bool:
    """An fp32 accumulation pin attached AT the call site."""
    for kw in call.keywords:
        if kw.arg in ("preferred_element_type", "dtype") and _is_f32_dtype(
            kw.value, aliases
        ):
            return True
        if kw.arg == "precision" and _is_highest(kw.value, aliases, highs):
            return True
    return False


def _highest_names(fn: ast.FunctionDef, aliases: Dict[str, str]) -> Set[str]:
    """Local names assigned from a HIGHEST precision value
    (``hp = jax.lax.Precision.HIGHEST``)."""
    out: Set[str] = set()
    for st in _own_nodes(fn):
        if isinstance(st, ast.Assign) and _is_highest(st.value, aliases):
            for tgt in st.targets:
                out.update(_target_names(tgt))
    return out


def _def_tokens(src: SourceFile, fn: ast.FunctionDef) -> Set[str]:
    """graftcheck tokens attached to a def: on the def/decorator lines or
    (via core's carry rule) a standalone comment directly above them."""
    lines = set(range(fn.lineno, fn.body[0].lineno))
    lines.add(fn.lineno)
    for dec in fn.decorator_list:
        lines.add(dec.lineno)
    out: Set[str] = set()
    for ln in lines:
        out |= src.waivers.get(ln, set())
    return out


def _islanded(src: SourceFile, info: Optional[FunctionInfo], line: int) -> bool:
    if ISLAND_TOKEN in src.waivers.get(line, ()):
        return True
    return info is not None and ISLAND_TOKEN in _def_tokens(src, info.node)


# --- call-graph plumbing ----------------------------------------------------

def _module_calls(src: SourceFile) -> List[ast.Call]:
    """Call nodes in the module body, pruning function bodies (those are
    covered per-FunctionInfo via ``_own_nodes``)."""
    out: List[ast.Call] = []
    stack: List[ast.AST] = [src.tree]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            out.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)
    return out


class _Ctx:
    """Per-sweep cache: exact call edges + per-function aliases."""

    def __init__(self, sources: Sequence[SourceFile], graph: CallGraph) -> None:
        self.sources = list(sources)
        self.graph = graph
        self.aliases = {s.rel: import_aliases(s.tree) for s in sources}
        # key -> [(Call node, [callee keys])] over _own_nodes, exact-only
        self.succs: Dict[str, List[Tuple[ast.Call, List[str]]]] = {}
        for key, info in graph.functions.items():
            edges: List[Tuple[ast.Call, List[str]]] = []
            for node in _own_nodes(info.node):
                if isinstance(node, ast.Call):
                    cks = _exact_callees(node.func, info.src, info, graph)
                    if cks:
                        edges.append((node, cks))
            self.succs[key] = edges

    def reach(self, roots: Sequence[str]) -> Dict[str, Tuple[str, ...]]:
        """key -> root-first chain of keys, closed over exact calls."""
        chains: Dict[str, Tuple[str, ...]] = {}
        frontier: List[str] = []
        for r in sorted(set(roots)):
            chains[r] = (r,)
            frontier.append(r)
        while frontier:
            nxt: List[str] = []
            for key in frontier:
                for _, cks in self.succs.get(key, ()):
                    for ck in cks:
                        if ck not in chains:
                            chains[ck] = chains[key] + (ck,)
                            nxt.append(ck)
            frontier = nxt
        return chains

    def chain_trace(self, chain: Tuple[str, ...], head: str) -> List[str]:
        steps: List[str] = []
        prev: Optional[FunctionInfo] = None
        for i, k in enumerate(chain):
            info = self.graph.functions[k]
            if i == 0:
                steps.append(
                    f"{info.src.path}:{info.node.lineno}: {head} {info.name!r}"
                )
            else:
                steps.append(
                    f"{info.src.path}:{info.node.lineno}: {info.name!r} "
                    f"reachable from {prev.name!r}"
                )
            prev = info
        return steps


# --- GC801 implicit promotion ----------------------------------------------

def _f64_sites(
    info: FunctionInfo, aliases: Dict[str, str]
) -> List[Tuple[ast.Call, str]]:
    out: List[Tuple[ast.Call, str]] = []
    for node in _own_nodes(info.node):
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
            and _is_f64_dtype(node.args[0], aliases)
        ):
            out.append((node, "astype(float64) widens the value"))
            continue
        rd = resolve_dotted(node.func, aliases)
        if rd in ("numpy.float64", "numpy.double", "jax.numpy.float64"):
            out.append((node, f"{rd}(...) builds a float64 scalar"))
            continue
        dtype_kw = next((kw for kw in node.keywords if kw.arg == "dtype"), None)
        if dtype_kw is not None:
            if _is_f64_dtype(dtype_kw.value, aliases):
                out.append((node, "dtype= selects float64"))
            continue
        if rd in _F64_DEFAULT_CREATORS:
            out.append((node, f"{rd}() defaults to float64 (no dtype=)"))
    return out


def _jit_roots(ctx: _Ctx) -> Set[str]:
    roots: Set[str] = set()
    graph = ctx.graph
    for key, info in graph.functions.items():
        if jit_decoration(info.node, ctx.aliases[info.src.rel]):
            roots.add(key)
    # jax.jit(fn) wrap sites, module-level and inside functions
    for src in ctx.sources:
        aliases = ctx.aliases[src.rel]

        def wrapped(call: ast.Call, caller: Optional[FunctionInfo]) -> None:
            if is_jax_jit(call.func, aliases) and call.args:
                keys, _ = graph.resolve_call(call.args[0], src, caller)
                roots.update(keys)

        for call in _module_calls(src):
            wrapped(call, None)
        for key, info in graph.functions.items():
            if info.src is not src:
                continue
            for node in _own_nodes(info.node):
                if isinstance(node, ast.Call):
                    wrapped(node, info)
    return roots


def _check_promotion(ctx: _Ctx) -> List[Finding]:
    graph = ctx.graph
    roots = _jit_roots(ctx)
    chains = ctx.reach(sorted(roots))
    # f64 constructs sitting in a function's RETURN path, for every
    # function in the project (the interprocedural leg needs them even
    # when the helper itself would not be swept)
    returning: Dict[str, List[Tuple[ast.Call, str]]] = {}
    for key, info in graph.functions.items():
        aliases = ctx.aliases[info.src.rel]
        in_return: Set[int] = set()
        for node in _own_nodes(info.node):
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    in_return.add(id(sub))
        hits = [
            (n, d) for n, d in _f64_sites(info, aliases) if id(n) in in_return
        ]
        if hits:
            returning[key] = hits

    out: List[Finding] = []
    seen: Set[Tuple[str, int, int, str]] = set()

    def emit(src, node, msg, trace):
        k = (src.path, node.lineno, node.col_offset, msg)
        if k in seen:
            return
        seen.add(k)
        out.append(
            Finding(src.path, node.lineno, node.col_offset, RULES["GC801"],
                    msg, _HINT_801, trace)
        )

    for key, chain in chains.items():
        info = graph.functions[key]
        src = info.src
        aliases = ctx.aliases[src.rel]
        ret_ids = {id(n) for n, _ in returning.get(key, ())}
        for node, desc in _f64_sites(info, aliases):
            if _islanded(src, info, node.lineno):
                continue
            if key not in roots and id(node) in ret_ids:
                # reported at the jit-side caller below, where the f64
                # value actually meets traced code
                continue
            emit(
                src, node,
                f"{desc} inside jit-reachable {info.name!r}",
                ctx.chain_trace(chain, "jitted entry"),
            )
        # interprocedural: calls whose exact callee RETURNS an f64 value
        for call, cks in ctx.succs.get(key, ()):
            for ck in cks:
                hits = returning.get(ck)
                if not hits or (ck in roots):
                    continue
                if _islanded(src, info, call.lineno):
                    continue
                callee = graph.functions[ck]
                for n, desc in hits:
                    emit(
                        src, call,
                        f"call to {callee.name!r} returns float64 into "
                        f"jit-reachable {info.name!r}",
                        [f"{callee.src.path}:{n.lineno}: {desc}"]
                        + ctx.chain_trace(chain, "jitted entry"),
                    )
    return out


# --- GC802 accumulation dtype ----------------------------------------------

def _dtype_field_classes(src: SourceFile) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for st in node.body:
            if (
                isinstance(st, ast.AnnAssign)
                and isinstance(st.target, ast.Name)
                and st.target.id == "dtype"
            ):
                out.add(node.name)
            elif isinstance(st, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "dtype" for t in st.targets
            ):
                out.add(node.name)
    return out


def _bf16_entries(ctx: _Ctx) -> Dict[str, str]:
    """entry key -> why it is bf16-polymorphic."""
    entries: Dict[str, str] = {}
    dtype_classes = {s.rel: _dtype_field_classes(s) for s in ctx.sources}
    for key, info in ctx.graph.functions.items():
        src = info.src
        if BF16_ENTRY_TOKEN in src.markers:
            entries[key] = "bf16-entry file marker"
            continue
        if BF16_ENTRY_TOKEN in _def_tokens(src, info.node):
            entries[key] = "bf16-entry declaration"
            continue
        if info.cls and info.cls in dtype_classes.get(src.rel, ()):
            entries[key] = f"method of dtype-polymorphic class {info.cls!r}"
            continue
        if "dtype" in param_names(info.node):
            entries[key] = "takes a dtype parameter"
    return entries


def _pinning_expr(
    node: ast.AST,
    aliases: Dict[str, str],
    pinned: Set[str],
    highs: Set[str] = frozenset(),
) -> bool:
    """Does evaluating ``node`` visibly produce an fp32 value?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in pinned:
            return True
        if isinstance(sub, ast.Attribute):
            rd = resolve_dotted(sub, aliases)
            if rd is not None and rd.endswith(".float32"):
                return True
        if isinstance(sub, ast.Call):
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "astype"
                and sub.args
                and _is_f32_dtype(sub.args[0], aliases)
            ):
                return True
            if _call_has_pin(sub, aliases, highs):
                return True
    return False


def _is_dtype_election(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """``.astype(self.dtype)`` / ``.astype(dtype)``: the expression casts
    to the entry's polymorphic dtype on purpose."""
    if not (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "astype"
        and node.args
    ):
        return False
    arg = node.args[0]
    if isinstance(arg, ast.Name) and arg.id == "dtype":
        return True
    return (
        isinstance(arg, ast.Attribute)
        and arg.attr == "dtype"
        and isinstance(arg.value, ast.Name)
        and arg.value.id == "self"
    )


def _electing_expr(
    node: ast.AST, aliases: Dict[str, str], elected: Set[str]
) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in elected:
            return True
        if _is_dtype_election(sub, aliases):
            return True
    return False


def _elected_names(fn: ast.FunctionDef, aliases: Dict[str, str]) -> Set[str]:
    """Local names visibly assigned from dtype-election expressions
    (``x = x.astype(self.dtype)``), propagated like ``_pinned_names``."""
    elected: Set[str] = set()
    stmts = [
        st
        for st in _own_nodes(fn)
        if isinstance(st, (ast.Assign, ast.AnnAssign)) and st.value is not None
    ]
    for _ in range(3):
        changed = False
        for st in stmts:
            if not _electing_expr(st.value, aliases, elected):
                continue
            targets = st.targets if isinstance(st, ast.Assign) else [st.target]
            for tgt in targets:
                for n in _target_names(tgt):
                    if n not in elected:
                        elected.add(n)
                        changed = True
        if not changed:
            break
    return elected


def _pinned_names(
    fn: ast.FunctionDef,
    aliases: Dict[str, str],
    seed: Optional[Set[str]] = None,
    highs: Set[str] = frozenset(),
) -> Set[str]:
    """Local names visibly assigned from fp32-pinned expressions,
    propagated through simple chains (3 passes)."""
    pinned: Set[str] = set(seed or ())
    stmts = [
        st
        for st in _own_nodes(fn)
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign))
    ]
    for _ in range(3):
        changed = False
        for st in stmts:
            if st.value is None:
                continue
            if not _pinning_expr(st.value, aliases, pinned, highs):
                continue
            targets = st.targets if isinstance(st, ast.Assign) else [st.target]
            for tgt in targets:
                for n in _target_names(tgt):
                    if n not in pinned:
                        pinned.add(n)
                        changed = True
        if not changed:
            break
    return pinned


def _operands(call: ast.Call, rd: Optional[str]) -> List[ast.AST]:
    args = list(call.args)
    if rd is not None and rd.endswith("einsum") and args:
        first = args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            args = args[1:]
    return args


def _check_accum(ctx: _Ctx) -> List[Finding]:
    graph = ctx.graph
    entries = _bf16_entries(ctx)
    chains = ctx.reach(sorted(entries))
    out: List[Finding] = []
    for key, chain in chains.items():
        info = graph.functions[key]
        src = info.src
        if src.rel.startswith("ops/pallas/") or PALLAS_MARKER in src.markers:
            continue  # GC805 owns kernel bodies
        aliases = ctx.aliases[src.rel]
        if ISLAND_TOKEN in _def_tokens(src, info.node):
            continue
        highs = _highest_names(info.node, aliases)
        pinned = _pinned_names(info.node, aliases, highs=highs)
        elected = _elected_names(info.node, aliases)
        entry = graph.functions[chain[0]]
        trace = ctx.chain_trace(chain, "bf16-polymorphic entry")

        def emit(node, what):
            out.append(
                Finding(
                    src.path, node.lineno, node.col_offset, RULES["GC802"],
                    f"{what} under bf16-polymorphic entry {entry.name!r} "
                    "without an fp32 accumulation pin",
                    _HINT_802, trace,
                )
            )

        for node in _own_nodes(info.node):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                if _islanded(src, None, node.lineno):
                    continue
                sides = (node.left, node.right)
                if any(_pinning_expr(s, aliases, pinned, highs) for s in sides):
                    continue
                if any(_electing_expr(s, aliases, elected) for s in sides):
                    continue  # operands cast to the entry dtype on purpose
                emit(node, "`@` matmul")
                continue
            if not isinstance(node, ast.Call):
                continue
            rd = resolve_dotted(node.func, aliases)
            kind: Optional[str] = None
            is_matmul = False
            operands: List[ast.AST] = []
            if rd in _MATMUL:
                kind = rd.rsplit(".", 1)[-1]
                is_matmul = True
                operands = _operands(node, rd)
            elif rd in _SENSITIVE:
                kind = rd.rsplit(".", 1)[-1]
                operands = list(node.args)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SENSITIVE_METHODS
                and isinstance(node.func.value, ast.Name)
            ):
                kind = f".{node.func.attr}()"
                operands = [node.func.value]
            if kind is None:
                continue
            if _islanded(src, None, node.lineno):
                continue
            if _call_has_pin(node, aliases, highs):
                continue
            if any(_pinning_expr(a, aliases, pinned, highs) for a in operands):
                continue
            if is_matmul and any(
                _electing_expr(a, aliases, elected) for a in operands
            ):
                # a matmul whose operands are deliberately cast to the
                # entry's polymorphic dtype made its precision choice
                # visibly (the MXU still accumulates f32 internally);
                # sensitive reductions get no such pass.
                continue
            emit(node, kind)
    return out


# --- GC803 cast discipline --------------------------------------------------

_CAST_SCOPE_PATTERNS = ("models/*/extract_*.py",)
_FRAME_PIECES = frozenset(
    {
        "frame", "frames", "clip", "clips", "img", "imgs", "image", "images",
        "video", "videos", "rgb", "flow", "pair", "pairs", "pixels", "stack",
        "stacks", "crop", "crops",
    }
)
_NP_WRAPPERS = frozenset(
    {
        "numpy.asarray", "numpy.array", "numpy.stack", "numpy.concatenate",
        "numpy.ascontiguousarray",
    }
)


def _frameish(name: str) -> bool:
    return any(p in _FRAME_PIECES for p in name.lower().split("_"))


def _is_host_f32(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """float32 spelled the *host* way: ``np.float32`` or a string.
    ``jnp.float32`` implies the cast targets a device value (e.g. the
    RAFT corr-pyramid pins) and is GC802's business, not GC803's."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in ("float32", "f4", "<f4", ">f4")
    rd = resolve_dotted(node, aliases)
    return rd in ("numpy.float32", "numpy.single", "float32")


def _frameish_locals(fn: ast.FunctionDef) -> Set[str]:
    local: Set[str] = {p for p in param_names(fn) if _frameish(p)}

    def mentions(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                _frameish(sub.id) or sub.id in local
            ):
                return True
            if isinstance(sub, ast.Attribute) and _frameish(sub.attr):
                return True
        return False

    for _ in range(2):
        changed = False
        for node in _own_nodes(fn):
            targets: List[ast.AST] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if mentions(node.iter):
                    targets = [node.target]
            elif isinstance(node, ast.comprehension):
                if mentions(node.iter):
                    targets = [node.target]
            elif isinstance(node, ast.Assign):
                if node.value is not None and mentions(node.value):
                    targets = list(node.targets)
            for tgt in targets:
                for n in _target_names(tgt):
                    if n not in local:
                        local.add(n)
                        changed = True
        if not changed:
            break
    return local


def _check_cast_discipline(
    ctx: _Ctx, project: ProjectTaint, jit_reach: Set[str]
) -> List[Finding]:
    out: List[Finding] = []
    for src in ctx.sources:
        in_scope = src.is_hot or any(
            fnmatch.fnmatch(src.rel, p) for p in _CAST_SCOPE_PATTERNS
        )
        if not in_scope:
            continue
        aliases = ctx.aliases[src.rel]
        for key, info in ctx.graph.functions.items():
            if info.src is not src or key in jit_reach:
                continue
            if ISLAND_TOKEN in _def_tokens(src, info.node):
                continue
            frameish = _frameish_locals(info.node)
            env = project.env_for(key)

            def is_frame_expr(node: ast.AST) -> bool:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and (
                        _frameish(sub.id) or sub.id in frameish
                    ):
                        return True
                    if isinstance(sub, ast.Attribute) and _frameish(sub.attr):
                        return True
                return False

            for node in _own_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                recv: Optional[ast.AST] = None
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and node.args
                    and _is_host_f32(node.args[0], aliases)
                ):
                    recv = node.func.value
                else:
                    rd = resolve_dotted(node.func, aliases)
                    if rd in _NP_WRAPPERS and node.args:
                        dt = next(
                            (kw.value for kw in node.keywords if kw.arg == "dtype"),
                            node.args[1] if len(node.args) > 1 else None,
                        )
                        if dt is not None and _is_host_f32(dt, aliases):
                            recv = node.args[0]
                if recv is None or not is_frame_expr(recv):
                    continue
                if _islanded(src, None, node.lineno):
                    continue
                if project.expr_taint(recv, env, src, info).device:
                    continue  # device value: the cast runs on-chip, not host
                out.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC803"],
                        "host-side float32 cast on a frame payload in "
                        f"{info.name!r}: 4x the uint8 wire bytes over H2D",
                        _HINT_803,
                    )
                )
    return out


# --- GC804 parity-pin coverage ----------------------------------------------

PARITY_BUDGET_BASENAME = "parity_budget.json"
ADMISSION_TABLE_NAME = "LOW_PRECISION_MODEL_FAMILIES"
_PARITY_ASSERT_TOKENS = ("assert_drift_within", "max_rel_drift")


def _parse_admissions(st: ast.Assign) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    if not isinstance(st.value, ast.Dict):
        return out
    for k, v in zip(st.value.keys, st.value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        fams: List[str] = []
        if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            for el in v.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    fams.append(el.value)
        out[k.value] = fams
    return out


def _tests_dirs(anchor: str) -> List[str]:
    cands = [
        os.path.join(anchor, "tests"),
        os.path.normpath(os.path.join(anchor, "..", "tests")),
        os.path.normpath(os.path.join(package_root(), "..", "tests")),
    ]
    # nearest existing dir only: a project that carries its own tests/
    # next to the analyzed file is judged by those tests, not by whatever
    # this package's suite happens to mention
    for c in cands:
        if os.path.isdir(c):
            return [c]
    return []


_TESTS_TEXT_CACHE: Dict[str, List[str]] = {}
_TESTS_TEXT_LOCK = threading.Lock()


def _tests_texts(dirs: Sequence[str]) -> List[str]:
    texts: List[str] = []
    with _TESTS_TEXT_LOCK:
        for d in dirs:
            if d not in _TESTS_TEXT_CACHE:
                blobs: List[str] = []
                try:
                    names = sorted(os.listdir(d))
                except OSError:
                    names = []
                for fn in names:
                    if not fn.endswith(".py"):
                        continue
                    try:
                        with open(
                            os.path.join(d, fn), "r", encoding="utf-8"
                        ) as fh:
                            blobs.append(fh.read())
                    except OSError:
                        continue
                _TESTS_TEXT_CACHE[d] = blobs
            texts.extend(_TESTS_TEXT_CACHE[d])
    return texts


def _check_parity_coverage(sources: Sequence[SourceFile]) -> List[Finding]:
    cfg = next((s for s in sources if s.rel == "config.py"), None)
    if cfg is None:
        return []
    table: Optional[ast.Assign] = None
    admitted: Dict[str, List[str]] = {}
    for st in cfg.tree.body:
        if isinstance(st, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == ADMISSION_TABLE_NAME
            for t in st.targets
        ):
            table = st
            admitted = _parse_admissions(st)
    out: List[Finding] = []

    def emit(line: int, msg: str) -> None:
        out.append(Finding(cfg.path, line, 0, RULES["GC804"], msg, _HINT_804))

    if table is None:
        # only meaningful for a config that really carries the dtype
        # axis (the fixture configs for other families do not)
        if "--dtype" in cfg.text:
            emit(
                1,
                f"config.py admits --dtype values but declares no "
                f"{ADMISSION_TABLE_NAME} table for GC804 to check",
            )
        return out

    budget_path = os.path.join(
        os.path.dirname(cfg.path), "analysis", PARITY_BUDGET_BASENAME
    )
    if not os.path.isfile(budget_path):
        emit(
            table.lineno,
            f"{ADMISSION_TABLE_NAME} admits low-precision dtypes but no "
            f"analysis/{PARITY_BUDGET_BASENAME} is committed",
        )
        return out
    try:
        with open(budget_path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        emit(table.lineno, f"unreadable {PARITY_BUDGET_BASENAME}: {e}")
        return out
    families = {
        k: v for k, v in doc.items() if not k.startswith("_") and isinstance(v, dict)
    }

    tests = _tests_texts(_tests_dirs(os.path.dirname(cfg.path)))
    for dtype, fams in admitted.items():
        for fam in fams:
            entry = families.get(fam, {}).get(dtype)
            kinds = entry if isinstance(entry, dict) else {}
            bounded = any(
                isinstance(spec, dict)
                and isinstance(spec.get("max_rel"), (int, float))
                for spec in kinds.values()
            )
            if not bounded:
                emit(
                    table.lineno,
                    f"admitted ({fam!r}, {dtype!r}) has no max_rel drift "
                    f"budget in {PARITY_BUDGET_BASENAME}",
                )
                continue
            asserted = any(
                any(tok in txt for tok in _PARITY_ASSERT_TOKENS)
                and (f'"{fam}"' in txt or f"'{fam}'" in txt)
                and (f'"{dtype}"' in txt or f"'{dtype}'" in txt)
                for txt in tests
            )
            if not asserted:
                emit(
                    table.lineno,
                    f"admitted ({fam!r}, {dtype!r}) has a parity budget but "
                    "no e2e test asserts it "
                    f"({'/'.join(_PARITY_ASSERT_TOKENS)} in tests/)",
                )
    for fam, dmap in families.items():
        for dtype in dmap:
            if fam not in admitted.get(dtype, ()):
                emit(
                    table.lineno,
                    f"orphan parity budget ({fam!r}, {dtype!r}): "
                    f"{ADMISSION_TABLE_NAME} no longer admits it",
                )
    return out


# --- GC805 pallas hygiene ---------------------------------------------------

def _pallas_scope(src: SourceFile) -> bool:
    return (
        src.rel.startswith("ops/pallas/") and not src.rel.endswith("__init__.py")
    ) or PALLAS_MARKER in src.markers


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _seq_elts(node: Optional[ast.AST]) -> List[ast.AST]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return list(node.elts)
    return [] if node is None else [node]


def _scratch_dtype(node: ast.AST, aliases: Dict[str, str]) -> Optional[ast.AST]:
    """The dtype arg of a ``pltpu.VMEM(shape, dtype)`` scratch spec; None
    for non-VMEM entries (semaphores etc. carry no accumulator risk)."""
    if isinstance(node, ast.Call):
        rd = resolve_dotted(node.func, aliases)
        if rd is not None and rd.endswith(".VMEM") and len(node.args) >= 2:
            return node.args[1]
    return None


def _resolve_kernel(
    arg: ast.AST, src: SourceFile, info: Optional[FunctionInfo], graph: CallGraph
) -> Optional[FunctionInfo]:
    keys, _ = graph.resolve_call(arg, src, info)
    for k in keys:
        return graph.functions[k]
    # the idiomatic wrappers bind the kernel through a local first:
    #   kernel = functools.partial(_kernel, disp=...); pl.pallas_call(kernel, ...)
    # resolve_call treats a bare local Name as opaque, so chase the
    # single-target assignment ourselves (resolve_call unwraps partial).
    if isinstance(arg, ast.Name) and info is not None:
        for st in _own_nodes(info.node):
            if (
                isinstance(st, ast.Assign)
                and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and st.targets[0].id == arg.id
            ):
                keys, _ = graph.resolve_call(st.value, src, info)
                for k in keys:
                    return graph.functions[k]
    return None


def _check_pallas(ctx: _Ctx) -> List[Finding]:
    out: List[Finding] = []
    graph = ctx.graph
    for src in ctx.sources:
        if not _pallas_scope(src):
            continue
        aliases = ctx.aliases[src.rel]
        for key, info in graph.functions.items():
            if info.src is not src:
                continue
            for node in _own_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                rd = resolve_dotted(node.func, aliases)
                if rd is None or not (
                    rd == "pallas_call" or rd.endswith(".pallas_call")
                ):
                    continue
                kernel = (
                    _resolve_kernel(node.args[0], src, info, graph)
                    if node.args
                    else None
                )
                out.extend(
                    _pallas_site(ctx, src, aliases, info, node, kernel)
                )
    return out


def _pallas_site(
    ctx: _Ctx,
    src: SourceFile,
    aliases: Dict[str, str],
    wrapper: FunctionInfo,
    call: ast.Call,
    kernel: Optional[FunctionInfo],
) -> List[Finding]:
    out: List[Finding] = []

    def emit(line: int, col: int, msg: str) -> None:
        out.append(Finding(src.path, line, col, RULES["GC805"], msg, _HINT_805))

    # --- wrapper-side: grid divisibility + interpret exposure ---------------
    wnode = wrapper.node
    has_mod_guard = any(
        isinstance(n, ast.If)
        and any(
            isinstance(s, ast.BinOp) and isinstance(s.op, ast.Mod)
            for s in ast.walk(n.test)
        )
        for n in _own_nodes(wnode)
    )
    has_pad = any(
        isinstance(n, ast.Call)
        and (resolve_dotted(n.func, aliases) or "").endswith(".pad")
        for n in _own_nodes(wnode)
    )
    for elt in _seq_elts(_kw(call, "grid")):
        if isinstance(elt, ast.BinOp) and isinstance(elt.op, ast.FloorDiv):
            if not has_mod_guard and not _islanded(src, None, elt.lineno):
                emit(
                    elt.lineno, elt.col_offset,
                    f"grid dimension `//` in {wrapper.name!r} with no "
                    "divisibility guard: a remainder silently drops rows",
                )
        elif isinstance(elt, ast.Call) and (
            resolve_dotted(elt.func, aliases) or ""
        ).endswith(".cdiv"):
            if not (has_pad or has_mod_guard):
                emit(
                    elt.lineno, elt.col_offset,
                    f"cdiv grid in {wrapper.name!r} rounds up but nothing "
                    "pads or guards the remainder rows",
                )
    if "interpret" not in param_names(wnode):
        emit(
            wnode.lineno, wnode.col_offset,
            f"kernel wrapper {wrapper.name!r} exposes no interpret= "
            "parameter: CPU parity tests cannot drive it",
        )
    else:
        dirs = _tests_dirs(os.path.dirname(src.path))
        texts = _tests_texts(dirs)
        tested = any(
            wrapper.name in txt and "interpret=True" in txt for txt in texts
        )
        if not tested:
            emit(
                wnode.lineno, wnode.col_offset,
                f"no interpret-mode parity test exercises {wrapper.name!r} "
                "(need `interpret=True` + the wrapper name under tests/)",
            )

    # --- kernel-side: accumulator dtypes + dot/reduction pins ---------------
    if kernel is None:
        return out
    knode = kernel.node
    params = [a.arg for a in knode.args.posonlyargs + knode.args.args]
    scratch_elts = _seq_elts(_kw(call, "scratch_shapes"))
    n_scratch = len(scratch_elts)
    scratch_of: Dict[str, ast.AST] = {}
    if n_scratch and len(params) >= n_scratch:
        for p, spec in zip(params[-n_scratch:], scratch_elts):
            scratch_of[p] = spec

    loads: Dict[str, str] = {}  # local name -> param it loads from
    for n in _own_nodes(knode):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Subscript):
            base = n.value.value
            if isinstance(base, ast.Name) and base.id in params:
                for tgt in n.targets:
                    for nm in _target_names(tgt):
                        loads[nm] = base.id

    # names loaded from f32 VMEM scratch seed the kernel's pinned set
    f32_scratch: Set[str] = set()
    for p, spec in scratch_of.items():
        dt = _scratch_dtype(spec, aliases)
        if dt is not None and _is_f32_dtype(dt, aliases):
            f32_scratch.add(p)
    seed = {nm for nm, p in loads.items() if p in f32_scratch}
    khighs = _highest_names(knode, aliases)
    pinned = _pinned_names(knode, aliases, seed=seed, highs=khighs)

    def subscript_writes(n: ast.AST) -> Optional[str]:
        tgt = None
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            tgt = n.targets[0]
        elif isinstance(n, ast.AugAssign):
            tgt = n.target
        if isinstance(tgt, ast.Subscript) and isinstance(tgt.value, ast.Name):
            if tgt.value.id in params:
                return tgt.value.id
        return None

    for n in _own_nodes(knode):
        p = subscript_writes(n)
        if p is not None:
            value = n.value
            rmw = isinstance(n, ast.AugAssign)
            if not rmw and value is not None:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Subscript) and isinstance(
                        sub.value, ast.Name
                    ) and sub.value.id == p:
                        rmw = True
                        break
                    if isinstance(sub, ast.Name) and loads.get(sub.id) == p:
                        rmw = True
                        break
            if rmw and not _islanded(src, None, n.lineno):
                if p in scratch_of:
                    dt = _scratch_dtype(scratch_of[p], aliases)
                    if dt is not None and not _is_f32_dtype(dt, aliases):
                        emit(
                            dt.lineno, dt.col_offset,
                            f"accumulator scratch {p!r} of kernel "
                            f"{kernel.name!r} is not float32",
                        )
                else:
                    emit(
                        n.lineno, n.col_offset,
                        f"kernel {kernel.name!r} accumulates into "
                        f"non-scratch ref {p!r}: carry partial sums in "
                        "float32 VMEM scratch and store once",
                    )
            continue
        if not isinstance(n, ast.Call):
            continue
        rd = resolve_dotted(n.func, aliases)
        kind: Optional[str] = None
        operands: List[ast.AST] = []
        if rd in _MATMUL:
            kind = rd.rsplit(".", 1)[-1]
            operands = _operands(n, rd)
        elif rd in _KERNEL_REDUCTIONS:
            kind = rd.rsplit(".", 1)[-1]
            operands = list(n.args)
        elif (
            isinstance(n.func, ast.Attribute)
            and n.func.attr in _KERNEL_REDUCTION_METHODS
            and isinstance(n.func.value, ast.Name)
        ):
            kind = f".{n.func.attr}()"
            operands = [n.func.value]
        if kind is None or _islanded(src, None, n.lineno):
            continue
        if _call_has_pin(n, aliases, khighs):
            continue
        if any(_pinning_expr(a, aliases, pinned, khighs) for a in operands):
            continue
        emit(
            n.lineno, n.col_offset,
            f"{kind} in kernel {kernel.name!r} accumulates in the input "
            "dtype (bf16 inputs lose the sum)",
        )
    return out


# --- family entry -----------------------------------------------------------

def check(
    sources: Sequence[SourceFile], graph: CallGraph, project: ProjectTaint
) -> List[Finding]:
    ctx = _Ctx(sources, graph)
    findings: List[Finding] = []
    findings.extend(_check_promotion(ctx))
    findings.extend(_check_accum(ctx))
    jit_reach = set(ctx.reach(sorted(_jit_roots(ctx))))
    findings.extend(_check_cast_discipline(ctx, project, jit_reach))
    findings.extend(_check_parity_coverage(sources))
    findings.extend(_check_pallas(ctx))
    return findings
