"""GC10x — host-sync lint for hot modules.

The per-video loop's throughput argument (PAPER.md, docs/tpu.md) depends
on dispatch staying asynchronous: XLA enqueues work and returns; the ONE
blocking point per video is the explicit result fetch at the sink
boundary. Any ``.item()``, ``float()``/``int()`` on a traced value,
``np.asarray`` on a device array, or ``block_until_ready`` inside the
hot modules (``extract/``, ``ops/``, ``models/*/model.py``) inserts a
hidden synchronous round-trip per call site — invisible in review,
catastrophic over a million-video corpus.

v2: device-value tracking is the *interprocedural* taint engine in
``taint.py`` — a name is device-tainted when it is a parameter of a
jitted function, was assigned from a ``jax.*``/``jnp.*``/``lax.*`` call
(or an expression over tainted names), **or flowed here through a
project call** (a helper's device return, a device argument a caller
passed in). ``int(math.ceil(...))`` on host geometry never taints;
``int(jnp.argmax(x))`` does; so does ``int(helper(x))`` when the helper
returns its jnp result. Every finding carries the propagation chain in
``Finding.trace`` (``--explain GC10x`` prints it). Unambiguous sync
idioms (``.item()``, ``.block_until_ready()``) are flagged regardless of
taint.

The sink/fetch boundary is allowlisted by function name: ``fetch_*`` and
``*sink*`` functions exist to sync (that is the contract — the pipelined
loop calls them exactly once per video, after the next video's dispatch
is already in flight). The allowlist covers defs nested inside them too.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from video_features_tpu.analysis.core import Finding, Rule, SourceFile
from video_features_tpu.analysis.taint import (
    _FETCHERS,
    ProjectTaint,
    Taint,
    flatten_body,
    format_chain,
)

RULES = {
    "GC101": Rule("GC101", "host-sync-item", ".item() forces a device->host sync"),
    "GC102": Rule(
        "GC102", "host-sync-cast", "float()/int() on a traced/device value syncs"
    ),
    "GC103": Rule(
        "GC103",
        "host-sync-fetch",
        "np.asarray/np.array/jax.device_get on a device value syncs",
    ),
    "GC104": Rule(
        "GC104", "host-sync-block", "block_until_ready() stalls the dispatch pipeline"
    ),
}

# the sink/fetch/drain boundary: these functions' JOB is the blocking
# fetch side of the pipeline. ``fetch_*`` are the extractor hooks
# (fetch_group/fetch_dispatched), ``drain_*`` is the pipelined loop's
# completion-queue drain (extract/base.py::drain_completed — the ONE
# place dispatched handles become host numpy since the async-ingest
# restructure), and "sink" covers the result writers. Anything else
# that forces a device->host sync in a hot module is a finding — the
# scope-pin test in tests/test_analysis.py proves a rename out of this
# list would refire.
ALLOWED_NAME_PREFIXES = ("fetch_", "_fetch", "drain_", "_drain")
ALLOWED_NAME_SUBSTRINGS = ("sink",)


def _allowlisted(name: str) -> bool:
    return name.startswith(ALLOWED_NAME_PREFIXES) or any(
        s in name for s in ALLOWED_NAME_SUBSTRINGS
    )


def check(src: SourceFile, project: ProjectTaint) -> List[Finding]:
    from video_features_tpu.analysis.core import resolve_dotted

    aliases = project._aliases[src.rel]
    findings: List[Finding] = []

    def trace_of(t: Taint, tail: str, line: int) -> List[str]:
        if not t.device or not t.chain:
            return []
        return format_chain(t.chain) + [f"{src.path}:{line}: {tail}"]

    def flag_call(node: ast.Call, env, info, fn_name: str) -> None:
        func = node.func
        taint = lambda e: project.expr_taint(e, env, src, info)  # noqa: E731
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC101"],
                        f".item() in hot function {fn_name!r}",
                        "keep the value on device (jnp.where/compare), or move "
                        "the sync to the fetch boundary",
                        trace=trace_of(
                            taint(func.value), ".item() syncs here", node.lineno
                        ),
                    )
                )
                return
            if func.attr == "block_until_ready":
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC104"],
                        f"block_until_ready() in hot function {fn_name!r}",
                        "only the sink/fetch boundary may block; delete the "
                        "barrier or move it into fetch_*",
                        trace=trace_of(
                            taint(func.value),
                            "block_until_ready() blocks here",
                            node.lineno,
                        ),
                    )
                )
                return
        rd = resolve_dotted(func, aliases)
        if rd in ("float", "int", "bool", "complex") and node.args:
            t = taint(node.args[0])
            if t.device:
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC102"],
                        f"{rd}() on a traced/device value in {fn_name!r}",
                        "keep the scalar on device (jnp ops) or fetch it once "
                        "at the sink boundary",
                        trace=trace_of(t, f"{rd}() syncs here", node.lineno),
                    )
                )
            return
        if rd in _FETCHERS:
            t = taint(node.args[0]) if node.args else Taint()
            if rd == "jax.device_get" or t.device:
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC103"],
                        f"{rd}() on a device value in {fn_name!r}",
                        "return the device array and let fetch_*/the sink "
                        "materialize it",
                        trace=trace_of(t, f"{rd}() syncs here", node.lineno),
                    )
                )

    def flag_scope(body, env, info, fn_name: str) -> None:
        """Walk each flattened statement's EXPRESSION children only
        (child statements are in the flat list themselves; nested defs
        get their own scope) so no call site is visited twice."""
        for st in flatten_body(body):
            for child in ast.iter_child_nodes(st):
                if isinstance(
                    child,
                    (ast.stmt, ast.excepthandler, ast.FunctionDef,
                     ast.AsyncFunctionDef),
                ) or type(child).__name__ == "match_case":
                    continue
                for node in ast.walk(child):
                    if isinstance(node, ast.Call):
                        flag_call(node, env, info, fn_name)

    flag_scope(src.tree.body, project.module_env(src), None, "<module>")

    for key, info in project.graph.functions.items():
        if info.src is not src:
            continue
        if _scope_allowlisted(project, info):
            continue
        flag_scope(info.node.body, project.env_for(key), info, info.name)

    return findings


def _scope_allowlisted(project: ProjectTaint, info) -> bool:
    cur: Optional[object] = info
    while cur is not None:
        if _allowlisted(cur.name):
            return True
        cur = project.graph.functions.get(cur.parent) if cur.parent else None
    return False
