"""GC10x — host-sync lint for hot modules.

The per-video loop's throughput argument (PAPER.md, docs/tpu.md) depends
on dispatch staying asynchronous: XLA enqueues work and returns; the ONE
blocking point per video is the explicit result fetch at the sink
boundary. Any ``.item()``, ``float()``/``int()`` on a traced value,
``np.asarray`` on a device array, or ``block_until_ready`` inside the
hot modules (``extract/``, ``ops/``, ``models/*/model.py``) inserts a
hidden synchronous round-trip per call site — invisible in review,
catastrophic over a million-video corpus.

Device-value tracking is a deliberately shallow intra-function taint
pass: a name is "device-tainted" when it is a parameter of a jitted
function or was assigned from a ``jax.*``/``jnp.*``/``lax.*`` call (or
an expression over tainted names). ``int(math.ceil(...))`` on host
geometry never taints; ``int(jnp.argmax(x))`` does. Unambiguous sync
idioms (``.item()``, ``.block_until_ready()``) are flagged regardless of
taint.

The sink/fetch boundary is allowlisted by function name: ``fetch_*`` and
``*sink*`` functions exist to sync (that is the contract — the pipelined
loop calls them exactly once per video, after the next video's dispatch
is already in flight).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    import_aliases,
    jit_decoration,
    param_names,
    resolve_dotted,
)

RULES = {
    "GC101": Rule("GC101", "host-sync-item", ".item() forces a device->host sync"),
    "GC102": Rule(
        "GC102", "host-sync-cast", "float()/int() on a traced/device value syncs"
    ),
    "GC103": Rule(
        "GC103",
        "host-sync-fetch",
        "np.asarray/np.array/jax.device_get on a device value syncs",
    ),
    "GC104": Rule(
        "GC104", "host-sync-block", "block_until_ready() stalls the dispatch pipeline"
    ),
}

# the sink/fetch boundary: these functions' JOB is the one blocking fetch
# per video (extract/base.py pipelined loop contract)
ALLOWED_NAME_PREFIXES = ("fetch_", "_fetch")
ALLOWED_NAME_SUBSTRINGS = ("sink",)

# heads whose call results live on device
_DEVICE_HEADS = ("jax", "jnp", "jax.numpy", "lax", "jax.lax", "flax")
# jax calls whose results are HOST values (never taint)
_HOST_RESULTS = frozenset(
    {
        "jax.device_get",
        "jax.process_index",
        "jax.process_count",
        "jax.device_count",
        "jax.local_device_count",
        "jax.devices",
        "jax.local_devices",
        "jax.default_backend",
    }
)
_FETCHERS = frozenset({"numpy.asarray", "numpy.array", "jax.device_get"})


def _allowlisted(name: str) -> bool:
    return name.startswith(ALLOWED_NAME_PREFIXES) or any(
        s in name for s in ALLOWED_NAME_SUBSTRINGS
    )


def check(src: SourceFile) -> List[Finding]:
    aliases = import_aliases(src.tree)
    findings: List[Finding] = []

    def scan_scope(body: List[ast.stmt], tainted: Set[str], fn_name: str) -> None:
        """One function (or module) scope: fixpoint-taint its locals,
        then flag sync idioms. Nested defs get their own scope (jitted
        nested defs start with their params tainted)."""
        if _allowlisted(fn_name):
            return

        nested: List[ast.FunctionDef] = []
        flat: List[ast.stmt] = []

        def flatten(stmts):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.append(st)
                    continue
                flat.append(st)
                for field in ("body", "orelse", "finalbody"):
                    flatten(getattr(st, field, []) or [])
                for h in getattr(st, "handlers", []) or []:
                    flatten(h.body)
                for case in getattr(st, "cases", []) or []:
                    flatten(case.body)

        flatten(body)

        # taint fixpoint over the flattened statement list
        for _ in range(4):
            changed = False
            for st in flat:
                if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    value = st.value
                    if value is None or not _tainted_expr(value, tainted, aliases):
                        continue
                    targets = (
                        st.targets
                        if isinstance(st, ast.Assign)
                        else [st.target]
                    )
                    for t in targets:
                        for n in _target_names(t):
                            if n not in tainted:
                                tainted.add(n)
                                changed = True
            if not changed:
                break

        # flag pass: walk each flattened statement's EXPRESSION children
        # only (child statements are in ``flat`` themselves; nested defs
        # get their own scope) so no call site is visited twice
        for st in flat:
            for child in ast.iter_child_nodes(st):
                if isinstance(
                    child,
                    (ast.stmt, ast.excepthandler, ast.FunctionDef,
                     ast.AsyncFunctionDef),
                ) or type(child).__name__ == "match_case":
                    continue
                for node in ast.walk(child):
                    if isinstance(node, ast.Call):
                        _flag_call(node, tainted, fn_name)

        for sub in nested:
            sub_tainted = set(tainted)
            site = jit_decoration(sub, aliases)
            if site is not None:
                static = set(site.static_argnames)
                sub_tainted |= {
                    p for p in param_names(sub) if p not in static
                }
            scan_scope(sub.body, sub_tainted, sub.name)

    def _flag_call(node: ast.Call, tainted: Set[str], fn_name: str) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC101"],
                        f".item() in hot function {fn_name!r}",
                        "keep the value on device (jnp.where/compare), or move "
                        "the sync to the fetch boundary",
                    )
                )
                return
            if func.attr == "block_until_ready":
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC104"],
                        f"block_until_ready() in hot function {fn_name!r}",
                        "only the sink/fetch boundary may block; delete the "
                        "barrier or move it into fetch_*",
                    )
                )
                return
        rd = resolve_dotted(func, aliases)
        if rd in ("float", "int", "bool", "complex") and node.args:
            if _tainted_expr(node.args[0], tainted, aliases):
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC102"],
                        f"{rd}() on a traced/device value in {fn_name!r}",
                        "keep the scalar on device (jnp ops) or fetch it once "
                        "at the sink boundary",
                    )
                )
            return
        if rd in _FETCHERS:
            if rd == "jax.device_get" or (
                node.args and _tainted_expr(node.args[0], tainted, aliases)
            ):
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC103"],
                        f"{rd}() on a device value in {fn_name!r}",
                        "return the device array and let fetch_*/the sink "
                        "materialize it",
                    )
                )

    scan_scope(src.tree.body, set(), "<module>")
    return findings


def _target_names(t: ast.AST) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in t.elts:
            out.extend(_target_names(el))
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return []


def _tainted_expr(node: ast.AST, tainted: Set[str], aliases: Dict[str, str]) -> bool:
    """Does evaluating ``node`` touch a device value?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
        if isinstance(sub, ast.Call):
            rd = resolve_dotted(sub.func, aliases)
            if rd is None:
                continue
            if rd in _HOST_RESULTS:
                continue
            head = rd.split(".")[0]
            resolved_head = aliases.get(head, head)
            if resolved_head in ("jax", "lax", "flax") or rd.startswith(
                ("jax.numpy.", "jax.lax.", "jax.nn.")
            ):
                return True
            if resolved_head == "jax.numpy" or resolved_head == "jax.lax":
                return True
    return False
