"""graftcheck — the repo's static-analysis suite (docs/analysis.md).

The checker families turn the design rules the hot path and the fleet
depend on into tier-1 test failures instead of review-time folklore:

- GC10x host-sync lint (:mod:`.hostsync`) — no hidden device->host
  syncs inside the per-video hot loop.
- GC20x jit-hygiene lint (:mod:`.jit_hygiene`) — jit closures stay
  immutable, Python control flow stays off traced values, static-arg
  declarations name real parameters.
- GC301 thread-safety lint (:mod:`.thread_safety`) — module-level
  mutable state on thread-reachable paths is locked, thread-local, or
  explicitly waived.
- GC31x concurrency lint (:mod:`.concurrency`) — lock ordering, no
  blocking I/O or waits under a held lock on dispatch paths.
- GC401 recompilation budget (:mod:`.compile_budget`) — a runtime
  tracer pins executable counts per extractor to
  ``analysis/compile_budget.json``.
- GC50x sharding contract (:mod:`.sharding_contract`) — mesh entries
  declare shardings that exist, mesh-capable models keep their specs.
- GC60x durability contracts (:mod:`.durability`) — durable publishes
  stage-then-``os.replace``, claim/lease sites branch on losing and
  heartbeat what they hold, renames carry the right semantics.
- GC70x observability contracts (:mod:`.obs_contract`) — every metric
  name maps to a curated exposition family (and every family has a
  producer), fault stages match ``fire()`` sites both directions, and
  config.py's flags / dataclass fields / sanity checks stay in sync.
- GC80x numerics & dtype-flow contracts (:mod:`.numerics`) — no f64
  promotion leaks into jit-reachable code, matmuls and sensitive
  reductions under bf16-polymorphic entries pin their accumulation
  dtype, host-side float32 casts on frame payloads are declared
  islands, every admitted (family, dtype) pair carries a committed
  drift ceiling in ``analysis/parity_budget.json`` plus an e2e parity
  assertion, and Pallas kernels keep accumulator/grid/interpret
  hygiene.

Run ``python -m video_features_tpu.analysis`` (CLI) or
``pytest -m analysis`` (tier-1). Waive individual findings with inline
``# graftcheck: <rule> — reason`` comments; audit them all with
``git grep 'graftcheck:'``.
"""

from video_features_tpu.analysis.compile_budget import (
    CompileCounter,
    assert_within_budget,
    check_counts,
    load_budget,
)
from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    all_rules,
    collect_sources,
    run_checks,
)
from video_features_tpu.analysis.parity import (
    assert_drift_within,
    load_parity_budget,
    max_rel_drift,
    rel_drift,
)

__all__ = [
    "CompileCounter",
    "Finding",
    "Rule",
    "all_rules",
    "assert_drift_within",
    "assert_within_budget",
    "check_counts",
    "collect_sources",
    "load_budget",
    "load_parity_budget",
    "max_rel_drift",
    "rel_drift",
    "run_checks",
]
