"""Project call graph for graftcheck's interprocedural passes (v2).

One :class:`CallGraph` is built per ``run_checks`` sweep and shared by the
taint pass (hostsync GC10x), the thread-safety pass (GC301), and the
sharding-contract pass (GC50x). Resolution is deliberately conservative —
static Python call resolution is undecidable, so unresolvable edges err
toward *more* reachability (a bare call through a variable fans out to
every project ``__call__``; ``self.prepare(...)`` fans out to every method
named ``prepare``) so the thread-safety walk never silently exempts a
function that might really run on a worker thread.

The graph also locates *thread entries*: functions handed to
``threading.Thread(target=...)``, ``pool.submit(fn, ...)``,
``executor.map(fn, ...)``, ``threading.Timer(_, fn)`` or
``_thread.start_new_thread(fn, ...)``. Files carrying the
``# graftcheck: thread-root`` marker but containing NO resolvable spawn
site (the test-fixture contract) treat every function they define as an
entry — a marker says "this file's code runs on threads" when the spawn
site itself is out of view.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.core import (
    SourceFile,
    import_aliases,
    resolve_dotted,
)


@dataclasses.dataclass
class FunctionInfo:
    key: str  # unique: "<rel>::<qualpath>"
    name: str  # bare name
    src: SourceFile
    node: ast.FunctionDef
    cls: Optional[str]  # enclosing class name, if a method
    parent: Optional[str]  # enclosing function's key, for closures


@dataclasses.dataclass
class CallSite:
    caller: str  # FunctionInfo.key, or "<rel>::" for module body
    callee: str  # resolved FunctionInfo.key
    node: ast.Call
    src: SourceFile


def module_suffixes(src: SourceFile) -> Set[str]:
    """Dotted-name suffixes this module answers to (mirrors the
    thread-safety import matcher): ``io/sink.py`` answers to
    ``io.sink`` and ``sink``; ``native/__init__.py`` also to ``native``."""
    name = src.module_name
    out = {name}
    parts = name.split(".")
    for i in range(1, len(parts)):
        out.add(".".join(parts[i:]))
    if parts[-1] == "__init__":
        pkg = ".".join(parts[:-1])
        if pkg:
            pp = pkg.split(".")
            for i in range(len(pp)):
                out.add(".".join(pp[i:]))
    return out


# spawn shapes: (attribute-or-name the call resolves to, how the target
# function rides the call)
_THREAD_CTORS = ("threading.Thread", "Thread")
_TIMER_CTORS = ("threading.Timer", "Timer")
_START_NEW = ("_thread.start_new_thread", "thread.start_new_thread",
              "start_new_thread")


class CallGraph:
    def __init__(self, sources: Sequence[SourceFile]) -> None:
        self.sources = list(sources)
        self.functions: Dict[str, FunctionInfo] = {}
        self.by_name: Dict[str, List[str]] = {}
        self.methods_of: Dict[Tuple[str, str, str], str] = {}  # (rel, cls, name)
        self.classes: Dict[Tuple[str, str], List[str]] = {}  # (rel, cls) -> keys
        self._module_by_suffix: Dict[str, SourceFile] = {}
        self._aliases: Dict[str, Dict[str, str]] = {}
        self.calls: Dict[str, List[CallSite]] = {}
        self.callers: Dict[str, List[CallSite]] = {}
        self.unresolved_callers: Set[str] = set()  # callers with a bare
        # call through a variable (fan out to __call__ methods)
        self._node_key: Dict[int, str] = {}  # id(FunctionDef) -> key
        self._spawn_targets: Dict[str, List[str]] = {}  # rel -> entry keys
        self._spawned_rels: Set[str] = set()  # rels with >=1 resolvable spawn

        for src in sources:
            for suf in module_suffixes(src):
                self._module_by_suffix.setdefault(suf, src)
            self._aliases[src.rel] = import_aliases(src.tree)
        for src in sources:
            self._index(src)
        for src in sources:
            self._link(src)

    # --- indexing -----------------------------------------------------------

    def _index(self, src: SourceFile) -> None:
        def visit(node, cls, fn_stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, fn_stack)
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    path = list(fn_stack) + [child.name]
                    key = f"{src.rel}::{'.'.join(([cls] if cls else []) + path)}"
                    # disambiguate re-defs (mesh/solo factory branches)
                    base, n = key, 2
                    while key in self.functions:
                        key = f"{base}#{n}"
                        n += 1
                    info = FunctionInfo(
                        key=key, name=child.name, src=src, node=child,
                        cls=cls,
                        parent=(fn_stack_keys[-1] if fn_stack_keys else None),
                    )
                    self.functions[key] = info
                    self._node_key[id(child)] = key
                    self.by_name.setdefault(child.name, []).append(key)
                    if cls and not fn_stack:  # a direct method, not a
                        # def nested inside one
                        self.methods_of.setdefault((src.rel, cls, child.name), key)
                        self.classes.setdefault((src.rel, cls), []).append(key)
                    fn_stack.append(child.name)
                    fn_stack_keys.append(key)
                    visit(child, cls, fn_stack)
                    fn_stack.pop()
                    fn_stack_keys.pop()
                else:
                    visit(child, cls, fn_stack)

        fn_stack_keys: List[str] = []
        visit(src.tree, None, [])

    def key_of(self, fn_node: ast.AST) -> Optional[str]:
        return self._node_key.get(id(fn_node))

    # --- resolution ---------------------------------------------------------

    def module_function(self, src: SourceFile, name: str) -> Optional[str]:
        key = f"{src.rel}::{name}"
        return key if key in self.functions else None

    def resolve_module(self, dotted: str) -> Optional[SourceFile]:
        parts = dotted.split(".")
        for i in range(len(parts)):
            hit = self._module_by_suffix.get(".".join(parts[i:]))
            if hit is not None:
                return hit
        return None

    def _class_init(self, src: SourceFile, cls: str) -> List[str]:
        key = self.methods_of.get((src.rel, cls, "__init__"))
        return [key] if key else []

    def _local_classes(self, src: SourceFile) -> Set[str]:
        return {
            n.name for n in src.tree.body if isinstance(n, ast.ClassDef)
        }

    def resolve_call(
        self, func: ast.AST, src: SourceFile, caller: Optional[FunctionInfo]
    ) -> Tuple[List[str], bool]:
        """Resolved callee keys for a call through ``func``, plus a flag
        for "bare call through a variable" (unresolvable — the caller
        conservatively reaches every project ``__call__``)."""
        aliases = self._aliases[src.rel]
        if isinstance(func, ast.Name):
            name = func.id
            # nested def in an enclosing function
            info = caller
            while info is not None:
                hits = [
                    k for k in self.by_name.get(name, ())
                    if self.functions[k].parent == info.key
                ]
                if hits:
                    return hits, False
                info = (
                    self.functions.get(info.parent) if info.parent else None
                )
            hit = self.module_function(src, name)
            if hit:
                return [hit], False
            if name in self._local_classes(src):
                return self._class_init(src, name), False
            target = aliases.get(name)
            if target:
                mod, _, attr = target.rpartition(".")
                m = self.resolve_module(mod) if attr else None
                if m is not None:
                    hit = self.module_function(m, attr)
                    if hit:
                        return [hit], False
                    if attr in self._local_classes(m):
                        return self._class_init(m, attr), False
                # imported from outside the project: external, resolved-empty
                return [], False
            # a variable holding a callable: unresolvable
            return [], True
        if isinstance(func, ast.Attribute):
            attr = func.attr
            base = func.value
            rd = resolve_dotted(base, aliases)
            if rd is not None:
                m = self.resolve_module(rd)
                if m is not None:
                    hit = self.module_function(m, attr)
                    if hit:
                        return [hit], False
                    if attr in self._local_classes(m):
                        return self._class_init(m, attr), False
            if (
                isinstance(base, ast.Name)
                and base.id in ("self", "cls")
                and caller is not None
                and caller.cls is not None
            ):
                own = self.methods_of.get((src.rel, caller.cls, attr))
                if own:
                    return [own], False
            # conservative by-name: every project def with this name
            return list(self.by_name.get(attr, ())), False
        if isinstance(func, ast.Call):
            # functools.partial(fn, ...) and friends: resolve the head arg
            rd = resolve_dotted(func.func, aliases)
            if rd in ("functools.partial", "partial") and func.args:
                return self.resolve_call(func.args[0], src, caller)
        return [], False

    # --- linking ------------------------------------------------------------

    def _enclosing(self, src: SourceFile, stack: List[str]) -> Optional[FunctionInfo]:
        return self.functions.get(stack[-1]) if stack else None

    def _link(self, src: SourceFile) -> None:
        spawn_keys: List[str] = []

        def visit(node, stack: List[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    key = self.key_of(child)
                    visit(child, stack + ([key] if key else []))
                    continue
                if isinstance(child, ast.Call):
                    caller_info = self._enclosing(src, stack)
                    caller_key = (
                        caller_info.key if caller_info else f"{src.rel}::"
                    )
                    callees, bare = self.resolve_call(
                        child.func, src, caller_info
                    )
                    if bare:
                        self.unresolved_callers.add(caller_key)
                    for ck in callees:
                        site = CallSite(caller_key, ck, child, src)
                        self.calls.setdefault(caller_key, []).append(site)
                        self.callers.setdefault(ck, []).append(site)
                    spawn_keys.extend(
                        self._spawn_target_keys(child, src, caller_info)
                    )
                visit(child, stack)

        visit(src.tree, [])
        if spawn_keys:
            self._spawned_rels.add(src.rel)
            self._spawn_targets[src.rel] = spawn_keys

    def _spawn_target_keys(
        self, call: ast.Call, src: SourceFile, caller: Optional[FunctionInfo]
    ) -> List[str]:
        aliases = self._aliases[src.rel]
        rd = resolve_dotted(call.func, aliases)
        target: Optional[ast.AST] = None
        if rd in _THREAD_CTORS or (rd or "").endswith("threading.Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
        elif rd in _TIMER_CTORS and len(call.args) >= 2:
            target = call.args[1]
        elif rd in _START_NEW and call.args:
            target = call.args[0]
        elif isinstance(call.func, ast.Attribute) and call.func.attr in (
            "submit", "map", "apply_async",
        ) and call.args:
            target = call.args[0]
        if target is None:
            return []
        keys, _ = self.resolve_call(target, src, caller)
        return keys

    # --- thread reachability ------------------------------------------------

    def thread_entries(self) -> Set[str]:
        entries: Set[str] = set()
        for keys in self._spawn_targets.values():
            entries.update(keys)
        for src in self.sources:
            if "thread-root" in src.markers and src.rel not in self._spawned_rels:
                # marker fixture with no visible spawn site: every def in
                # the file runs on threads by declaration
                entries.update(
                    k for k, f in self.functions.items() if f.src is src
                )
        return entries

    def thread_side(self) -> Dict[str, Tuple[str, ...]]:
        """key -> reachability chain (entry-first list of keys) for every
        function reachable from a thread entry, closed over calls. A bare
        call through a variable inside thread-side code fans out to every
        project ``__call__`` method."""
        chains: Dict[str, Tuple[str, ...]] = {}
        frontier: List[str] = []
        for e in sorted(self.thread_entries()):
            if e not in chains:
                chains[e] = (e,)
                frontier.append(e)
        call_methods = [
            k for k, f in self.functions.items() if f.name == "__call__"
        ]
        while frontier:
            nxt: List[str] = []
            for key in frontier:
                succ = [s.callee for s in self.calls.get(key, ())]
                if key in self.unresolved_callers:
                    succ.extend(call_methods)
                for s in succ:
                    if s not in chains:
                        chains[s] = chains[key] + (s,)
                        nxt.append(s)
            frontier = nxt
        return chains
