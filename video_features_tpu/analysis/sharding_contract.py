"""GC50x — sharding-spec contracts for mesh-reachable jit entries.

``--sharding mesh`` turns every jit dispatch into a collective: an entry
that does not say where its inputs and outputs live either silently
replicates the whole batch onto every device (memory x8, bandwidth x8)
or deadlocks a multi-host run when processes disagree on layout. The
paper's throughput argument needs the *fused preprocess* entries — which
take the raw frame batch plus the banded resample taps — to shard the
frame-batch axis over ``'data'`` and replicate the taps; docs/tpu.md
documents the contract, this family enforces it statically.

Scope: modules that declare a ``mesh_capable = True`` extractor plus
everything under ``parallel/``. Within scope, every jit application
(``@jax.jit`` / ``@partial(jax.jit, ...)`` decorations and
``name = jax.jit(fn, ...)`` wrap-calls) is classified by *mesh polarity*
— a lexical reachability fact derived from ``is_mesh(...)`` guards:

- inside ``if is_mesh(device):`` the polarity is mesh-True;
- inside ``else:`` / under ``not is_mesh(...)`` (including name-bound
  conditions like ``dev_pre = enabled and not is_mesh(device)``) it is
  mesh-False — such sites are single-device by construction and exempt;
- after a *terminal* ``if is_mesh(...): ... return`` branch the rest of
  the suite is mesh-False (the factory early-return pattern);
- anything else is mesh-possible and must carry a contract.

Rules:

- **GC501 mesh-jit-unsharded** — a mesh-possible jit entry declares no
  sharding at all: no ``in_shardings``/``out_shardings`` at the site, no
  ``**multihost_out_kwargs(...)`` splat, and no
  ``with_sharding_constraint``/``shard_map`` inside the jitted body
  (directly or via a one-level local helper).
- **GC502 mesh-fused-shardings** — a mesh-possible jit entry whose body
  runs the fused preprocess (``device_preprocess_frames`` /
  ``device_resize_frames``) must pin BOTH ``in_shardings`` and
  ``out_shardings`` explicitly, and a tuple-literal ``in_shardings``
  must cover every positional parameter (dropping one spec silently
  replicates that input).
- **GC503 mesh-transfer-unsharded** — under mesh-True polarity, raw
  ``jax.device_put`` belongs to the ``parallel.sharding`` placement
  helpers (``place_batch``/``place_params``/``place_raw_payload``),
  which attach NamedShardings; a direct call in an extractor places the
  whole batch on one device.
- **GC504 mesh-fused-payload-roles** — GC502 proves the specs EXIST;
  GC504 proves they say the right thing for the shape-contract payload:
  the raw frame/stack batch the fused entry consumes must shard over
  ``'data'`` (or be constrained inside the body via ``shard_seq``-style
  ``with_sharding_constraint``), and every other payload input — the
  banded resample taps, crop offsets, padder grids — must replicate
  (``P()``). Specs are resolved through local ``NamedSharding(dev,
  P(...))`` bindings and the ``fused_payload_shardings`` helper;
  unresolvable specs are skipped, never guessed.
- **GC505 mesh-admission-coverage** — the other direction of the
  contract: every feature type ``config.py`` admits for ``--sharding
  mesh --preprocess device`` (``MESH_DEVICE_PREPROCESS_FEATURE_TYPES``)
  must map, through ``extract/registry.py``'s dispatch chain, to an
  extractor module (or a module it directly imports) that declares at
  least one mesh-reachable fused jit entry. Admitting a type whose
  fused path is still ``not is_mesh``-gated would let ``sanity_check``
  wave through a config the runtime cannot shard.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence

from video_features_tpu.analysis.callgraph import CallGraph
from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    import_aliases,
    is_jax_jit,
    param_names,
    resolve_dotted,
)

RULES = {
    "GC501": Rule(
        "GC501", "mesh-jit-unsharded",
        "a jit entry reachable under --sharding mesh declares no sharding spec",
    ),
    "GC502": Rule(
        "GC502", "mesh-fused-shardings",
        "a mesh-reachable fused-preprocess jit entry must pin in_shardings "
        "and out_shardings for the frame batch and resample taps",
    ),
    "GC503": Rule(
        "GC503", "mesh-transfer-unsharded",
        "raw jax.device_put under mesh polarity bypasses the sharded "
        "placement helpers",
    ),
    "GC504": Rule(
        "GC504", "mesh-fused-payload-roles",
        "a fused-preprocess in_shardings spec gives a shape-contract "
        "payload the wrong role: frames shard over 'data', taps/offsets/"
        "grids replicate",
    ),
    "GC505": Rule(
        "GC505", "mesh-admission-coverage",
        "a feature type admitted for --sharding mesh --preprocess device "
        "has no mesh-reachable fused jit entry in its extractor module",
    ),
}

_FUSED_ENTRIES = ("device_preprocess_frames", "device_resize_frames")
_BODY_CONSTRAINTS = ("with_sharding_constraint", "shard_map")
_SHARDING_SPLATS = ("multihost_out_kwargs",)


@dataclasses.dataclass
class _JitApp:
    """One jit application in scope: the site, its mesh polarity, the
    jitted def when resolvable, and the keywords at the site."""

    line: int
    col: int
    name: str  # display name of the jitted entry
    polarity: int  # +1 mesh, -1 not-mesh, 0 unknown
    fn: Optional[ast.FunctionDef]
    keywords: List[ast.keyword]


def check(sources: Sequence[SourceFile], graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    for src in sources:
        if not _in_scope(src):
            continue
        findings.extend(_check_file(src))
    findings.extend(_check_admission(sources, graph))
    return findings


def _in_scope(src: SourceFile) -> bool:
    if src.rel.startswith("parallel/"):
        return True
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef):
            for st in node.body:
                targets = []
                if isinstance(st, ast.Assign):
                    targets = st.targets
                elif isinstance(st, ast.AnnAssign):
                    targets = [st.target]
                for t in targets:
                    if (
                        isinstance(t, ast.Name)
                        and t.id == "mesh_capable"
                        and isinstance(getattr(st, "value", None), ast.Constant)
                        and st.value.value is True
                    ):
                        return True
    return False


def _collect(src: SourceFile):
    """All jit applications and raw device_put sites in one module, with
    mesh polarity attached. Shared by the per-file rules (GC501-504) and
    the admission-coverage pass (GC505)."""
    aliases = import_aliases(src.tree)
    apps: List[_JitApp] = []
    puts: List[tuple] = []  # (call, polarity)

    def polarity_of(test: ast.AST, local_pol: Dict[str, int]) -> int:
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return -polarity_of(test.operand, local_pol)
        if isinstance(test, ast.Call):
            rd = resolve_dotted(test.func, aliases)
            if rd is not None and rd.split(".")[-1] == "is_mesh":
                return 1
            return 0
        if isinstance(test, ast.Name):
            return local_pol.get(test.id, 0)
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            # the branch being taken implies every conjunct held
            for v in test.values:
                p = polarity_of(v, local_pol)
                if p:
                    return p
        return 0

    def combine(outer: int, inner: int) -> int:
        return inner if inner else outer

    def terminates(body: List[ast.stmt]) -> bool:
        return bool(body) and isinstance(
            body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
        )

    def record_decorated(fn: ast.FunctionDef, ctx: int) -> None:
        for dec in fn.decorator_list:
            if is_jax_jit(dec, aliases):
                apps.append(_JitApp(fn.lineno, fn.col_offset, fn.name, ctx, fn, []))
                return
            if isinstance(dec, ast.Call):
                callee = resolve_dotted(dec.func, aliases)
                if callee in ("functools.partial", "partial") and dec.args:
                    if is_jax_jit(dec.args[0], aliases):
                        apps.append(
                            _JitApp(fn.lineno, fn.col_offset, fn.name, ctx,
                                    fn, list(dec.keywords))
                        )
                        return
                elif is_jax_jit(dec.func, aliases):
                    apps.append(
                        _JitApp(fn.lineno, fn.col_offset, fn.name, ctx,
                                fn, list(dec.keywords))
                    )
                    return

    def scan_expr(node: ast.AST, ctx: int, defs: Dict[str, ast.FunctionDef],
                  display: str) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if is_jax_jit(sub.func, aliases):
                fn = None
                name = display
                if sub.args and isinstance(sub.args[0], ast.Name):
                    fn = defs.get(sub.args[0].id)
                    name = sub.args[0].id
                apps.append(
                    _JitApp(sub.lineno, sub.col_offset, name, ctx, fn,
                            list(sub.keywords))
                )
            else:
                rd = resolve_dotted(sub.func, aliases)
                if rd is not None and rd.split(".")[-1] == "device_put":
                    puts.append((sub, ctx))

    def visit_suite(stmts: List[ast.stmt], ctx: int,
                    local_pol: Dict[str, int],
                    defs: Dict[str, ast.FunctionDef]) -> None:
        cur = ctx
        for st in stmts:
            if isinstance(st, ast.If):
                p = polarity_of(st.test, local_pol)
                scan_expr(st.test, cur, defs, "<test>")
                visit_suite(st.body, combine(cur, p), dict(local_pol), defs)
                visit_suite(st.orelse, combine(cur, -p if p else 0),
                            dict(local_pol), defs)
                if p and terminates(st.body) and not st.orelse:
                    # factory early-return: the rest of this suite only
                    # runs when the test was false
                    cur = combine(cur, -p)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[st.name] = st
                record_decorated(st, cur)
                visit_suite(st.body, cur, dict(local_pol), dict(defs))
                continue
            if isinstance(st, ast.ClassDef):
                visit_suite(st.body, cur, dict(local_pol), dict(defs))
                continue
            display = "<expr>"
            if isinstance(st, ast.Assign):
                if len(st.targets) == 1 and isinstance(st.targets[0], ast.Name):
                    tname = st.targets[0].id
                    display = tname
                    p = polarity_of(st.value, local_pol)
                    local_pol[tname] = p
                elif len(st.targets) == 1 and isinstance(
                    st.targets[0], ast.Subscript
                ):
                    display = "<subscript>"
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if sub:
                    visit_suite(sub, cur, local_pol, defs)
            for h in getattr(st, "handlers", []) or []:
                visit_suite(h.body, cur, local_pol, defs)
            for child in ast.iter_child_nodes(st):
                if isinstance(child, (ast.stmt, ast.excepthandler)):
                    continue
                scan_expr(child, cur, defs, display)

    visit_suite(src.tree.body, 0, {}, {})
    return aliases, apps, puts


def _check_file(src: SourceFile) -> List[Finding]:
    aliases, apps, puts = _collect(src)
    findings: List[Finding] = []
    splat_names = _sharding_splat_names(src.tree, aliases)
    spec_env = _spec_env(src.tree, aliases)

    for app in apps:
        if app.polarity < 0:
            continue  # provably single-device
        fused = app.fn is not None and _calls_fused(app.fn, aliases)
        kwnames = {kw.arg for kw in app.keywords if kw.arg}
        if fused:
            missing = [
                k for k in ("in_shardings", "out_shardings") if k not in kwnames
            ]
            if missing:
                findings.append(
                    Finding(
                        src.path, app.line, app.col, RULES["GC502"],
                        f"fused-preprocess jit entry {app.name!r} is mesh-"
                        f"reachable but does not pin {', '.join(missing)}",
                        "declare in_shardings=(None, NamedSharding(mesh, "
                        "P('data')), rep, rep) and out_shardings for the "
                        "fused entry, or guard the build with `not "
                        "is_mesh(device)`",
                    )
                )
                continue
            bad = _inshardings_arity_gap(app)
            if bad is not None:
                findings.append(
                    Finding(
                        src.path, app.line, app.col, RULES["GC502"],
                        f"in_shardings on fused entry {app.name!r} covers "
                        f"{bad[0]} of {bad[1]} positional inputs — a dropped "
                        f"spec replicates that input onto every device",
                        "give every positional input an explicit spec (None "
                        "inherits from the placed argument)",
                    )
                )
            else:
                findings.extend(
                    _payload_role_findings(src, app, aliases, spec_env)
                )
            continue
        if kwnames & {"in_shardings", "out_shardings"}:
            continue
        if _has_sharding_splat(app, splat_names, aliases):
            continue
        if app.fn is not None and _body_constrained(app.fn, aliases):
            continue
        findings.append(
            Finding(
                src.path, app.line, app.col, RULES["GC501"],
                f"jit entry {app.name!r} is reachable under --sharding mesh "
                f"but declares no sharding spec",
                "add in_shardings/out_shardings (or **multihost_out_kwargs), "
                "constrain inside the body with with_sharding_constraint/"
                "shard_map, or guard the build with `not is_mesh(device)`",
            )
        )

    if not src.rel.startswith("parallel/"):
        for call, ctx in puts:
            if ctx > 0:
                findings.append(
                    Finding(
                        src.path, call.lineno, call.col_offset, RULES["GC503"],
                        "raw jax.device_put under mesh polarity places the "
                        "whole batch on one device",
                        "route placement through parallel.sharding "
                        "(place_batch/place_params/place_raw_payload) so the "
                        "batch axis lands sharded over 'data'",
                    )
                )
    return findings


def _sharding_splat_names(tree: ast.AST, aliases: Dict[str, str]) -> set:
    """Local names bound (anywhere) to ``multihost_out_kwargs(...)`` —
    ``mh = multihost_out_kwargs(dev); jax.jit(fn, **mh)`` carries the
    contract through the name."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            rd = resolve_dotted(node.value.func, aliases)
            if rd is not None and rd.split(".")[-1] in _SHARDING_SPLATS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _has_sharding_splat(app: _JitApp, splat_names: set,
                        aliases: Dict[str, str]) -> bool:
    for kw in app.keywords:
        if kw.arg is not None:
            continue
        if isinstance(kw.value, ast.Name) and kw.value.id in splat_names:
            return True
        if isinstance(kw.value, ast.Call):
            rd = resolve_dotted(kw.value.func, aliases)
            if rd is not None and rd.split(".")[-1] in _SHARDING_SPLATS:
                return True
    return False


def _local_defs(fn: ast.FunctionDef) -> Dict[str, ast.FunctionDef]:
    return {
        n.name: n
        for n in ast.walk(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not fn
    }


def _calls_in(fn: ast.FunctionDef, aliases: Dict[str, str],
              targets: Sequence[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            rd = resolve_dotted(node.func, aliases)
            if rd is not None and rd.split(".")[-1] in targets:
                return True
    return False


def _calls_fused(fn: ast.FunctionDef, aliases: Dict[str, str]) -> bool:
    return _calls_in(fn, aliases, _FUSED_ENTRIES)


def _body_constrained(fn: ast.FunctionDef, aliases: Dict[str, str]) -> bool:
    """with_sharding_constraint/shard_map in the jitted body, directly or
    through a one-level local helper call (the i3d ``shard_seq`` idiom)."""
    if _calls_in(fn, aliases, _BODY_CONSTRAINTS):
        return True
    # one level: names this body calls that are local defs of the body's
    # own enclosing scope are out of view here, so resolve bare-name calls
    # against the defs nested in fn itself
    local = _local_defs(fn)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            helper = local.get(node.func.id)
            if helper is not None and _calls_in(helper, aliases, _BODY_CONSTRAINTS):
                return True
    return False


def _inshardings_arity_gap(app: _JitApp):
    """(given, expected) when a tuple-literal in_shardings does not cover
    every positional parameter of the jitted def; None when fine."""
    if app.fn is None:
        return None
    for kw in app.keywords:
        if kw.arg == "in_shardings" and isinstance(kw.value, (ast.Tuple, ast.List)):
            expected = len(param_names(app.fn)) - (
                1 if app.fn.args.vararg else 0
            ) - (1 if app.fn.args.kwarg else 0)
            given = len(kw.value.elts)
            if given != expected:
                return (given, expected)
    return None


# --- GC504: payload-role classification -------------------------------------

_DATA = "data"
_REP = "rep"
_AMBIG = "ambig"
_PAYLOAD_HELPER = "fused_payload_shardings"


def _classify_pspec(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """'data' / 'rep' / None for a ``PartitionSpec(...)`` call literal."""
    rd = resolve_dotted(call.func, aliases)
    if rd is None or rd.split(".")[-1] not in ("PartitionSpec", "P"):
        return None
    if not call.args and not call.keywords:
        return _REP
    for a in call.args:
        if isinstance(a, ast.Constant) and a.value == "data":
            return _DATA
    return None  # sharded over some other axis / dynamic — don't judge


def _classify_sharding_expr(expr: ast.AST,
                            aliases: Dict[str, str]) -> Optional[str]:
    if isinstance(expr, ast.Call):
        rd = resolve_dotted(expr.func, aliases)
        if (
            rd is not None
            and rd.split(".")[-1] == "NamedSharding"
            and len(expr.args) >= 2
            and isinstance(expr.args[1], ast.Call)
        ):
            return _classify_pspec(expr.args[1], aliases)
    return None


def _spec_env(tree: ast.AST, aliases: Dict[str, str]) -> Dict[str, str]:
    """Name -> role for every sharding binding visible in the module:
    ``batch_sh = NamedSharding(dev, P('data'))`` style assigns plus the
    ``batch_sh, rep = fused_payload_shardings(dev)`` unpack idiom. A name
    bound to conflicting roles anywhere in the file becomes ambiguous."""
    env: Dict[str, str] = {}

    def put(name: str, kind: Optional[str]) -> None:
        if kind is None:
            return
        if name in env and env[name] != kind:
            env[name] = _AMBIG
        else:
            env[name] = kind

    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        if isinstance(tgt, ast.Name):
            put(tgt.id, _classify_sharding_expr(val, aliases))
        elif (
            isinstance(tgt, ast.Tuple)
            and len(tgt.elts) == 2
            and all(isinstance(e, ast.Name) for e in tgt.elts)
            and isinstance(val, ast.Call)
        ):
            rd = resolve_dotted(val.func, aliases)
            if rd is not None and rd.split(".")[-1] == _PAYLOAD_HELPER:
                put(tgt.elts[0].id, _DATA)
                put(tgt.elts[1].id, _REP)
    return env


def _spec_kind(expr: ast.AST, env: Dict[str, str],
               aliases: Dict[str, str]) -> Optional[str]:
    """Role of one in_shardings tuple element; None when unresolvable
    (never guess — an unknown spec is GC502's arity problem, not ours)."""
    if isinstance(expr, ast.Constant) and expr.value is None:
        return None  # inherits from the placed argument
    if isinstance(expr, (ast.Tuple, ast.List)):
        kinds = [_spec_kind(e, env, aliases) for e in expr.elts]
        if any(k == _DATA for k in kinds):
            return _DATA
        if kinds and all(k == _REP for k in kinds):
            return _REP
        return None
    if isinstance(expr, ast.Name):
        k = env.get(expr.id)
        return None if k == _AMBIG else k
    if isinstance(expr, ast.Call):
        return _classify_sharding_expr(expr, aliases)
    return None


def _frames_param(fn: ast.FunctionDef, aliases: Dict[str, str]) -> Optional[str]:
    """The positional parameter feeding the fused call's frame slot —
    the first param name appearing inside the first argument of the
    fused-entry call (covers both ``device_resize_frames(x, wy, wx)``
    and the wrapped ``device_resize_frames(shard_seq(stack), ...)``)."""
    params = set(param_names(fn))
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        rd = resolve_dotted(node.func, aliases)
        if rd is not None and rd.split(".")[-1] in _FUSED_ENTRIES and node.args:
            for sub in ast.walk(node.args[0]):
                if isinstance(sub, ast.Name) and sub.id in params:
                    return sub.id
    return None


def _payload_role_findings(src: SourceFile, app: _JitApp,
                           aliases: Dict[str, str],
                           env: Dict[str, str]) -> List[Finding]:
    fn = app.fn
    spec = None
    for kw in app.keywords:
        if kw.arg == "in_shardings" and isinstance(kw.value, (ast.Tuple, ast.List)):
            spec = kw.value
    if spec is None or fn is None:
        return []
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    if len(spec.elts) != len(pos):
        return []  # arity gap — GC502 already owns that finding
    frames = _frames_param(fn, aliases)
    constrained = _body_constrained(fn, aliases)
    out: List[Finding] = []
    for name, elt in zip(pos, spec.elts):
        kind = _spec_kind(elt, env, aliases)
        if name == frames:
            if kind == _REP and not constrained:
                out.append(
                    Finding(
                        src.path, app.line, app.col, RULES["GC504"],
                        f"fused entry {app.name!r} replicates its frame "
                        f"batch {name!r} — the frame axis must shard over "
                        f"'data' or the whole mesh recomputes every clip",
                        "bind the frame input to NamedSharding(mesh, "
                        "P('data')) (fused_payload_shardings gives the "
                        "data/rep pair) or constrain it inside the body "
                        "with with_sharding_constraint",
                    )
                )
        elif kind == _DATA:
            out.append(
                Finding(
                    src.path, app.line, app.col, RULES["GC504"],
                    f"fused entry {app.name!r} shards shape-contract "
                    f"payload {name!r} over 'data' — resample taps, crop "
                    f"offsets and padder grids are per-shape metadata and "
                    f"must replicate",
                    "use P() (the rep half of fused_payload_shardings) for "
                    "every non-frame payload input",
                )
            )
    return out


# --- GC505: admission-list coverage -----------------------------------------


def _eval_strings(expr: ast.AST,
                  consts: Dict[str, List[str]]) -> Optional[List[str]]:
    """Mini-evaluator for the config string-list idiom: literal lists,
    ``A + B`` concatenation, ``list(NAME)`` copies, and names bound to
    earlier string lists. None when any part is dynamic."""
    if isinstance(expr, (ast.List, ast.Tuple)):
        out: List[str] = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    if isinstance(expr, ast.Name):
        return consts.get(expr.id)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _eval_strings(expr.left, consts)
        right = _eval_strings(expr.right, consts)
        if left is not None and right is not None:
            return left + right
        return None
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id == "list"
        and len(expr.args) == 1
        and not expr.keywords
    ):
        return _eval_strings(expr.args[0], consts)
    return None


def _string_consts(src: SourceFile) -> Dict[str, List[str]]:
    consts: Dict[str, List[str]] = {}
    for st in src.tree.body:
        if (
            isinstance(st, ast.Assign)
            and len(st.targets) == 1
            and isinstance(st.targets[0], ast.Name)
        ):
            val = _eval_strings(st.value, consts)
            if val is not None:
                consts[st.targets[0].id] = val
    return consts


def _admitted_types(cfg: SourceFile,
                    consts: Dict[str, List[str]]) -> tuple:
    for st in cfg.tree.body:
        if (
            isinstance(st, ast.Assign)
            and len(st.targets) == 1
            and isinstance(st.targets[0], ast.Name)
            and st.targets[0].id == "MESH_DEVICE_PREPROCESS_FEATURE_TYPES"
        ):
            return _eval_strings(st.value, consts) or [], st.lineno
    return [], 0


def _test_feature_types(test: ast.AST,
                        consts: Dict[str, List[str]]) -> List[str]:
    """Feature strings admitted by one registry dispatch test:
    ``ft == "raft"``, ``ft in CLIP_FEATURE_TYPES``, or an ``or`` of those."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        out: List[str] = []
        for v in test.values:
            out.extend(_test_feature_types(v, consts))
        return out
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        right = test.comparators[0]
        if (
            isinstance(test.ops[0], ast.Eq)
            and isinstance(right, ast.Constant)
            and isinstance(right.value, str)
        ):
            return [right.value]
        if isinstance(test.ops[0], ast.In):
            return _eval_strings(right, consts) or []
    return []


def _registry_modules(reg: SourceFile,
                      consts: Dict[str, List[str]]) -> Dict[str, str]:
    """feature type -> extractor module dotted path, from the lazy-import
    dispatch chain in extract/registry.py."""
    out: Dict[str, str] = {}
    for node in ast.walk(reg.tree):
        if not isinstance(node, ast.If):
            continue
        fts = _test_feature_types(node.test, consts)
        if not fts:
            continue
        mod = None
        for st in node.body:
            if isinstance(st, ast.ImportFrom) and st.module:
                mod = st.module
                break
        if mod is None:
            continue
        for ft in fts:
            out.setdefault(ft, mod)
    return out


def _direct_imports(src: SourceFile, graph: CallGraph) -> List[SourceFile]:
    out: List[SourceFile] = []
    seen = {src.rel}
    for node in ast.walk(src.tree):
        mods: List[str] = []
        if isinstance(node, ast.ImportFrom) and node.module:
            mods.append(node.module)
        elif isinstance(node, ast.Import):
            mods.extend(a.name for a in node.names)
        for m in mods:
            hit = graph.resolve_module(m)
            if hit is not None and hit.rel not in seen:
                seen.add(hit.rel)
                out.append(hit)
    return out


def _module_has_mesh_fused(src: SourceFile, cache: Dict[str, bool]) -> bool:
    hit = cache.get(src.rel)
    if hit is None:
        aliases, apps, _ = _collect(src)
        hit = any(
            app.polarity >= 0
            and app.fn is not None
            and _calls_fused(app.fn, aliases)
            for app in apps
        )
        cache[src.rel] = hit
    return hit


def _check_admission(sources: Sequence[SourceFile],
                     graph: CallGraph) -> List[Finding]:
    by_rel = {s.rel: s for s in sources}
    cfg = by_rel.get("config.py")
    reg = by_rel.get("extract/registry.py")
    if cfg is None or reg is None:
        return []  # single-file run: the admission facts are out of view
    consts = _string_consts(cfg)
    admitted, line = _admitted_types(cfg, consts)
    if not admitted:
        return []
    consts.update(_string_consts(reg))
    mapping = _registry_modules(reg, consts)
    cache: Dict[str, bool] = {}
    findings: List[Finding] = []
    for ft in admitted:
        mod = mapping.get(ft)
        if mod is None:
            continue  # dispatch not statically resolvable — never guess
        target = graph.resolve_module(mod)
        if target is None:
            continue  # extractor module outside this sweep
        if _module_has_mesh_fused(target, cache) or any(
            _module_has_mesh_fused(m, cache)
            for m in _direct_imports(target, graph)
        ):
            continue
        findings.append(
            Finding(
                cfg.path, line, 0, RULES["GC505"],
                f"feature type {ft!r} is admitted for --sharding mesh "
                f"--preprocess device but its extractor module {mod!r} "
                f"declares no mesh-reachable fused jit entry — sanity_check "
                f"would wave through a config the runtime cannot shard",
                "declare in_shardings/out_shardings on the family's fused "
                "entry (see docs/tpu.md) before admitting it, or drop it "
                "from MESH_DEVICE_PREPROCESS_FEATURE_TYPES",
            )
        )
    return findings
