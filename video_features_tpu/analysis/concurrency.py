"""GC31x — concurrency soundness for the threaded serve/extract runtime.

GC301 proves each shared write sits under *a* lock; nothing proved the
locks COMPOSE. The serve daemon is now five lock domains deep (admission
condition, daemon registry, extractor pool, request tracker, breakers),
and the two failure modes GC301 cannot see are exactly the ones that
take a resident daemon down:

- **GC311 lock-order-cycle** — builds a lock-acquisition-order graph
  across the thread roots: an edge ``A -> B`` means some function
  acquires ``B`` (directly, or through a resolvable call chain) while
  holding ``A``. A cycle in that graph is a potential deadlock: two
  threads entering the cycle from different locks wait on each other
  forever. Lock identity is the module-level binding
  (``_lock = threading.Lock()``) or the instance attribute assigned a
  lock constructor in a class body (``self._lock = threading.Lock()``
  -> ``Cls._lock``; all instances share the ordering discipline even
  though each has its own lock object).
- **GC312 blocking-under-lock** — flags blocking calls reachable while
  a lock is held in the hot thread-root modules (serve/ and the
  extract pipeline): untimed ``.get()``/``.join()``/``.wait()``,
  ``time.sleep``, subprocess spawns/waits, file I/O (``open``,
  ``os.replace``...), socket accepts, and device syncs (the GC10x
  facts: ``jax.device_get``/``np.asarray`` on a device-tainted value,
  ``block_until_ready``). A blocking call under a lock turns every
  reader of that lock into a queue behind the slow operation — the
  ``status()``-blocked-behind-a-compile class of bug. The sink/fetch
  boundary allowlist (``fetch_*``/``*sink*``) is shared with GC10x:
  those functions exist to block, and calls INTO them are not
  descended. ``cond.wait()`` while holding only that condition is the
  canonical consumer loop and is exempt (wait releases the lock);
  ``wait(timeout=...)`` is statically timed and always fine.
- **GC313 resource-lifecycle** — non-daemon ``threading.Thread``s in a
  module with no ``.join`` anywhere, ``subprocess.Popen`` neither used
  as a context manager nor reaped (wait/communicate/kill/terminate/
  poll) in its function, and ``f = open(...)`` handles that are never
  closed, returned, stored on ``self`` or entered as a context
  manager. Each is a leak the daemon pays for per request.

Resolution here is deliberately *exact-only* (module functions, import
aliases, ``self.method`` on the caller's own class, plus attribute
names defined exactly once in the project): GC311/GC312 prove the
ABSENCE of a defect with zero waivers, so a by-name fan-out that drags
every ``get`` in the tree into every lock region would bury the real
findings. The cost is under-approximation through dynamic dispatch —
documented, and bounded by keeping lock regions small (the fix GC312
pushes toward anyway).

Findings carry the acquisition/call provenance in ``trace``
(``--explain GC311`` / ``--explain GC312`` print it).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.callgraph import CallGraph, FunctionInfo
from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    import_aliases,
    resolve_dotted,
)
from video_features_tpu.analysis.hostsync import _allowlisted
from video_features_tpu.analysis.taint import _FETCHERS, ProjectTaint
from video_features_tpu.analysis.thread_safety import _LOCK_CALLS

RULES = {
    "GC311": Rule(
        "GC311", "lock-order-cycle",
        "locks are acquired in conflicting orders on concurrent paths — "
        "a potential deadlock",
    ),
    "GC312": Rule(
        "GC312", "blocking-under-lock",
        "a blocking call (untimed wait/join/get, file I/O, subprocess, "
        "device sync) runs while a lock is held on a hot threaded path",
    ),
    "GC313": Rule(
        "GC313", "resource-lifecycle",
        "a thread, subprocess, or file handle is created without a "
        "provable join/reap/close",
    ),
}

# one lock DISCIPLINE: (rel, class-or-None, binding name). Instance locks
# of the same class share an id — every instance must follow one order.
LockId = Tuple[str, Optional[str], str]

_REAP_METHODS = frozenset({"wait", "communicate", "kill", "terminate", "poll"})
_OS_BLOCKING = frozenset(
    {"os.replace", "os.rename", "os.makedirs", "os.remove", "os.unlink",
     "os.listdir", "os.stat", "os.scandir", "os.rmdir", "os.fsync"}
)
_SUBPROCESS_CALLS = frozenset(
    {"subprocess.run", "subprocess.call", "subprocess.check_call",
     "subprocess.check_output", "subprocess.Popen"}
)
_SOCKET_BLOCKING_ATTRS = frozenset({"accept", "recvfrom", "connect_ex"})
_THREAD_CTORS = ("threading.Thread", "Thread")


def _display(lid: LockId) -> str:
    rel, cls, name = lid
    return f"{cls}.{name}" if cls else f"{rel}::{name}"


def _lock_key(lid: LockId) -> Tuple[str, str, str]:
    # LockId's class slot is None for module locks: order with "" so
    # module and instance locks of one file sort deterministically
    rel, cls, name = lid
    return (rel, cls or "", name)


def _own_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Every AST node of a function EXCLUDING nested defs (they run on
    their own schedule — a closure body executes at call time, not while
    the enclosing lock is held)."""
    stack: List[ast.AST] = [fn_node]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if (
                isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                and child is not fn_node
            ):
                continue
            stack.append(child)


class _Locks:
    """Lock identity across the sweep: module-level lock bindings plus
    ``self.<attr> = threading.Lock()``-style instance locks per class."""

    def __init__(self, sources: Sequence[SourceFile]) -> None:
        self.module_locks: Dict[str, Set[str]] = {}
        self.instance_locks: Dict[Tuple[str, str], Set[str]] = {}
        for src in sources:
            aliases = import_aliases(src.tree)
            names: Set[str] = set()
            for st in src.tree.body:
                if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                    if resolve_dotted(st.value.func, aliases) in _LOCK_CALLS:
                        names.update(
                            t.id for t in st.targets if isinstance(t, ast.Name)
                        )
            self.module_locks[src.rel] = names
            for cls in src.tree.body:
                if not isinstance(cls, ast.ClassDef):
                    continue
                attrs: Set[str] = set()
                for node in ast.walk(cls):
                    if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                        if resolve_dotted(node.value.func, aliases) in _LOCK_CALLS:
                            for t in node.targets:
                                if (
                                    isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                ):
                                    attrs.add(t.attr)
                if attrs:
                    self.instance_locks[(src.rel, cls.name)] = attrs

    def classify(
        self, expr: ast.AST, src: SourceFile, info: Optional[FunctionInfo]
    ) -> Optional[LockId]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
            and info is not None
            and info.cls is not None
        ):
            if expr.attr in self.instance_locks.get((src.rel, info.cls), ()):
                return (src.rel, info.cls, expr.attr)
            return None
        dn = dotted_name(expr)
        if dn is not None:
            last = dn.split(".")[-1]
            if last in self.module_locks.get(src.rel, ()):
                return (src.rel, None, last)
        return None


def _exact_callees(
    func: ast.AST, src: SourceFile, info: Optional[FunctionInfo], graph: CallGraph
) -> List[str]:
    """Exact-only callee resolution (taint.py semantics) plus one cheap
    extension: an attribute name defined exactly ONCE in the project is
    unambiguous even through a variable receiver (``b.snapshot()``)."""
    if isinstance(func, ast.Name):
        keys, _ = graph.resolve_call(func, src, info)
        return keys
    if isinstance(func, ast.Attribute):
        aliases = graph._aliases[src.rel]
        rd = resolve_dotted(func.value, aliases)
        if rd is not None:
            m = graph.resolve_module(rd)
            if m is not None:
                hit = graph.module_function(m, func.attr)
                if hit:
                    return [hit]
        if (
            isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
            and info is not None
            and info.cls is not None
        ):
            own = graph.methods_of.get((src.rel, info.cls, func.attr))
            if own:
                return [own]
        hits = graph.by_name.get(func.attr, ())
        if len(hits) == 1:
            return list(hits)
        return []
    if isinstance(func, ast.Call):
        aliases = graph._aliases[src.rel]
        rd = resolve_dotted(func.func, aliases)
        if rd in ("functools.partial", "partial") and func.args:
            return _exact_callees(func.args[0], src, info, graph)
    return []


def _scope_allowlisted(graph: CallGraph, info: FunctionInfo) -> bool:
    cur: Optional[FunctionInfo] = info
    while cur is not None:
        if _allowlisted(cur.name):
            return True
        cur = graph.functions.get(cur.parent) if cur.parent else None
    return False


def _walk_held(info: FunctionInfo, locks: _Locks, visit_call, visit_with=None):
    """Walk a function body tracking the lexically-held lock stack:
    ``visit_with(lock_id, with_node, held)`` fires at each classified
    acquisition, ``visit_call(call_node, held)`` at every call site.
    Nested defs are skipped (their bodies run at call time)."""
    src = info.src

    def walk(node: ast.AST, held: List[Tuple[LockId, int]]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                # context expressions evaluate BEFORE the acquisition
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        visit_call(sub, tuple(inner))
                lid = locks.classify(item.context_expr, src, info)
                if lid is not None:
                    if visit_with is not None:
                        visit_with(lid, node, tuple(inner))
                    inner.append((lid, node.lineno))
            for st in node.body:
                walk(st, inner)
            return
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node is not info.node
        ):
            return
        if isinstance(node, ast.Call):
            visit_call(node, tuple(held))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    walk(info.node, [])


# --- GC311: lock-acquisition-order graph -------------------------------------


class _AcquireClosure:
    """lock ids a function acquires, directly or through exact callees,
    each with a first-witness provenance chain."""

    def __init__(self, graph: CallGraph, locks: _Locks) -> None:
        self.graph = graph
        self.locks = locks
        self.memo: Dict[str, Dict[LockId, Tuple[str, ...]]] = {}

    def of(self, key: str, depth: int = 0) -> Dict[LockId, Tuple[str, ...]]:
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = {}  # cut recursion
        out: Dict[LockId, Tuple[str, ...]] = {}
        info = self.graph.functions.get(key)
        if info is None or depth > 4:
            return out
        src = info.src
        for node in _own_nodes(info.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self.locks.classify(item.context_expr, src, info)
                    if lid is not None and lid not in out:
                        out[lid] = (
                            f"{src.path}:{node.lineno}: {_display(lid)} "
                            f"acquired in {info.name}()",
                        )
            elif isinstance(node, ast.Call):
                for ck in _exact_callees(node.func, src, info, self.graph):
                    for lid, chain in self.of(ck, depth + 1).items():
                        if lid not in out:
                            out[lid] = (
                                f"{src.path}:{node.lineno}: "
                                f"{info.name}() calls the step below",
                            ) + chain
        self.memo[key] = out
        return out


def _check_lock_order(
    sources: Sequence[SourceFile], graph: CallGraph, locks: _Locks
) -> List[Finding]:
    closure = _AcquireClosure(graph, locks)
    # (A, B) -> (path, line, witness trace): B acquired while A held
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, List[str]]] = {}

    for key in sorted(graph.functions):
        info = graph.functions[key]
        if not info.src.is_thread_root:
            continue
        src = info.src

        def visit_with(lid, node, held, info=info, src=src):
            for a, aline in held:
                if a != lid and (a, lid) not in edges:
                    edges[(a, lid)] = (
                        src.path, node.lineno,
                        [
                            f"{src.path}:{aline}: {_display(a)} acquired "
                            f"in {info.name}()",
                            f"{src.path}:{node.lineno}: {_display(lid)} "
                            "acquired while holding it",
                        ],
                    )

        def visit_call(call, held, info=info, src=src):
            if not held:
                return
            for ck in _exact_callees(call.func, src, info, graph):
                for lid, chain in closure.of(ck).items():
                    for a, aline in held:
                        if a != lid and (a, lid) not in edges:
                            edges[(a, lid)] = (
                                src.path, call.lineno,
                                [
                                    f"{src.path}:{aline}: {_display(a)} "
                                    f"acquired in {info.name}()",
                                    f"{src.path}:{call.lineno}: this call "
                                    f"reaches a {_display(lid)} acquisition",
                                    *chain,
                                ],
                            )

        _walk_held(info, locks, visit_call, visit_with)

    findings: List[Finding] = []
    for comp in _cyclic_components(edges):
        in_cycle = sorted(
            (e for e in edges if e[0] in comp and e[1] in comp),
            key=lambda e: (edges[e][0], edges[e][1]),
        )
        if not in_cycle:
            continue
        path, line, _ = edges[in_cycle[0]]
        order = " -> ".join(_display(l) for l in sorted(comp, key=_lock_key)) or "?"
        trace: List[str] = []
        for e in in_cycle:
            trace.extend(edges[e][2])
        findings.append(
            Finding(
                path, line, 0, RULES["GC311"],
                f"lock-order cycle between {order}: these locks are "
                "acquired in conflicting orders on thread-reachable paths",
                "pick ONE global acquisition order for the locks involved "
                "(document it where they are declared) and restructure the "
                "offending path — usually by copying state under the first "
                "lock and calling out after releasing it",
                trace=trace,
            )
        )
    return findings


def _cyclic_components(edges) -> List[Set[LockId]]:
    """Tarjan SCCs of the lock-order graph with more than one node (a
    self-edge cannot occur: same-lock re-acquisition is never recorded)."""
    adj: Dict[LockId, List[LockId]] = {}
    nodes: Set[LockId] = set()
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    stack: List[LockId] = []
    on: Set[LockId] = set()
    out: List[Set[LockId]] = []
    counter = [0]

    def strong(v: LockId) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp: Set[LockId] = set()
            while True:
                w = stack.pop()
                on.discard(w)
                comp.add(w)
                if w == v:
                    break
            if len(comp) > 1:
                out.append(comp)

    for v in sorted(nodes, key=_lock_key):
        if v not in index:
            strong(v)
    return out


# --- GC312: blocking calls while a lock is held ------------------------------


def _blocking_reason(
    call: ast.Call,
    src: SourceFile,
    info: Optional[FunctionInfo],
    locks: _Locks,
    held_ids: Optional[Sequence[LockId]],
    project: ProjectTaint,
    env,
) -> Optional[str]:
    """Why this call blocks, or None. ``held_ids`` is the lexically-held
    lock set at the site (None inside a callee summary, where only the
    callee's OWN condition-wait idiom is exempt)."""
    func = call.func
    aliases = project._aliases.get(src.rel) or import_aliases(src.tree)
    kwnames = {kw.arg for kw in call.keywords if kw.arg}
    if isinstance(func, ast.Attribute):
        if func.attr == "get" and not call.args and not (kwnames & {"timeout", "block"}):
            return "untimed .get()"
        if func.attr == "join" and not call.args and "timeout" not in kwnames:
            return "untimed .join()"
        if func.attr == "wait" and not call.args and "timeout" not in kwnames:
            recv = locks.classify(func.value, src, info)
            if recv is not None:
                if held_ids is None:
                    # callee context: waiting on its own condition is the
                    # canonical consumer loop (wait releases the lock)
                    return None
                if recv in held_ids and len(set(held_ids)) == 1:
                    return None
            return "untimed .wait()"
        if func.attr == "communicate":
            return "subprocess .communicate()"
        if func.attr == "block_until_ready":
            return "device sync (.block_until_ready())"
        if func.attr in _SOCKET_BLOCKING_ATTRS and not call.args:
            return f"socket .{func.attr}()"
    rd = resolve_dotted(func, aliases)
    if rd is None:
        return None
    if rd == "time.sleep":
        return "time.sleep()"
    if rd == "open":
        return "file I/O (open())"
    if rd in _OS_BLOCKING:
        return f"file I/O ({rd}())"
    if rd.split(".")[0] == "shutil":
        return f"file I/O ({rd}())"
    if rd in _SUBPROCESS_CALLS:
        return f"{rd}() spawn/wait"
    if rd == "jax.device_get":
        return "device sync (jax.device_get)"
    if rd in _FETCHERS and call.args:
        t = project.expr_taint(call.args[0], env, src, info)
        if t.device:
            return f"device sync ({rd} on a device value)"
    return None


class _BlockingSites:
    """Blocking sites reachable inside a function (through exact callees,
    bounded depth), each with a provenance chain to the site."""

    def __init__(self, graph: CallGraph, locks: _Locks, project: ProjectTaint) -> None:
        self.graph = graph
        self.locks = locks
        self.project = project
        self.memo: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}

    def of(self, key: str, depth: int = 0) -> List[Tuple[str, Tuple[str, ...]]]:
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = []  # cut recursion
        info = self.graph.functions.get(key)
        if info is None or depth > 3:
            return []
        src = info.src
        env = self.project.env_for(key)
        out: List[Tuple[str, Tuple[str, ...]]] = []
        for node in _own_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            reason = _blocking_reason(
                node, src, info, self.locks, None, self.project, env
            )
            if reason is not None:
                out.append(
                    (reason,
                     (f"{src.path}:{node.lineno}: {reason} in {info.name}()",))
                )
                continue
            for ck in _exact_callees(node.func, src, info, self.graph):
                callee = self.graph.functions.get(ck)
                if callee is None or _scope_allowlisted(self.graph, callee):
                    continue
                for r, chain in self.of(ck, depth + 1):
                    out.append(
                        (r,
                         (f"{src.path}:{node.lineno}: {info.name}() calls "
                          "the step below",) + chain)
                    )
        self.memo[key] = out[:8]  # bound noise per callee
        return self.memo[key]


def _check_blocking(
    sources: Sequence[SourceFile],
    graph: CallGraph,
    locks: _Locks,
    project: ProjectTaint,
) -> List[Finding]:
    findings: List[Finding] = []
    summaries = _BlockingSites(graph, locks, project)
    flagged: Set[Tuple[str, int, str]] = set()

    for key in sorted(graph.functions):
        info = graph.functions[key]
        src = info.src
        if not (src.is_hot and src.is_thread_root):
            continue
        if _scope_allowlisted(graph, info):
            continue
        env = project.env_for(key)

        def visit_call(call, held, info=info, src=src, env=env):
            if not held:
                return
            held_ids = [h[0] for h in held]
            lock, lock_line = held[-1]
            reason = _blocking_reason(
                call, src, info, locks, held_ids, project, env
            )
            if reason is not None:
                sig = (src.path, call.lineno, reason)
                if sig not in flagged:
                    flagged.add(sig)
                    findings.append(
                        Finding(
                            src.path, call.lineno, call.col_offset,
                            RULES["GC312"],
                            f"{reason} while {_display(lock)} is held in "
                            f"{info.name!r}",
                            "move the blocking work outside the lock (copy "
                            "state under the lock, act after releasing it), "
                            "or give the wait a timeout",
                            trace=[
                                f"{src.path}:{lock_line}: {_display(lock)} "
                                "acquired here",
                                f"{src.path}:{call.lineno}: {reason} while "
                                "the lock is held",
                            ],
                        )
                    )
                return
            for ck in _exact_callees(call.func, src, info, graph):
                callee = graph.functions.get(ck)
                if callee is None or _scope_allowlisted(graph, callee):
                    continue
                for r, chain in summaries.of(ck):
                    sig = (src.path, call.lineno, r)
                    if sig in flagged:
                        continue
                    flagged.add(sig)
                    findings.append(
                        Finding(
                            src.path, call.lineno, call.col_offset,
                            RULES["GC312"],
                            f"{r} reachable while {_display(lock)} is held "
                            f"in {info.name!r}",
                            "move the blocking call out of the lock region, "
                            "or restructure the callee so its blocking work "
                            "happens before/after the locked section",
                            trace=[
                                f"{src.path}:{lock_line}: {_display(lock)} "
                                "acquired here",
                                f"{src.path}:{call.lineno}: "
                                f"{callee.name}() called under the lock",
                                *chain,
                            ],
                        )
                    )

        _walk_held(info, locks, visit_call)
    return findings


# --- GC313: resource lifecycle -----------------------------------------------


def _check_lifecycle(
    sources: Sequence[SourceFile], graph: CallGraph
) -> List[Finding]:
    findings: List[Finding] = []
    for src in sources:
        if not src.is_thread_root:
            continue
        aliases = import_aliases(src.tree)
        findings.extend(_thread_lifecycle(src, aliases))
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_handle_lifecycle(node, src, aliases))
    return findings


def _is_thread_ctor(call: ast.Call, aliases) -> bool:
    rd = resolve_dotted(call.func, aliases)
    return rd in _THREAD_CTORS or (rd or "").endswith("threading.Thread")


def _module_has_join(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and len(node.args) <= 1
            and not (resolve_dotted(node.func.value, {}) or "").startswith("os")
        ):
            return True
    return False


def _thread_lifecycle(src: SourceFile, aliases) -> List[Finding]:
    out: List[Finding] = []
    if _module_has_join(src.tree):
        return out
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node, aliases)):
            continue
        daemon = any(
            kw.arg == "daemon"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in node.keywords
        )
        if daemon:
            continue
        out.append(
            Finding(
                src.path, node.lineno, node.col_offset, RULES["GC313"],
                "non-daemon Thread created in a module with no .join() — "
                "shutdown will leave it running",
                "join the thread on the shutdown path, or mark it "
                "daemon=True if abandoning it at exit is the design",
            )
        )
    return out


def _handle_lifecycle(
    fn: ast.FunctionDef, src: SourceFile, aliases
) -> List[Finding]:
    """Popen handles never reaped and open() handles never closed within
    the creating function (conservative: a close/reap/with/return/self-
    store anywhere in the function counts as evidence)."""
    out: List[Finding] = []
    ctx_calls: Set[int] = set()
    method_calls: Dict[str, Set[str]] = {}  # receiver name -> attrs called
    with_names: Set[str] = set()
    returned: Set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    ctx_calls.add(id(item.context_expr))
                if isinstance(item.context_expr, ast.Name):
                    with_names.add(item.context_expr.id)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if isinstance(node.func.value, ast.Name):
                method_calls.setdefault(node.func.value.id, set()).add(
                    node.func.attr
                )
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    returned.add(sub.id)

    for node in _own_nodes(fn):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if id(call) in ctx_calls:
            continue
        rd = resolve_dotted(call.func, aliases)
        kind = None
        if rd == "subprocess.Popen":
            kind = ("subprocess.Popen handle", _REAP_METHODS,
                    "reap it (wait/communicate) in a finally, or use "
                    "`with subprocess.Popen(...) as p:`")
        elif rd == "open":
            kind = ("open() file handle", {"close"},
                    "close it on all paths: `with open(...) as f:` or a "
                    "try/finally close")
        if kind is None:
            continue
        what, evidence, hint = kind
        escapes = False
        targets: List[str] = []
        for t in node.targets:
            if isinstance(t, ast.Attribute):
                escapes = True  # stored on self/obj: lifetime escapes fn
            elif isinstance(t, ast.Name):
                targets.append(t.id)
        if escapes:
            continue
        ok = any(
            n in returned
            or n in with_names
            or (method_calls.get(n, set()) & evidence)
            for n in targets
        )
        if targets and not ok:
            out.append(
                Finding(
                    src.path, node.lineno, node.col_offset, RULES["GC313"],
                    f"{what} {targets[0]!r} in {fn.name!r} is neither "
                    "closed/reaped, returned, nor a context manager",
                    hint,
                )
            )
    return out


# --- entry -------------------------------------------------------------------


def check(
    sources: Sequence[SourceFile], graph: CallGraph, project: ProjectTaint
) -> List[Finding]:
    locks = _Locks(sources)
    findings: List[Finding] = []
    findings.extend(_check_lock_order(sources, graph, locks))
    findings.extend(_check_blocking(sources, graph, locks, project))
    findings.extend(_check_lifecycle(sources, graph))
    return findings
