"""GC301 — thread-safety lint for module-level mutable state.

The runtime is threads all the way down (one host thread per device,
``--decode_workers`` prepare pools, native preprocess threads), so any
module-level mutable binding written from a function is a data race
UNLESS the write is provably serialized. v1 accepted exactly one proof —
a lexical ``with <module lock>:`` around the write — and everything else
needed a ``# graftcheck: unlocked`` waiver. v2 resolves three more
shapes through the project call graph (``callgraph.py``):

- **decorator locks**: ``@synchronized`` where the decorator resolves to
  a project def whose body takes a module lock around the wrapped call;
- **contextmanager helpers**: ``with locked():`` where ``locked`` is a
  ``@contextlib.contextmanager`` def whose body holds a lock across its
  ``yield``;
- **guarded callers**: every resolved call site of the writing function
  sits inside a ``with <lock>`` in its caller (the classic private
  ``_unlocked_append`` helper);
- **thread reachability**: a function NOT reachable from any thread
  entry (``Thread(target=...)``, ``pool.submit``, timers) never races —
  config-set-once setters called only from ``__init__`` before workers
  exist are exempt by *analysis*, not by waiver. Files carrying the
  ``# graftcheck: thread-root`` marker but no visible spawn site treat
  every def as an entry (the fixture contract).

Findings carry the entry-to-write reachability chain in ``trace``
(``--explain GC301`` prints it).

Scope: modules *reachable from the thread roots* (import graph, both
directions — see core.THREAD_ROOT_PATTERNS). Import-time writes (module
body statements) are exempt: the import lock serializes them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.callgraph import CallGraph, FunctionInfo
from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    import_aliases,
    resolve_dotted,
)

RULE = Rule(
    "GC301", "unlocked-global",
    "module-level mutable state written without a lock on a thread-reachable path",
)

_LOCK_CALLS = frozenset(
    {"threading.Lock", "threading.RLock", "threading.Condition",
     "threading.Semaphore", "threading.BoundedSemaphore",
     "multiprocessing.Lock", "multiprocessing.RLock"}
)
_LOCAL_CALLS = frozenset({"threading.local"})
_MUTABLE_CALLS = frozenset(
    {"list", "dict", "set", "collections.defaultdict", "defaultdict",
     "collections.deque", "deque", "collections.Counter", "Counter",
     "collections.OrderedDict", "OrderedDict", "bytearray"}
)
_MUTATING_METHODS = frozenset(
    {"append", "extend", "insert", "update", "add", "setdefault", "pop",
     "popitem", "clear", "remove", "discard"}
)
_CONTEXTMANAGER = ("contextlib.contextmanager", "contextmanager")


class _ModuleInfo:
    def __init__(self, src: SourceFile) -> None:
        self.src = src
        self.aliases = import_aliases(src.tree)
        self.imports = self._imported_modules()
        self.locks, self.locals_, self.mutables = self._module_bindings()

    def _imported_modules(self) -> Set[str]:
        mods: Set[str] = set()
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mods.add(a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module:
                    mods.add(node.module)
                    for a in node.names:
                        # "from pkg.io import sink" imports module pkg.io.sink
                        mods.add(f"{node.module}.{a.name}")
        return mods

    def _module_bindings(self) -> Tuple[Set[str], Set[str], Set[str]]:
        locks: Set[str] = set()
        locals_: Set[str] = set()
        mutables: Set[str] = set()
        for st in self.src.tree.body:
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(st, ast.Assign):
                targets, value = st.targets, st.value
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                targets, value = [st.target], st.value
            else:
                continue
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names or value is None:
                continue
            if isinstance(value, ast.Call):
                callee = resolve_dotted(value.func, self.aliases)
                if callee in _LOCK_CALLS:
                    locks.update(names)
                    continue
                if callee in _LOCAL_CALLS:
                    locals_.update(names)
                    continue
                if callee in _MUTABLE_CALLS:
                    mutables.update(names)
                    continue
            if isinstance(
                value,
                (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp),
            ):
                mutables.update(names)
        return locks, locals_, mutables


def _module_candidates(info: _ModuleInfo) -> Set[str]:
    """Dotted-name suffixes this module answers to, so imports match
    whether written package-absolute or tested from a fixture dir."""
    name = info.src.module_name
    out = {name}
    parts = name.split(".")
    for i in range(1, len(parts)):
        out.add(".".join(parts[i:]))
    if parts[-1] == "__init__":
        pkg = ".".join(parts[:-1])
        if pkg:
            out.add(pkg)
            pp = pkg.split(".")
            for i in range(1, len(pp)):
                out.add(".".join(pp[i:]))
    return out


class _LockResolver:
    """Answers "does this ``with``/decorator/caller hold a lock?" through
    the call graph: lexical locks, @contextmanager lock helpers, lock
    decorators, and per-call-site lock context for guarded callers."""

    def __init__(self, infos: Sequence[_ModuleInfo], graph: CallGraph) -> None:
        self.graph = graph
        self.by_src = {info.src.rel: info for info in infos}
        self._cm_cache: Dict[str, bool] = {}
        self._dec_cache: Dict[str, bool] = {}
        self._guarded_sites: Dict[str, Set[int]] = {}

    # -- lock-expression classification --------------------------------------

    def is_lock_expr(self, expr: ast.AST, src: SourceFile,
                     caller: Optional[FunctionInfo]) -> bool:
        info = self.by_src.get(src.rel)
        lock_names = info.locks if info else set()
        dn = dotted_name(expr)
        if dn is not None:
            head = dn.split(".")[0]
            # Name('_lock'), or conservative: any dotted chain ending in a
            # module-level lock name (cls._lock) or containing 'lock'
            if (
                head in lock_names
                or dn.split(".")[-1] in lock_names
                or "lock" in dn.split(".")[-1].lower()
            ):
                return True
        if isinstance(expr, ast.Call):
            # ``with locked():`` — a @contextmanager helper that holds a
            # module lock across its yield counts as taking that lock
            callees, _ = self.graph.resolve_call(expr.func, src, caller)
            return any(self._cm_lock_helper(k) for k in callees)
        return False

    def _cm_lock_helper(self, key: str) -> bool:
        if key in self._cm_cache:
            return self._cm_cache[key]
        self._cm_cache[key] = False  # cut recursion
        fn = self.graph.functions.get(key)
        ok = False
        if fn is not None and self._is_contextmanager(fn):
            ok = self._contains_lock_with(fn)
        self._cm_cache[key] = ok
        return ok

    def _is_contextmanager(self, fn: FunctionInfo) -> bool:
        aliases = self.by_src.get(fn.src.rel)
        aliases = aliases.aliases if aliases else {}
        for dec in fn.node.decorator_list:
            if resolve_dotted(dec, aliases) in _CONTEXTMANAGER:
                return True
        return False

    def _contains_lock_with(self, fn: FunctionInfo) -> bool:
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(
                    self.is_lock_expr(item.context_expr, fn.src, fn)
                    for item in node.items
                ):
                    return True
        return False

    # -- decorator locks -----------------------------------------------------

    def decorator_locked(self, fn_node: ast.FunctionDef,
                         src: SourceFile) -> bool:
        """A decorator that resolves to a project def whose body takes a
        lock (the @synchronized wrapper pattern) serializes every call."""
        for dec in fn_node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            callees, _ = self.graph.resolve_call(target, src, None)
            for k in callees:
                if self._decorator_lock(k):
                    return True
        return False

    def _decorator_lock(self, key: str) -> bool:
        if key in self._dec_cache:
            return self._dec_cache[key]
        self._dec_cache[key] = False
        fn = self.graph.functions.get(key)
        ok = fn is not None and self._contains_lock_with(fn)
        self._dec_cache[key] = ok
        return ok

    # -- guarded callers -----------------------------------------------------

    def _locked_call_ids(self, caller_key: str) -> Set[int]:
        """ids of Call nodes lexically under a lock inside ``caller``."""
        if caller_key in self._guarded_sites:
            return self._guarded_sites[caller_key]
        out: Set[int] = set()
        fn = self.graph.functions.get(caller_key)
        if fn is not None:
            def walk(node: ast.AST, locked: bool) -> None:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    locked = locked or any(
                        self.is_lock_expr(item.context_expr, fn.src, fn)
                        for item in node.items
                    )
                if locked and isinstance(node, ast.Call):
                    out.add(id(node))
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        continue
                    walk(child, locked)

            walk(fn.node, self.decorator_locked(fn.node, fn.src))
        self._guarded_sites[caller_key] = out
        return out

    def all_callers_locked(self, key: str) -> bool:
        """True when the function is only ever entered with a lock held:
        every resolved call site sits under a ``with <lock>`` in its
        caller (module-body call sites are import-time, serialized by the
        import lock). Spawn targets and ``__call__`` (reachable through
        unresolvable bare calls) never qualify."""
        fn = self.graph.functions.get(key)
        if fn is None:
            return False
        if key in self.graph.thread_entries():
            return False
        if fn.name == "__call__" and self.graph.unresolved_callers:
            return False
        sites = self.graph.callers.get(key, [])
        if not sites:
            return False
        for site in sites:
            if site.caller.endswith("::"):
                continue  # module body: import lock serializes
            if id(site.node) not in self._locked_call_ids(site.caller):
                return False
        return True


def check(sources: Sequence[SourceFile], graph: CallGraph) -> List[Finding]:
    infos = [_ModuleInfo(s) for s in sources]
    by_suffix: Dict[str, _ModuleInfo] = {}
    for info in infos:
        for cand in _module_candidates(info):
            by_suffix.setdefault(cand, info)

    def resolve_import(mod: str) -> Optional[_ModuleInfo]:
        # longest-suffix match: "video_features_tpu.io.sink" and "io.sink"
        # both land on io/sink.py
        parts = mod.split(".")
        for i in range(len(parts)):
            hit = by_suffix.get(".".join(parts[i:]))
            if hit is not None:
                return hit
        return None

    # edges in both directions of interest
    imports_of: Dict[int, Set[int]] = {}
    for idx, info in enumerate(infos):
        tgt: Set[int] = set()
        for mod in info.imports:
            hit = resolve_import(mod)
            if hit is not None and hit is not info:
                tgt.add(infos.index(hit))
        imports_of[idx] = tgt

    roots = {i for i, info in enumerate(infos) if info.src.is_thread_root}
    # (1) everything the roots call into
    reachable = set(roots)
    frontier = set(roots)
    while frontier:
        nxt = set()
        for i in frontier:
            nxt |= imports_of[i] - reachable
        reachable |= nxt
        frontier = nxt
    # (2) modules that run on the threads by importing a root (extractor
    # subclasses etc.), closed over THEIR imports too
    importers = {
        i for i in range(len(infos)) if imports_of[i] & roots
    }
    frontier = importers - reachable
    reachable |= importers
    while frontier:
        nxt = set()
        for i in frontier:
            nxt |= imports_of[i] - reachable
        reachable |= nxt
        frontier = nxt

    resolver = _LockResolver(infos, graph)
    thread_side = graph.thread_side()
    findings: List[Finding] = []
    for i in sorted(reachable):
        findings.extend(_check_module(infos[i], graph, resolver, thread_side))
    return findings


def _chain_trace(
    graph: CallGraph, chain: Tuple[str, ...]
) -> List[str]:
    out = []
    for j, key in enumerate(chain):
        fn = graph.functions.get(key)
        if fn is None:
            continue
        what = "thread entry" if j == 0 else "called from the step above"
        out.append(f"{fn.src.path}:{fn.node.lineno}: {fn.name}() — {what}")
    return out


def _check_module(
    info: _ModuleInfo,
    graph: CallGraph,
    resolver: _LockResolver,
    thread_side: Dict[str, Tuple[str, ...]],
) -> List[Finding]:
    src = info.src
    findings: List[Finding] = []
    module_names = info.mutables | {
        n
        for fn in _functions(src.tree)
        for n in _global_decls(fn)
    }
    if not module_names and not info.mutables:
        return findings

    for fn in _functions(src.tree):
        globals_here = _global_decls(fn)
        watched = (info.mutables | globals_here) - info.locals_
        if not watched:
            continue
        key = graph.key_of(fn)
        fn_info = graph.functions.get(key) if key else None
        chain = thread_side.get(key) if key else None
        if key is not None and chain is None:
            # interprocedural exemption #1: not reachable from any thread
            # entry — an init-only / config-set-once path cannot race
            continue
        if resolver.decorator_locked(fn, src):
            # interprocedural exemption #2: a lock-wrapping decorator
            # serializes every call of this function
            continue
        callers_locked: Optional[bool] = None  # lazy: costs graph walks
        for write_line, write_col, name, how, guarded in _writes(
            fn, watched, globals_here, info, resolver, fn_info
        ):
            if guarded:
                continue
            if callers_locked is None:
                # interprocedural exemption #3: every resolved call site
                # of this function already holds a lock
                callers_locked = (
                    resolver.all_callers_locked(key) if key else False
                )
            if callers_locked:
                break
            findings.append(
                Finding(
                    src.path, write_line, write_col, RULE,
                    f"{how} of module-level {name!r} in {fn.name!r} without "
                    f"holding a module lock",
                    "guard with `with <module lock>:` (directly, via a "
                    "@contextmanager helper, a lock decorator, or in every "
                    "caller), make it threading.local(), or waive with "
                    "`# graftcheck: unlocked — <why it is safe>`",
                    trace=_chain_trace(graph, chain) if chain else [],
                )
            )
    return findings


def _functions(tree: ast.AST) -> List[ast.FunctionDef]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def _global_decls(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _writes(
    fn,
    watched: Set[str],
    globals_here: Set[str],
    info: _ModuleInfo,
    resolver: _LockResolver,
    fn_info: Optional[FunctionInfo],
):
    """(line, col, name, kind, guarded) for every write to a watched
    module-level name in ``fn``. Guarded = lexically inside a ``with``
    over a module-level lock or a @contextmanager lock helper."""
    src = info.src

    def walk(node: ast.AST, under_lock: bool):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locked = under_lock or any(
                resolver.is_lock_expr(item.context_expr, src, fn_info)
                for item in node.items
            )
            for st in node.body:
                yield from walk(st, locked)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            # nested defs: visited by _functions in their own right; their
            # lock context comes from their call sites (guarded callers)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                yield from _target_writes(t, node, under_lock)
        elif isinstance(node, ast.AugAssign):
            yield from _target_writes(node.target, node, under_lock)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            yield from _target_writes(node.target, node, under_lock)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (
                node.func.attr in _MUTATING_METHODS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in watched
            ):
                yield (
                    node.lineno, node.col_offset, node.func.value.id,
                    f".{node.func.attr}() mutation", under_lock,
                )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from walk(child, under_lock)

    def _target_writes(t: ast.AST, node: ast.AST, under_lock: bool):
        if isinstance(t, ast.Name):
            # a plain rebind counts only when the name is module-global
            # here (declared ``global``); otherwise it's a local shadow
            if t.id in globals_here and t.id in watched | globals_here:
                yield (node.lineno, node.col_offset, t.id, "rebind", under_lock)
        elif isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
            if t.value.id in watched:
                yield (
                    node.lineno, node.col_offset, t.value.id,
                    "item assignment", under_lock,
                )
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                yield from _target_writes(el, node, under_lock)

    for st in fn.body:
        yield from walk(st, False)
