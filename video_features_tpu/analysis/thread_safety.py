"""GC301 — thread-safety lint for module-level mutable state.

The runtime is threads all the way down (one host thread per device,
``--decode_workers`` prepare pools, native preprocess threads), so any
module-level mutable binding written from a function is a data race
UNLESS the write is (a) inside a ``with <lock>`` where the lock is a
module-level ``threading.Lock/RLock/Condition``, (b) the binding is
``threading.local()``, or (c) the line carries an explicit
``# graftcheck: unlocked`` waiver stating why the race is benign (e.g.
config-set-once before any worker thread exists).

Scope: modules *reachable from the thread roots* — the six modules that
spawn or run on worker threads (core.THREAD_ROOT_PATTERNS) — where
"reachable" is the union of (1) modules the roots transitively import
(code the threads call into) and (2) modules that transitively import a
root (extractors subclass ``extract.base`` and their methods run ON the
worker threads), closed over imports again. Import-time writes (module
body statements) are exempt: the import lock serializes them.

Read-only module tables (``CONFIGS``, ``WEIGHT_FILES``) never trip the
rule — only names written from function bodies are considered state.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    import_aliases,
    resolve_dotted,
)

RULE = Rule(
    "GC301", "unlocked-global",
    "module-level mutable state written without a lock on a thread-reachable path",
)

_LOCK_CALLS = frozenset(
    {"threading.Lock", "threading.RLock", "threading.Condition",
     "threading.Semaphore", "threading.BoundedSemaphore",
     "multiprocessing.Lock", "multiprocessing.RLock"}
)
_LOCAL_CALLS = frozenset({"threading.local"})
_MUTABLE_CALLS = frozenset(
    {"list", "dict", "set", "collections.defaultdict", "defaultdict",
     "collections.deque", "deque", "collections.Counter", "Counter",
     "collections.OrderedDict", "OrderedDict", "bytearray"}
)
_MUTATING_METHODS = frozenset(
    {"append", "extend", "insert", "update", "add", "setdefault", "pop",
     "popitem", "clear", "remove", "discard"}
)


class _ModuleInfo:
    def __init__(self, src: SourceFile) -> None:
        self.src = src
        self.aliases = import_aliases(src.tree)
        self.imports = self._imported_modules()
        self.locks, self.locals_, self.mutables = self._module_bindings()

    def _imported_modules(self) -> Set[str]:
        mods: Set[str] = set()
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mods.add(a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module:
                    mods.add(node.module)
                    for a in node.names:
                        # "from pkg.io import sink" imports module pkg.io.sink
                        mods.add(f"{node.module}.{a.name}")
        return mods

    def _module_bindings(self) -> Tuple[Set[str], Set[str], Set[str]]:
        locks: Set[str] = set()
        locals_: Set[str] = set()
        mutables: Set[str] = set()
        for st in self.src.tree.body:
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(st, ast.Assign):
                targets, value = st.targets, st.value
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                targets, value = [st.target], st.value
            else:
                continue
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names or value is None:
                continue
            if isinstance(value, ast.Call):
                callee = resolve_dotted(value.func, self.aliases)
                if callee in _LOCK_CALLS:
                    locks.update(names)
                    continue
                if callee in _LOCAL_CALLS:
                    locals_.update(names)
                    continue
                if callee in _MUTABLE_CALLS:
                    mutables.update(names)
                    continue
            if isinstance(
                value,
                (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp),
            ):
                mutables.update(names)
        return locks, locals_, mutables


def _module_candidates(info: _ModuleInfo) -> Set[str]:
    """Dotted-name suffixes this module answers to, so imports match
    whether written package-absolute or tested from a fixture dir."""
    name = info.src.module_name
    out = {name}
    parts = name.split(".")
    for i in range(1, len(parts)):
        out.add(".".join(parts[i:]))
    if parts[-1] == "__init__":
        pkg = ".".join(parts[:-1])
        if pkg:
            out.add(pkg)
            pp = pkg.split(".")
            for i in range(1, len(pp)):
                out.add(".".join(pp[i:]))
    return out


def check(sources: Sequence[SourceFile]) -> List[Finding]:
    infos = [_ModuleInfo(s) for s in sources]
    by_suffix: Dict[str, _ModuleInfo] = {}
    for info in infos:
        for cand in _module_candidates(info):
            by_suffix.setdefault(cand, info)

    def resolve_import(mod: str) -> Optional[_ModuleInfo]:
        # longest-suffix match: "video_features_tpu.io.sink" and "io.sink"
        # both land on io/sink.py
        parts = mod.split(".")
        for i in range(len(parts)):
            hit = by_suffix.get(".".join(parts[i:]))
            if hit is not None:
                return hit
        return None

    # edges in both directions of interest
    imports_of: Dict[int, Set[int]] = {}
    for idx, info in enumerate(infos):
        tgt: Set[int] = set()
        for mod in info.imports:
            hit = resolve_import(mod)
            if hit is not None and hit is not info:
                tgt.add(infos.index(hit))
        imports_of[idx] = tgt

    roots = {i for i, info in enumerate(infos) if info.src.is_thread_root}
    # (1) everything the roots call into
    reachable = set(roots)
    frontier = set(roots)
    while frontier:
        nxt = set()
        for i in frontier:
            nxt |= imports_of[i] - reachable
        reachable |= nxt
        frontier = nxt
    # (2) modules that run on the threads by importing a root (extractor
    # subclasses etc.), closed over THEIR imports too
    importers = {
        i for i in range(len(infos)) if imports_of[i] & roots
    }
    frontier = importers - reachable
    reachable |= importers
    while frontier:
        nxt = set()
        for i in frontier:
            nxt |= imports_of[i] - reachable
        reachable |= nxt
        frontier = nxt

    findings: List[Finding] = []
    for i in sorted(reachable):
        findings.extend(_check_module(infos[i]))
    return findings


def _check_module(info: _ModuleInfo) -> List[Finding]:
    src = info.src
    findings: List[Finding] = []
    module_names = info.mutables | {
        n
        for fn in _functions(src.tree)
        for n in _global_decls(fn)
    }
    if not module_names and not info.mutables:
        return findings

    for fn in _functions(src.tree):
        globals_here = _global_decls(fn)
        watched = (info.mutables | globals_here) - info.locals_
        if not watched:
            continue
        for write_line, write_col, name, how, guarded in _writes(
            fn, watched, globals_here, info
        ):
            if guarded:
                continue
            findings.append(
                Finding(
                    src.path, write_line, write_col, RULE,
                    f"{how} of module-level {name!r} in {fn.name!r} without "
                    f"holding a module lock",
                    "guard with `with <module lock>:`, make it threading.local(), "
                    "or waive with `# graftcheck: unlocked — <why it is safe>`",
                )
            )
    return findings


def _functions(tree: ast.AST) -> List[ast.FunctionDef]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def _global_decls(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _writes(fn, watched: Set[str], globals_here: Set[str], info: _ModuleInfo):
    """(line, col, name, kind, guarded) for every write to a watched
    module-level name in ``fn``. Guarded = lexically inside a ``with``
    over a module-level lock."""
    lock_names = info.locks

    def walk(node: ast.AST, under_lock: bool):
        if isinstance(node, ast.With):
            locked = under_lock or any(
                _is_lock_expr(item.context_expr, lock_names)
                for item in node.items
            )
            for st in node.body:
                walk(st, locked)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            # nested defs: globals they declare are checked when _functions
            # visits them; their lock context is their call site's, which
            # is unknowable statically — treat as unguarded there.
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                yield from _target_writes(t, node, under_lock)
        elif isinstance(node, ast.AugAssign):
            yield from _target_writes(node.target, node, under_lock)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            yield from _target_writes(node.target, node, under_lock)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (
                node.func.attr in _MUTATING_METHODS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in watched
            ):
                yield (
                    node.lineno, node.col_offset, node.func.value.id,
                    f".{node.func.attr}() mutation", under_lock,
                )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from walk(child, under_lock)

    def _target_writes(t: ast.AST, node: ast.AST, under_lock: bool):
        if isinstance(t, ast.Name):
            # a plain rebind counts only when the name is module-global
            # here (declared ``global``); otherwise it's a local shadow
            if t.id in globals_here and t.id in watched | globals_here:
                yield (node.lineno, node.col_offset, t.id, "rebind", under_lock)
        elif isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
            if t.value.id in watched:
                yield (
                    node.lineno, node.col_offset, t.value.id,
                    "item assignment", under_lock,
                )
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                yield from _target_writes(el, node, under_lock)

    for st in fn.body:
        yield from walk(st, False)


def _is_lock_expr(expr: ast.AST, lock_names: Set[str]) -> bool:
    dn = dotted_name(expr)
    if dn is None:
        return False
    head = dn.split(".")[0]
    # Name('_lock'), or conservative: any dotted chain ending in a
    # module-level lock name (cls._lock) or containing 'lock'
    return (
        head in lock_names
        or dn.split(".")[-1] in lock_names
        or "lock" in dn.split(".")[-1].lower()
    )
