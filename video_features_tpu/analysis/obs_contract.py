"""GC70x — observability contracts: every signal is real, end to end.

The serving story leans on three cross-module naming contracts that
nothing enforced statically:

- **GC701 metric-exposition-contract** — registry series names
  (``metrics.inc("frames_decoded")``, ``set_gauge(f"queue_depth.{q}")``)
  must map onto a curated exposition family in
  ``telemetry/exposition.py::families_from_snapshot`` — matched against
  the conventions that function itself encodes (``name.startswith(...)``
  prefixes, ``name == ...`` exacts, ``name in _PLAIN_*`` tables). A name
  that only hits the sanitized fallback renders with auto-generated
  HELP/TYPE — /metrics shows it, but no dashboard was ever told it
  exists. The reverse direction is checked too: a convention with no
  producer anywhere in the sweep is an orphaned family (dead dashboards,
  or a producer renamed out from under them). Producers resolve through
  constant strings, f-strings with constant heads, name-building helpers
  (``group_service_metric``) and single-registry-call forwarders
  (``self._count("requests_admitted")``).
- **GC702 fault-stage-contract** — every constant-stage ``fire("...")``
  site must name a stage declared in ``runtime/faults.py::STAGES``, and
  every declared stage must have at least one fire site: a dead stage
  rots the chaos matrix (drills "cover" a stage no code path can hit).
- **GC703 config-flag-contract** — ``config.py``: every ``add_argument``
  dest is a field of some config dataclass (or consumed by a module
  function), every field is settable (a flag dest, or an explicit
  constructor kwarg in a parse wrapper), every free-form flag (no
  ``choices``, no non-str ``type``, not boolean) is touched by a
  ``sanity_check*`` function, and every attribute a sanity function
  touches is a real field — the typo direction.

All three are pure-AST and cross-module: a contract side missing from
the sweep (running graftcheck on a subdirectory without exposition.py /
faults.py / config.py) skips that rule rather than reporting one-sided
orphans. Findings carry the contract's defining line in ``trace``
(``--explain GC701``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from video_features_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    dotted_name,
    import_aliases,
    resolve_dotted,
)

RULES = {
    "GC701": Rule(
        "GC701", "metric-exposition-contract",
        "a registry metric name maps to no curated exposition family "
        "(sanitized-fallback HELP/TYPE), or a family has no producer",
    ),
    "GC702": Rule(
        "GC702", "fault-stage-contract",
        "a fire() site uses an undeclared fault stage, or a declared "
        "stage has no fire site (dead chaos coverage)",
    ),
    "GC703": Rule(
        "GC703", "config-flag-contract",
        "an argparse flag, config dataclass field, and sanity check "
        "disagree: orphan flag/field, unvalidated free-form flag, or a "
        "sanity touch on a non-field",
    ),
}

_REGISTRY_METHODS = ("inc", "set_gauge", "observe")


# -- name specs ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Spec:
    """A statically-known metric name: exact, or a constant prefix of an
    f-string (``f"stage_s.{stage}"`` -> prefix ``stage_s.``)."""

    text: str
    is_prefix: bool

    def matches_token(self, token: str, token_is_prefix: bool) -> bool:
        if not self.is_prefix and not token_is_prefix:
            return self.text == token
        if not self.is_prefix:  # exact name vs prefix convention
            return token_is_prefix and self.text.startswith(token)
        if not token_is_prefix:  # prefix producer vs exact convention
            return token.startswith(self.text)
        return self.text.startswith(token) or token.startswith(self.text)


def _spec_of(expr: ast.AST) -> Optional[_Spec]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return _Spec(expr.value, False)
    if isinstance(expr, ast.JoinedStr) and expr.values:
        head = expr.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            if len(expr.values) == 1:
                return _Spec(head.value, False)
            return _Spec(head.value, True)
    return None


def _return_spec(fn: ast.FunctionDef) -> Optional[_Spec]:
    """The spec of a helper that builds metric names: a single constant
    or constant-headed f-string return."""
    specs = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            specs.append(_spec_of(node.value))
    live = [s for s in specs if s is not None]
    return live[0] if len(live) == len(specs) == 1 else None


# -- GC701 ---------------------------------------------------------------


def _find_exposition(sources: Sequence[SourceFile]) -> Optional[
    Tuple[SourceFile, ast.FunctionDef]
]:
    for src in sources:
        for node in src.tree.body:
            if isinstance(node, ast.FunctionDef) and node.name == "families_from_snapshot":
                return src, node
    return None


def _module_str_collections(src: SourceFile) -> Dict[str, List[Tuple[str, int]]]:
    """Module-level ``NAME = {...}/(...)`` literals of string keys, for
    ``name in _PLAIN_COUNTERS`` membership conventions."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for st in src.tree.body:
        if not (isinstance(st, ast.Assign) and len(st.targets) == 1):
            continue
        target = st.targets[0]
        if not isinstance(target, ast.Name):
            continue
        keys: List[Tuple[str, int]] = []
        if isinstance(st.value, ast.Dict):
            elts = st.value.keys
        elif isinstance(st.value, (ast.Set, ast.Tuple, ast.List)):
            elts = st.value.elts
        else:
            continue
        for el in elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                keys.append((el.value, el.lineno))
        if keys:
            out[target.id] = keys
    return out


def _conventions(
    src: SourceFile, fn: ast.FunctionDef
) -> List[Tuple[str, bool, int]]:
    """(token, is_prefix, defining line) for every naming convention the
    exposition mapper encodes — startswith prefixes, == exacts, and
    membership in a module-level string table."""
    tables = _module_str_collections(src)
    out: List[Tuple[str, bool, int]] = []
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "startswith"
            and isinstance(node.func.value, ast.Name)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.append((node.args[0].value, True, node.lineno))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1:
            left, op, right = node.left, node.ops[0], node.comparators[0]
            if not isinstance(left, ast.Name):
                continue
            if isinstance(op, ast.Eq) and isinstance(right, ast.Constant) and isinstance(right.value, str):
                out.append((right.value, False, node.lineno))
            elif isinstance(op, ast.In) and isinstance(right, ast.Name):
                for key, line in tables.get(right.id, ()):
                    out.append((key, False, line))
    return out


def _receiver_text(func: ast.Attribute) -> str:
    parts: List[str] = []
    node: ast.AST = func.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(parts)


def _name_helpers(sources: Sequence[SourceFile]) -> Dict[str, _Spec]:
    """Project functions (unique by bare name) whose return is a metric
    name spec — ``group_service_metric`` style builders."""
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for src in sources:
        for node in src.tree.body:
            if isinstance(node, ast.FunctionDef):
                defs.setdefault(node.name, []).append(node)
    out: Dict[str, _Spec] = {}
    for name, fns in defs.items():
        if len(fns) != 1:
            continue
        spec = _return_spec(fns[0])
        if spec is not None:
            out[name] = spec
    return out


def _forwarders(sources: Sequence[SourceFile]) -> Dict[str, int]:
    """Functions whose body forwards a parameter straight into a registry
    call (``def _count(self, name): ...metrics.inc(name)``): bare name ->
    positional index of the forwarded parameter at the call site."""
    defs: Dict[str, List[Tuple[ast.FunctionDef, int]]] = {}
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            params = [a.arg for a in node.args.args]
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _REGISTRY_METHODS
                    and sub.args
                    and isinstance(sub.args[0], ast.Name)
                    and sub.args[0].id in params
                ):
                    idx = params.index(sub.args[0].id)
                    if params[:1] == ["self"]:
                        idx -= 1
                    if idx >= 0:
                        defs.setdefault(node.name, []).append((node, idx))
    return {
        name: hits[0][1] for name, hits in defs.items() if len(hits) == 1
    }


def _check_metrics(sources: Sequence[SourceFile]) -> List[Finding]:
    hit = _find_exposition(sources)
    if hit is None:
        return []
    expo_src, expo_fn = hit
    conventions = _conventions(expo_src, expo_fn)
    if not conventions:
        return []
    helpers = _name_helpers(sources)
    forwarders = _forwarders(sources)

    producers: List[Tuple[_Spec, SourceFile, ast.Call]] = []
    for src in sources:
        if src.rel.startswith("analysis/"):
            continue
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            spec: Optional[_Spec] = None
            if node.func.attr in _REGISTRY_METHODS and node.args:
                if (
                    node.func.attr == "observe"
                    and "metrics" not in _receiver_text(node.func)
                ):
                    continue  # .observe() on a non-registry object
                arg = node.args[0]
                spec = _spec_of(arg)
                if spec is None and isinstance(arg, ast.Call):
                    inner = arg.func
                    iname = inner.attr if isinstance(inner, ast.Attribute) else (
                        inner.id if isinstance(inner, ast.Name) else None
                    )
                    if iname is not None:
                        spec = helpers.get(iname)
            else:
                fname = node.func.attr
                if fname in forwarders:
                    idx = forwarders[fname]
                    if idx < len(node.args):
                        spec = _spec_of(node.args[idx])
            if spec is not None:
                producers.append((spec, src, node))

    findings: List[Finding] = []
    for spec, src, node in producers:
        if src is expo_src:
            continue  # the mapper's own branches are not producers
        if not any(spec.matches_token(t, p) for t, p, _ in conventions):
            shown = f"{spec.text}*" if spec.is_prefix else spec.text
            findings.append(
                Finding(
                    src.path, node.lineno, node.col_offset, RULES["GC701"],
                    f"metric {shown!r} maps to no exposition family — "
                    "/metrics renders it through the sanitized fallback "
                    "with auto-generated HELP/TYPE",
                    "add a family convention for it in telemetry/"
                    "exposition.py families_from_snapshot (a _PLAIN_* "
                    "entry with real HELP text, or a labelled prefix "
                    "branch), or rename the series into an existing family",
                    trace=[
                        f"{expo_src.path}:{expo_fn.lineno}: conventions "
                        "extracted from families_from_snapshot",
                    ],
                )
            )
    if producers:
        for token, is_prefix, line in conventions:
            if not any(
                s.matches_token(token, is_prefix) for s, psrc, _ in producers
                if psrc is not expo_src
            ):
                shown = f"{token}*" if is_prefix else token
                findings.append(
                    Finding(
                        expo_src.path, line, 0, RULES["GC701"],
                        f"exposition family convention {shown!r} has no "
                        "producer anywhere in the sweep — an orphaned "
                        "family (dashboards chart a series nothing emits)",
                        "delete the dead branch, or wire the producer that "
                        "was renamed out from under it",
                    )
                )
    return findings


# -- GC702 ---------------------------------------------------------------


def _find_stages(sources: Sequence[SourceFile]) -> Optional[
    Tuple[SourceFile, ast.Assign, List[str]]
]:
    for src in sources:
        for st in src.tree.body:
            if (
                isinstance(st, ast.Assign)
                and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and st.targets[0].id == "STAGES"
                and isinstance(st.value, (ast.Tuple, ast.List))
            ):
                stages = [
                    el.value for el in st.value.elts
                    if isinstance(el, ast.Constant) and isinstance(el.value, str)
                ]
                if stages:
                    return src, st, stages
    return None


def _check_stages(sources: Sequence[SourceFile]) -> List[Finding]:
    hit = _find_stages(sources)
    if hit is None:
        return []
    stages_src, assign, stages = hit
    declared = set(stages)
    fired: Set[str] = set()
    findings: List[Finding] = []
    for src in sources:
        if src.rel.startswith("analysis/"):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fname = None
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname != "fire":
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                continue
            stage = arg.value
            fired.add(stage)
            if stage not in declared:
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, RULES["GC702"],
                        f"fire({stage!r}) uses a stage not declared in "
                        "STAGES — --fault_inject can never drill it and "
                        "parse-time validation rejects it",
                        "declare the stage in runtime/faults.py STAGES (and "
                        "give it chaos-drill coverage), or use an existing "
                        "stage name",
                        trace=[
                            f"{stages_src.path}:{assign.lineno}: STAGES "
                            "declared here",
                        ],
                    )
                )
    if fired:
        for stage in stages:
            if stage not in fired:
                findings.append(
                    Finding(
                        stages_src.path, assign.lineno, assign.col_offset,
                        RULES["GC702"],
                        f"stage {stage!r} is declared in STAGES but has no "
                        "fire() site — the chaos matrix claims coverage no "
                        "code path can hit",
                        "remove the dead stage, or add the fire() site at "
                        "the boundary it is supposed to drill",
                    )
                )
    return findings


# -- GC703 ---------------------------------------------------------------


@dataclasses.dataclass
class _Flag:
    flag: str
    dest: str
    node: ast.Call
    validated: bool  # parser-side constraint: choices / bool / non-str type


def _dataclass_defs(src: SourceFile, aliases) -> Dict[str, ast.ClassDef]:
    out: Dict[str, ast.ClassDef] = {}
    for st in src.tree.body:
        if not isinstance(st, ast.ClassDef):
            continue
        for dec in st.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            rd = resolve_dotted(target, aliases)
            if rd in ("dataclasses.dataclass", "dataclass"):
                out[st.name] = st
                break
    return out


def _class_fields(cls: ast.ClassDef) -> Dict[str, int]:
    fields: Dict[str, int] = {}
    for st in cls.body:
        if isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name):
            fields[st.target.id] = st.lineno
    return fields


def _flags_of(src: SourceFile) -> List[_Flag]:
    out: List[_Flag] = []
    for node in ast.walk(src.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("--")
        ):
            continue
        flag = node.args[0].value
        dest = flag[2:].replace("-", "_")
        validated = False
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = str(kw.value.value)
            elif kw.arg == "choices":
                validated = True
            elif kw.arg == "action" and isinstance(kw.value, ast.Constant):
                if kw.value.value in ("store_true", "store_false", "count"):
                    validated = True
            elif kw.arg == "type":
                tname = dotted_name(kw.value)
                if tname is not None and tname != "str":
                    validated = True
        out.append(_Flag(flag, dest, node, validated))
    return out


def _check_config(sources: Sequence[SourceFile]) -> List[Finding]:
    src = next(
        (s for s in sources if s.rel.rsplit("/", 1)[-1] == "config.py"), None
    )
    if src is None:
        return []
    aliases = import_aliases(src.tree)
    dclasses = _dataclass_defs(src, aliases)
    flags = _flags_of(src)
    if not dclasses or not flags:
        return []

    all_fields: Dict[str, int] = {}
    methods: Set[str] = {"replace"}  # dataclasses.replace idiom
    for cls in dclasses.values():
        all_fields.update(_class_fields(cls))
        methods.update(
            st.name for st in cls.body
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))
        )

    # attribute reads on any local/param name inside module functions —
    # the "consumed somewhere" evidence for leg (a)
    referenced: Set[str] = set()
    # attrs touched on the first param of sanity_check* functions, with
    # witness lines for the typo leg (d)
    sanity_touched: Dict[str, int] = {}
    ctor_kwargs: Set[str] = set()
    for fn in ast.walk(src.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        params = [a.arg for a in fn.args.args]
        sanity_param = (
            params[0] if fn.name.startswith("sanity_check") and params else None
        )
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
                referenced.add(node.attr)
                if sanity_param is not None and node.value.id == sanity_param:
                    sanity_touched.setdefault(node.attr, node.lineno)
            elif isinstance(node, ast.Call):
                cname = None
                if isinstance(node.func, ast.Name):
                    cname = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    cname = node.func.attr
                rd = resolve_dotted(node.func, aliases)
                if cname in dclasses or rd in ("dataclasses.replace",):
                    ctor_kwargs.update(
                        kw.arg for kw in node.keywords if kw.arg
                    )

    findings: List[Finding] = []
    dests = {f.dest for f in flags}
    for f in flags:
        if f.dest not in all_fields and f.dest not in referenced:
            findings.append(
                Finding(
                    src.path, f.node.lineno, f.node.col_offset, RULES["GC703"],
                    f"flag {f.flag} parses into dest {f.dest!r}, which is "
                    "neither a config dataclass field nor consumed by any "
                    "function in config.py — a flag users can set that "
                    "goes nowhere",
                    "add the matching dataclass field (and a sanity touch), "
                    "or delete the dead flag",
                )
            )
        elif f.dest in all_fields and not f.validated and f.dest not in sanity_touched:
            findings.append(
                Finding(
                    src.path, f.node.lineno, f.node.col_offset, RULES["GC703"],
                    f"free-form flag {f.flag} has no parser-side constraint "
                    "(choices/type/boolean action) and no sanity_check "
                    "touch — any junk value flows straight into the run",
                    "validate it in the sanity_check covering its dataclass "
                    "(even an empty-string/format guard), or constrain it "
                    "at the parser",
                )
            )
    for field, line in sorted(all_fields.items()):
        if field not in dests and field not in ctor_kwargs:
            findings.append(
                Finding(
                    src.path, line, 0, RULES["GC703"],
                    f"dataclass field {field!r} is neither any flag's dest "
                    "nor explicitly constructed in a parse wrapper — it "
                    "can never be set from the CLI",
                    "add the --flag for it, or construct it explicitly in "
                    "the parse wrapper so the wiring is visible",
                )
            )
    for attr, line in sorted(sanity_touched.items()):
        if attr not in all_fields and attr not in methods:
            findings.append(
                Finding(
                    src.path, line, 0, RULES["GC703"],
                    f"sanity check reads cfg.{attr}, which is not a field "
                    "or method of any config dataclass — a typo that makes "
                    "the check always crash or never run",
                    "fix the attribute name to the real field",
                )
            )
    return findings


# -- entry ---------------------------------------------------------------


def check(sources: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(_check_metrics(sources))
    findings.extend(_check_stages(sources))
    findings.extend(_check_config(sources))
    return findings
