// Native host-side preprocessing: the decode->model gap of the frame
// pipeline (resize / center-crop / normalize / layout), threaded across
// frames. This is the TPU-native counterpart of the native transform
// code the reference rides inside PIL/mmcv/torchvision (SURVEY.md §2
// component 3/14) — the host CPUs must keep 8 chips fed, and per-frame
// Python/PIL calls are the bottleneck (SURVEY.md §7 hard part #5).
//
// Resize follows PIL's convolution-based BILINEAR: triangle filter whose
// support scales with the downsampling ratio (antialiased), half-pixel
// centers, computed in float (PIL quantizes coefficients to 8-bit fixed
// point, so outputs match PIL within ~1/255 per pixel — the native path
// is an opt-in throughput mode, --host_preprocess native).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread (see native/__init__.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Tap {
    int lo;          // first source index
    int n;           // number of taps
    int coeff_off;   // offset into the coefficient array
};

// filter kernels, PIL semantics: 0 = BILINEAR (triangle, support 1),
// 1 = BICUBIC (Keys a=-0.5, support 2)
double filter_weight(int filter, double x) {
    x = std::abs(x);
    if (filter == 1) {
        const double a = -0.5;
        if (x < 1.0) return ((a + 2.0) * x - (a + 3.0)) * x * x + 1.0;
        if (x < 2.0) return (((x - 5.0) * x + 8.0) * x - 4.0) * a;
        return 0.0;
    }
    return x < 1.0 ? 1.0 - x : 0.0;
}

// PIL-style antialiased filter taps for size in -> out (support scales
// with the downsampling ratio, half-pixel centers).
void build_taps(int in_size, int out_size, int filter, std::vector<Tap>& taps,
                std::vector<float>& coeffs) {
    const double scale = static_cast<double>(in_size) / out_size;
    const double fscale = scale < 1.0 ? 1.0 : scale;
    const double support = (filter == 1 ? 2.0 : 1.0) * fscale;
    taps.resize(out_size);
    coeffs.clear();
    for (int i = 0; i < out_size; ++i) {
        const double center = (i + 0.5) * scale;
        int lo = static_cast<int>(std::floor(center - support + 0.5));
        int hi = static_cast<int>(std::floor(center + support + 0.5));
        lo = std::max(lo, 0);
        hi = std::min(hi, in_size);
        Tap t{lo, hi - lo, static_cast<int>(coeffs.size())};
        double total = 0.0;
        for (int j = lo; j < hi; ++j) {
            const double w = filter_weight(filter, (j + 0.5 - center) / fscale);
            coeffs.push_back(static_cast<float>(w));
            total += w;
        }
        if (total != 0.0) {
            for (int j = 0; j < t.n; ++j)
                coeffs[t.coeff_off + j] /= static_cast<float>(total);
        }
        taps[i] = t;
    }
}

// PIL rounds + clips to uint8 BETWEEN the separable passes and after the
// final one (ImagingResample's 8bpc path) — with bicubic's negative
// lobes the clipping is visible at hard edges, so parity requires
// quantizing exactly where PIL does.
inline float quant8(float v) {
    return std::min(255.0f, std::max(0.0f, std::nearbyint(v)));
}

// Resize one HWC uint8 frame to (oh, ow) float HWC via separable passes.
void resize_frame(const uint8_t* src, int h, int w, float* dst, int oh, int ow,
                  const std::vector<Tap>& ytaps, const std::vector<float>& ycoef,
                  const std::vector<Tap>& xtaps, const std::vector<float>& xcoef,
                  float* tmp /* h * ow * 3 */) {
    // horizontal pass: (h, w, 3) u8 -> (h, ow, 3) f32
    for (int y = 0; y < h; ++y) {
        const uint8_t* row = src + static_cast<size_t>(y) * w * 3;
        float* trow = tmp + static_cast<size_t>(y) * ow * 3;
        for (int x = 0; x < ow; ++x) {
            const Tap& t = xtaps[x];
            float acc[3] = {0.f, 0.f, 0.f};
            for (int k = 0; k < t.n; ++k) {
                const float c = xcoef[t.coeff_off + k];
                const uint8_t* p = row + static_cast<size_t>(t.lo + k) * 3;
                acc[0] += c * p[0];
                acc[1] += c * p[1];
                acc[2] += c * p[2];
            }
            float* o = trow + static_cast<size_t>(x) * 3;
            o[0] = quant8(acc[0]); o[1] = quant8(acc[1]); o[2] = quant8(acc[2]);
        }
    }
    // vertical pass: (h, ow, 3) -> (oh, ow, 3)
    for (int y = 0; y < oh; ++y) {
        const Tap& t = ytaps[y];
        float* orow = dst + static_cast<size_t>(y) * ow * 3;
        std::memset(orow, 0, sizeof(float) * ow * 3);
        for (int k = 0; k < t.n; ++k) {
            const float c = ycoef[t.coeff_off + k];
            const float* trow = tmp + static_cast<size_t>(t.lo + k) * ow * 3;
            for (int i = 0; i < ow * 3; ++i) orow[i] += c * trow[i];
        }
        for (int i = 0; i < ow * 3; ++i) orow[i] = quant8(orow[i]);
    }
}

}  // namespace

namespace {

// Shared chain for a batch of same-sized frames: resize smaller edge ->
// resize_to (aspect kept, `filter` kernel), center-crop crop x crop,
// /255, normalize (mean/std per channel), emit NCHW float32.
void preprocess_batch_impl(const uint8_t* src, int n, int h, int w,
                           int resize_to, int crop, int filter,
                           const float* mean, const float* stddev,
                           float* out, int threads) {
    int oh, ow;
    if (h <= w) {
        oh = resize_to;
        ow = static_cast<int>(static_cast<int64_t>(resize_to) * w / h);
    } else {
        ow = resize_to;
        oh = static_cast<int>(static_cast<int64_t>(resize_to) * h / w);
    }
    std::vector<Tap> ytaps, xtaps;
    std::vector<float> ycoef, xcoef;
    build_taps(h, oh, filter, ytaps, ycoef);
    build_taps(w, ow, filter, xtaps, xcoef);

    // round-half-to-even, matching Python round() in the PIL chain
    const int top = static_cast<int>(std::nearbyint((oh - crop) / 2.0));
    const int left = static_cast<int>(std::nearbyint((ow - crop) / 2.0));
    const float inv255 = 1.0f / 255.0f;

    auto work = [&](int begin, int end) {
        std::vector<float> resized(static_cast<size_t>(oh) * ow * 3);
        std::vector<float> tmp(static_cast<size_t>(h) * ow * 3);
        for (int f = begin; f < end; ++f) {
            resize_frame(src + static_cast<size_t>(f) * h * w * 3, h, w,
                         resized.data(), oh, ow, ytaps, ycoef, xtaps, xcoef,
                         tmp.data());
            float* o = out + static_cast<size_t>(f) * 3 * crop * crop;
            for (int c = 0; c < 3; ++c) {
                const float m = mean[c], inv_s = 1.0f / stddev[c];
                float* oc = o + static_cast<size_t>(c) * crop * crop;
                for (int y = 0; y < crop; ++y) {
                    const float* r =
                        resized.data() +
                        (static_cast<size_t>(top + y) * ow + left) * 3 + c;
                    for (int x = 0; x < crop; ++x)
                        oc[static_cast<size_t>(y) * crop + x] =
                            (r[static_cast<size_t>(x) * 3] * inv255 - m) * inv_s;
                }
            }
        }
    };

    threads = std::max(1, std::min(threads, n));
    if (threads == 1) {
        work(0, n);
        return;
    }
    std::vector<std::thread> pool;
    const int per = (n + threads - 1) / threads;
    for (int t = 0; t < threads; ++t) {
        const int b = t * per, e = std::min(n, b + per);
        if (b < e) pool.emplace_back(work, b, e);
    }
    for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// torchvision chain (ResNet family): BILINEAR resize.
void imagenet_preprocess_batch(const uint8_t* src, int n, int h, int w,
                               int resize_to, int crop,
                               const float* mean, const float* stddev,
                               float* out, int threads) {
    preprocess_batch_impl(src, n, h, w, resize_to, crop, /*filter=*/0, mean,
                          stddev, out, threads);
}

// CLIP chain (pip `clip` preprocess): BICUBIC resize of the smaller edge
// straight to the crop size, then the same crop/normalize.
void clip_preprocess_batch(const uint8_t* src, int n, int h, int w, int size,
                           const float* mean, const float* stddev, float* out,
                           int threads) {
    preprocess_batch_impl(src, n, h, w, size, size, /*filter=*/1, mean, stddev,
                          out, threads);
}

}  // extern "C"
