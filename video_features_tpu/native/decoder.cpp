// Native video decode loader: libavformat demux -> libavcodec decode ->
// libswscale RGB24, exposed through a C ABI for ctypes (no pybind11 in
// the image). This is the framework's own data-loader — the reference
// rides the native decoders inside mmcv/cv2 (SURVEY.md §2 component 3,
// L3 layer); here the loop itself is ours, which buys one structural
// win cv2's read() cannot offer: grab/retrieve separation at the C
// level, so frames a sampler skips are decoded but never color-converted
// (uni_12 over a 120-frame clip converts 12 frames, not 120).
//
// Sequential-exact by construction (frame counter increments per decoded
// frame, like cv2's sequential read). Random access stays with the
// Python cv2 seek path — pts->index mapping is container-dependent and
// the sparse case is rare (io/video.py's 1-in-16 crossover).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 decoder.cpp
//        -lavformat -lavcodec -lswscale -lavutil  (see native/__init__.py)

extern "C" {
#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/display.h>
#include <libavutil/imgutils.h>
#include <libswscale/swscale.h>
}

#include <cstdint>
#include <cstring>

namespace {

struct VfDec {
    AVFormatContext* fmt = nullptr;
    AVCodecContext* dec = nullptr;
    SwsContext* sws = nullptr;
    AVPacket* pkt = nullptr;
    AVFrame* frame = nullptr;
    int stream = -1;
    int w = 0, h = 0;
    double fps = 0.0;
    int64_t nframes = 0;   // container estimate; 0 when unknown
    int64_t index = -1;    // index of the frame currently held
    bool draining = false;
    bool have_frame = false;
};

void vf_free(VfDec* d) {
    if (!d) return;
    if (d->sws) sws_freeContext(d->sws);
    if (d->frame) av_frame_free(&d->frame);
    if (d->pkt) av_packet_free(&d->pkt);
    if (d->dec) avcodec_free_context(&d->dec);
    if (d->fmt) avformat_close_input(&d->fmt);
    delete d;
}

// Pull the next decoded frame into d->frame. Returns 1 on success, 0 at
// end of stream, <0 on error.
int vf_next_frame(VfDec* d) {
    while (true) {
        int r = avcodec_receive_frame(d->dec, d->frame);
        if (r == 0) return 1;
        if (r == AVERROR_EOF) return 0;
        if (r != AVERROR(EAGAIN)) return r;
        if (d->draining) return 0;
        while (true) {
            r = av_read_frame(d->fmt, d->pkt);
            if (r == AVERROR_EOF) {
                d->draining = true;
                avcodec_send_packet(d->dec, nullptr);  // flush
                break;
            }
            if (r < 0) return r;
            const bool ours = d->pkt->stream_index == d->stream;
            if (ours) r = avcodec_send_packet(d->dec, d->pkt);
            av_packet_unref(d->pkt);
            if (ours) {
                if (r < 0 && r != AVERROR(EAGAIN)) return r;
                break;
            }
        }
    }
}

}  // namespace

extern "C" {

void* vfdec_open(const char* path) {
    auto* d = new VfDec();
    if (avformat_open_input(&d->fmt, path, nullptr, nullptr) < 0) {
        vf_free(d);
        return nullptr;
    }
    if (avformat_find_stream_info(d->fmt, nullptr) < 0) {
        vf_free(d);
        return nullptr;
    }
    const AVCodec* codec = nullptr;
    d->stream =
        av_find_best_stream(d->fmt, AVMEDIA_TYPE_VIDEO, -1, -1, &codec, 0);
    if (d->stream < 0 || !codec) {
        vf_free(d);
        return nullptr;
    }
    AVStream* st = d->fmt->streams[d->stream];
    d->dec = avcodec_alloc_context3(codec);
    if (!d->dec || avcodec_parameters_to_context(d->dec, st->codecpar) < 0 ||
        avcodec_open2(d->dec, codec, nullptr) < 0) {
        vf_free(d);
        return nullptr;
    }
    d->pkt = av_packet_alloc();
    d->frame = av_frame_alloc();
    d->w = d->dec->width;
    d->h = d->dec->height;
    AVRational r = st->avg_frame_rate.num ? st->avg_frame_rate : st->r_frame_rate;
    d->fps = r.den ? static_cast<double>(r.num) / r.den : 0.0;
    d->nframes = st->nb_frames;
    if (d->nframes == 0 && d->fps > 0.0) {
        // containers without per-stream counts (MKV/WebM): estimate from
        // duration x fps, the same arithmetic cv2's CAP_PROP_FRAME_COUNT
        // uses for them
        if (st->duration > 0) {
            d->nframes = llround(st->duration * av_q2d(st->time_base) * d->fps);
        } else if (d->fmt->duration > 0) {
            d->nframes = llround(
                d->fmt->duration / static_cast<double>(AV_TIME_BASE) * d->fps);
        }
    }
    // Rotated streams (display-matrix side data): cv2 auto-rotates them,
    // this loader does not — refuse to open so the 'auto' backend falls
    // back to cv2 instead of silently decoding a different orientation.
    if (const uint8_t* sd = av_stream_get_side_data(
            st, AV_PKT_DATA_DISPLAYMATRIX, nullptr)) {
        const double theta =
            av_display_rotation_get(reinterpret_cast<const int32_t*>(sd));
        if (theta == theta && theta != 0.0) {  // non-NaN, non-zero
            vf_free(d);
            return nullptr;
        }
    }
    if (!d->pkt || !d->frame || d->w <= 0 || d->h <= 0) {
        vf_free(d);
        return nullptr;
    }
    return d;
}

void vfdec_probe(void* h, int* w, int* ht, double* fps, int64_t* nframes) {
    auto* d = static_cast<VfDec*>(h);
    *w = d->w;
    *ht = d->h;
    *fps = d->fps;
    *nframes = d->nframes;
}

// Advance to the next frame WITHOUT color conversion.
// Returns the new frame index, or -1 at end of stream / error.
int64_t vfdec_grab(void* h) {
    auto* d = static_cast<VfDec*>(h);
    int r = vf_next_frame(d);
    if (r != 1) {
        d->have_frame = false;
        return -1;
    }
    d->have_frame = true;
    return ++d->index;
}

// Convert the currently-held frame to packed RGB24 into out (h*w*3).
// Returns 0 on success, -1 if no frame is held or conversion fails.
int vfdec_retrieve(void* h, uint8_t* out) {
    auto* d = static_cast<VfDec*>(h);
    if (!d->have_frame) return -1;
    d->sws = sws_getCachedContext(
        d->sws, d->frame->width, d->frame->height,
        static_cast<AVPixelFormat>(d->frame->format), d->w, d->h,
        AV_PIX_FMT_RGB24, SWS_BILINEAR, nullptr, nullptr, nullptr);
    if (!d->sws) return -1;
    uint8_t* dst[4] = {out, nullptr, nullptr, nullptr};
    int stride[4] = {3 * d->w, 0, 0, 0};
    const int rows = sws_scale(d->sws, d->frame->data, d->frame->linesize, 0,
                               d->frame->height, dst, stride);
    return rows == d->h ? 0 : -1;
}

void vfdec_close(void* h) { vf_free(static_cast<VfDec*>(h)); }

}  // extern "C"
