"""Native (C++) host-side components: build-on-demand + ctypes bindings.

The shared library compiles once per machine into ``native/_build/`` with
plain g++ (no pybind11 in the image; the C ABI + ctypes is the binding
layer). Everything degrades gracefully: ``available()`` is False when no
toolchain exists and callers fall back to the PIL/numpy path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "preprocess.cpp")
_BUILD_DIR = os.path.join(_DIR, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libvfpreproc.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _compile_lib(src: str, out_path: str, extra: Sequence[str] = ()) -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # compile to a per-pid temp and rename: concurrent processes may race
    # on the shared output path, and dlopen of a half-written .so would
    # poison this process's native path for the whole run
    tmp_out = f"{out_path}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        src, "-o", tmp_out, *extra,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            return proc.stderr[-2000:]
        os.replace(tmp_out, out_path)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"{type(e).__name__}: {e}"
    finally:
        if os.path.exists(tmp_out):
            try:
                os.remove(tmp_out)
            except OSError:
                pass
    return None


def _compile() -> Optional[str]:
    return _compile_lib(_SRC, _LIB_PATH)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
        ):
            err = _compile()
            if err is not None:
                _build_error = err
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            _build_error = str(e)
            return None
        lib.imagenet_preprocess_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.imagenet_preprocess_batch.restype = None
        lib.clip_preprocess_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.clip_preprocess_batch.restype = None
        _lib = lib
        return _lib


def cpu_budget() -> int:
    """Cores this process may actually run on: the scheduler affinity
    mask when available (containers often pin it below os.cpu_count()),
    else os.cpu_count()."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):  # non-Linux
        return max(os.cpu_count() or 1, 1)


def _resolve_threads(threads: int) -> int:
    """Worker count for the C++ batch chains. ctypes already drops the
    GIL for the whole call and preprocess.cpp fans the frame batch out
    over std::thread — so extra threads only help while spare cores
    exist. BENCH_r05 measured 2/4 requested threads SLOWER than 1 on a
    1-core host (259/254 vs 260 fps): pure context-switch overhead. The
    knob was dead weight there, so every request — including explicit
    ones — clamps to the affinity-visible core count; <=0 keeps the
    auto default (all cores, capped at 16)."""
    budget = cpu_budget()
    if threads <= 0:
        return min(budget, 16)
    return min(threads, budget)


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


def imagenet_preprocess_batch(
    frames: np.ndarray,
    resize_to: int = 256,
    crop: int = 224,
    mean: Sequence[float] = (0.485, 0.456, 0.406),
    std: Sequence[float] = (0.229, 0.224, 0.225),
    threads: int = 0,
) -> np.ndarray:
    """(N, H, W, 3) uint8 frames -> (N, 3, crop, crop) float32 via the
    threaded C++ chain (near-PIL antialiased resize; see preprocess.cpp)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native preprocess unavailable: {_build_error}")
    frames = np.ascontiguousarray(frames, dtype=np.uint8)
    if frames.ndim != 4 or frames.shape[-1] != 3:
        raise ValueError(f"expected (N, H, W, 3) uint8, got {frames.shape}")
    n, h, w, _ = frames.shape
    if min(h, w) < 1 or crop < 1 or resize_to < crop:
        raise ValueError(f"bad sizes: frame {h}x{w}, resize {resize_to}, crop {crop}")
    out = np.empty((n, 3, crop, crop), np.float32)
    mean_a = np.ascontiguousarray(mean, np.float32)
    std_a = np.ascontiguousarray(std, np.float32)
    threads = _resolve_threads(threads)
    lib.imagenet_preprocess_batch(
        frames.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, h, w, resize_to, crop,
        mean_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        threads,
    )
    return out


def clip_preprocess_batch(
    frames: np.ndarray,
    size: int = 224,
    mean: Sequence[float] = (0.48145466, 0.4578275, 0.40821073),
    std: Sequence[float] = (0.26862954, 0.26130258, 0.27577711),
    threads: int = 0,
) -> np.ndarray:
    """(N, H, W, 3) uint8 frames -> (N, 3, size, size) float32 via the
    CLIP chain (BICUBIC smaller-edge resize, center crop, CLIP normalize;
    within ~1/255 per pixel of the pip ``clip`` package's PIL preprocess)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native preprocess unavailable: {_build_error}")
    frames = np.ascontiguousarray(frames, dtype=np.uint8)
    if frames.ndim != 4 or frames.shape[-1] != 3:
        raise ValueError(f"expected (N, H, W, 3) uint8, got {frames.shape}")
    n, h, w, _ = frames.shape
    if min(h, w) < 1 or size < 1:
        raise ValueError(f"bad sizes: frame {h}x{w}, size {size}")
    out = np.empty((n, 3, size, size), np.float32)
    mean_a = np.ascontiguousarray(mean, np.float32)
    std_a = np.ascontiguousarray(std, np.float32)
    threads = _resolve_threads(threads)
    lib.clip_preprocess_batch(
        frames.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, h, w, size,
        mean_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std_a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        threads,
    )
    return out


# --- native video decode loader (decoder.cpp) ------------------------------
#
# Separate .so with its own graceful availability: it links libavformat/
# libavcodec/libswscale, which may be absent on some hosts even when the
# C++ toolchain (and so the preprocess library) is fine.

_DEC_SRC = os.path.join(_DIR, "decoder.cpp")
_DEC_LIB_PATH = os.path.join(_BUILD_DIR, "libvfdecode.so")
_dec_lib: Optional[ctypes.CDLL] = None
_dec_build_error: Optional[str] = None


def _load_decoder() -> Optional[ctypes.CDLL]:
    global _dec_lib, _dec_build_error
    with _lock:
        if _dec_lib is not None or _dec_build_error is not None:
            return _dec_lib
        if not os.path.exists(_DEC_LIB_PATH) or (
            os.path.getmtime(_DEC_LIB_PATH) < os.path.getmtime(_DEC_SRC)
        ):
            err = _compile_lib(
                _DEC_SRC, _DEC_LIB_PATH,
                extra=["-lavformat", "-lavcodec", "-lswscale", "-lavutil"],
            )
            if err is not None:
                _dec_build_error = err
                return None
        try:
            lib = ctypes.CDLL(_DEC_LIB_PATH)
        except OSError as e:
            _dec_build_error = str(e)
            return None
        lib.vfdec_open.argtypes = [ctypes.c_char_p]
        lib.vfdec_open.restype = ctypes.c_void_p
        lib.vfdec_probe.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.vfdec_probe.restype = None
        lib.vfdec_grab.argtypes = [ctypes.c_void_p]
        lib.vfdec_grab.restype = ctypes.c_int64
        lib.vfdec_retrieve.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)
        ]
        lib.vfdec_retrieve.restype = ctypes.c_int
        lib.vfdec_close.argtypes = [ctypes.c_void_p]
        lib.vfdec_close.restype = None
        _dec_lib = lib
        return _dec_lib


def decoder_available() -> bool:
    return _load_decoder() is not None


def decoder_build_error() -> Optional[str]:
    _load_decoder()
    return _dec_build_error


class NativeVideoReader:
    """Sequential RGB frame reader over the C decode loader.

    ``grab()`` advances one frame WITHOUT color conversion (returns the
    new frame index or -1 at end); ``retrieve()`` converts the held frame
    to an (H, W, 3) RGB uint8 array. Samplers that skip frames pay decode
    cost only — no swscale pass — for the frames they drop, which cv2's
    ``read()`` cannot avoid."""

    def __init__(self, path: str) -> None:
        lib = _load_decoder()
        if lib is None:
            raise RuntimeError(f"native decoder unavailable: {_dec_build_error}")
        self._lib = lib
        self._h = lib.vfdec_open(os.fsencode(path))
        if not self._h:
            raise IOError(f"native decoder could not open {path}")
        w = ctypes.c_int()
        h = ctypes.c_int()
        fps = ctypes.c_double()
        n = ctypes.c_int64()
        lib.vfdec_probe(self._h, ctypes.byref(w), ctypes.byref(h),
                        ctypes.byref(fps), ctypes.byref(n))
        self.width, self.height = w.value, h.value
        self.fps = fps.value or None
        self.frame_count = n.value or None  # container estimate; may be None

    def grab(self) -> int:
        return int(self._lib.vfdec_grab(self._h))

    def retrieve(self) -> np.ndarray:
        out = np.empty((self.height, self.width, 3), np.uint8)
        r = self._lib.vfdec_retrieve(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        )
        if r != 0:
            raise IOError("native decoder retrieve failed")
        return out

    def read(self) -> Optional[np.ndarray]:
        """cv2-style: next frame as RGB, or None at end of stream."""
        if self.grab() < 0:
            return None
        return self.retrieve()

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.vfdec_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort; close() is the real contract
        try:
            self.close()
        except Exception:
            pass
