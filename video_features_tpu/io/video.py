"""Video decode + frame sampling on the host CPU.

The reference uses four decode backends (mmcv, cv2 streaming, torchvision
read_video, ffmpeg re-encode — SURVEY.md §1 L3). Here there is ONE reader
abstraction with two interchangeable backends:

- ``cv2`` — OpenCV's ``VideoCapture`` (decodes BGR; flipped to RGB once
  per retrieved frame),
- ``native`` — the framework's own C++ decode loader
  (native/decoder.cpp: libavformat/libavcodec/libswscale via ctypes),
  which converts straight to RGB24 — no BGR round trip.

``--decoder`` picks: 'auto' (default) uses the native loader when its
library builds, 'cv2'/'native' force one. Both decode the same bitstream
through libavcodec, so frames are bit-identical (tests/test_native.py).
Samplers drop frames with ``grab()`` (decode, no color conversion) and
pay ``retrieve()`` only for frames they keep.

Wrapped in:

- :func:`stream_frames` — a generator for frame-wise extractors (the
  cv2 streaming loop of ref models/resnet/extract_resnet.py:121-156),
- :func:`read_all_frames` — whole-clip decode for stack-wise extractors
  (ref models/r21d/extract_r21d.py:102, models/i3d/extract_i3d.py:239-259),
- :func:`extract_frames` — the ``fix_N`` / ``uni_N`` samplers
  (ref utils/utils.py:297-333).

fps re-targeting is done in-process by nearest-timestamp frame selection
instead of an ffmpeg re-encode subprocess (ref utils/utils.py:222-244);
if an ffmpeg binary exists it can still be used via io.ffmpeg.

Note: the reference computes ``mspf = 0.001 / fps`` (ref
utils/utils.py:312) which is a unit bug; the correct milliseconds-per-frame
``1000 / fps`` is used here (matching upstream v-iashin/video_features).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import cv2
import numpy as np

from video_features_tpu.io.probe import MIN_SANE_FPS, NO_CAPS, ResourceCaps
from video_features_tpu.runtime import faults
from video_features_tpu.runtime import telemetry
from video_features_tpu.runtime.faults import (
    CorruptVideoError,
    DecodeTimeout,
    ResourceCapExceeded,
)

_DECODER = "auto"  # 'auto' | 'cv2' | 'native'; set once from the config
_DECODE_TIMEOUT: Optional[float] = None  # seconds per reader; set from the config
_RESOURCE_CAPS: ResourceCaps = NO_CAPS  # --max_pixels etc.; set from the config
# shared-decode frame cache (extract/plan.py::SharedFrameCache) for
# multi-model fan-out: when installed, probe/extract_frames/
# stream_frames serve decoded frames from it instead of opening a
# reader — duck-typed (.acquire(path, decoder) -> clip or None) so io
# keeps zero extract imports
_FRAME_CACHE = None
# BaseExtractor.__init__ sets the timeout, and the serve daemon builds
# extractors from its dispatcher thread — rebinds must hold this lock
_CONFIG_LOCK = threading.Lock()

# decode warnings (fps defaulted, partial decode) accumulate per THREAD:
# readers are constructed deep inside samplers with no manifest in
# reach, and prepare() runs one video per decode-worker thread at a
# time, so thread-local accumulation maps notes to the right video when
# extract/base.py drains them into the manifest after each attempt
_NOTES = threading.local()


def _note(kind: str, message: str, **fields: object) -> None:
    items = getattr(_NOTES, "items", None)
    if items is None:
        items = _NOTES.items = []
    note: Dict[str, object] = {"kind": kind, "message": message, **fields}
    if note not in items:  # one fps-default note per video, not per reader
        items.append(note)


def pop_decode_warnings() -> List[Dict[str, object]]:
    """Drain this thread's accumulated decode warnings — each is
    ``{'kind', 'message', ...}`` (``partial_decode`` notes also carry
    ``decoded``/``declared`` counts). extract/base.py calls this after
    every decode attempt and records the notes as per-video manifest
    warnings instead of letting them vanish as silent defaults."""
    items = getattr(_NOTES, "items", None) or []
    _NOTES.items = []
    return items


def set_decoder(name: str) -> None:
    """Select the decode backend (called from config sanity_check /
    BaseExtractor; 'native' raises at open time if the library can't
    build, 'auto' silently falls back to cv2)."""
    global _DECODER
    if name not in ("auto", "cv2", "native"):
        raise ValueError(f"unknown decoder backend: {name!r}")
    _DECODER = name


def set_decode_timeout(seconds: Optional[float]) -> None:
    """Wall-clock budget per reader lifetime (``--decode_timeout``); a
    reader open longer than this raises :class:`DecodeTimeout` from its
    next ``grab()``. None disables. Module-global like the decoder
    choice: the readers are constructed deep inside samplers that don't
    thread config through."""
    global _DECODE_TIMEOUT
    with _CONFIG_LOCK:
        _DECODE_TIMEOUT = float(seconds) if seconds else None


def set_resource_caps(caps: Optional[ResourceCaps]) -> None:
    """Install the ``--max_pixels``/``--max_duration_s``/
    ``--max_decode_bytes`` running decode budget (BaseExtractor wires it
    from the config, like the timeout). Every subsequently-opened reader
    snapshots the caps and raises :class:`ResourceCapExceeded` the
    moment ACTUAL decode crosses one — the backstop for container
    metadata that lied its way past the preflight probe."""
    global _RESOURCE_CAPS
    with _CONFIG_LOCK:
        _RESOURCE_CAPS = caps or NO_CAPS


def set_frame_cache(cache) -> None:
    """Install (or, with None, remove) the shared-decode frame cache.
    Scoped by the caller — extract/plan.py's fan-out context manager,
    the serve daemon's lifetime — and module-global like the decoder
    choice, because the samplers that benefit are constructed deep
    inside extractors that don't thread config through."""
    global _FRAME_CACHE
    with _CONFIG_LOCK:
        _FRAME_CACHE = cache


def _cached_clip(path: str, decoder: Optional[str]):
    """The cached decoded clip for ``path`` when a frame cache is
    installed and admits it, else None (open a reader). Decode errors
    from a cache population propagate unchanged — same failure
    surface as a direct open."""
    with _CONFIG_LOCK:
        cache = _FRAME_CACHE
    if cache is None:
        return None
    return cache.acquire(str(path), decoder)


def _cached_fps_or_default(clip, path: str) -> float:
    if clip.fps:
        return clip.fps
    _note(
        "fps_defaulted",
        f"fps metadata absent or ~zero; timestamps assume 25.0 fps: {path}",
    )
    return 25.0


def _stream_from_cached(
    clip, extraction_fps: Optional[float], path: str
) -> Iterator[Tuple[np.ndarray, float]]:
    """:func:`_stream_from_reader`'s exact selection arithmetic replayed
    over a cached frame list — same grid formula, same duplicate-on-
    upsample behavior, same stop-at-decodable-end — so cached and
    direct streams are bit-identical (tests/test_cache.py pins it)."""
    src_fps = _cached_fps_or_default(clip, path)
    frames = clip.frames
    if extraction_fps is None:
        for i, frame in enumerate(frames):
            yield frame, i * 1000.0 / src_fps
    else:
        out_k = 0
        while True:
            target = int(round(out_k * src_fps / extraction_fps))
            if target >= len(frames):
                return
            yield frames[target], out_k * 1000.0 / extraction_fps
            out_k += 1


def _resolve(decoder: Optional[str]) -> str:
    d = decoder or _DECODER
    if d not in ("auto", "cv2", "native"):
        raise ValueError(f"unknown decoder backend: {d!r}")
    return d


class _Reader:
    """grab/retrieve reader over either backend, always yielding RGB.

    ``grab()`` advances one frame without color conversion;
    ``retrieve()`` converts the held frame. Dropping a frame costs decode
    only — the sampler pattern both backends support.

    ``decoder`` is per-reader (extractors pass their config's choice);
    None uses the module default set by :func:`set_decoder`. 'auto' falls
    back to cv2 PER FILE — the native loader refuses files it cannot
    handle faithfully (unsupported codec, rotation metadata), not just
    hosts where its library fails to build.
    """

    def __init__(self, path: str, decoder: Optional[str] = None) -> None:
        # one 'decode' span per reader lifetime (open -> close), via the
        # module-level hook so samplers need no telemetry plumbing; the
        # token is None when telemetry is absent/disabled
        self._span = telemetry.begin("decode", video=str(path))
        d = _resolve(decoder)
        self._nat = None
        self._cap = None
        if d != "cv2":
            from video_features_tpu import native

            if native.decoder_available():
                try:
                    self._nat = native.NativeVideoReader(path)
                except IOError as e:
                    if d == "native":
                        # forced native: an unopenable container is bad
                        # bytes, not a flake — fail fast, don't retry
                        raise CorruptVideoError(str(e)) from e
            elif d == "native":
                raise RuntimeError(
                    f"--decoder native requested but the decode library is "
                    f"unavailable: {native.decoder_build_error()}"
                )
        if self._nat is not None:
            raw_fps = self._nat.fps or 0.0
            self.frame_count = int(self._nat.frame_count or 0)
            self.width, self.height = self._nat.width, self._nat.height
        else:
            self._cap = cv2.VideoCapture(str(path))
            if not self._cap.isOpened():
                raise CorruptVideoError(f"cannot open video: {path}")
            raw_fps = self._cap.get(cv2.CAP_PROP_FPS) or 0.0
            self.frame_count = int(self._cap.get(cv2.CAP_PROP_FRAME_COUNT))
            self.width = int(self._cap.get(cv2.CAP_PROP_FRAME_WIDTH))
            self.height = int(self._cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
        # near-zero/non-finite declared fps IS absent fps (a hostile AVI
        # can declare dwScale ~2^32 -> fps ~1e-10); normalizing to 0.0
        # routes it into the recorded 25.0-default warning path
        self.fps = (
            float(raw_fps)
            if math.isfinite(raw_fps) and raw_fps >= MIN_SANE_FPS
            else 0.0
        )
        if self.frame_count < 0 or self.frame_count > 10 ** 9:
            self.frame_count = 0  # bit-flipped headers declare garbage counts
        self._path = str(path)
        self._deadline = (
            time.monotonic() + _DECODE_TIMEOUT if _DECODE_TIMEOUT else None
        )
        # the running resource budget (snapshot: a daemon rebind mid-read
        # must not change this reader's contract)
        with _CONFIG_LOCK:
            self._caps = _RESOURCE_CAPS
        self._grabs = 0
        self._retrieved_bytes = 0
        self._eof = False
        self._closed = False
        if self._caps.max_pixels is not None \
                and self.width * self.height > self._caps.max_pixels:
            self.close()
            raise ResourceCapExceeded(
                f"declared frame size {self.width}x{self.height} exceeds "
                f"--max_pixels {self._caps.max_pixels}: {path}"
            )
        self._max_frames = (
            int(self._caps.max_duration_s * (self.fps or 25.0)) + 1
            if self._caps.max_duration_s is not None
            else None
        )
        # injected 'decode' faults land here, after open: a hang eats
        # into this reader's deadline exactly like a stalled demuxer
        faults.fire("decode")

    def grab(self) -> bool:
        if self._deadline is not None and time.monotonic() > self._deadline:
            raise DecodeTimeout(
                f"decode exceeded --decode_timeout {_DECODE_TIMEOUT:g}s: {self._path}"
            )
        ok = self._nat.grab() >= 0 if self._nat is not None else self._cap.grab()
        if not ok:
            self._eof = True
            return False
        self._grabs += 1
        if self._max_frames is not None and self._grabs > self._max_frames:
            raise ResourceCapExceeded(
                f"decoded past --max_duration_s {self._caps.max_duration_s:g} "
                f"(~{self._max_frames} frames at {self.fps or 25.0:g} fps) — "
                f"declared metadata lied: {self._path}"
            )
        return True

    def retrieve(self) -> Optional[np.ndarray]:
        if self._nat is not None:
            frame = self._nat.retrieve()
        else:
            ok, frame = self._cap.retrieve()
            frame = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB) if ok else None
        if frame is not None:
            caps = self._caps
            if caps.max_pixels is not None:
                px = int(frame.shape[0]) * int(frame.shape[1])
                if px > caps.max_pixels:
                    raise ResourceCapExceeded(
                        f"decoded frame {frame.shape[1]}x{frame.shape[0]} "
                        f"({px} pixels) exceeds --max_pixels "
                        f"{caps.max_pixels}: {self._path}"
                    )
            if caps.max_decode_bytes is not None:
                self._retrieved_bytes += int(frame.nbytes)
                if self._retrieved_bytes > caps.max_decode_bytes:
                    raise ResourceCapExceeded(
                        f"decoded {self._retrieved_bytes} bytes, over "
                        f"--max_decode_bytes {caps.max_decode_bytes}: "
                        f"{self._path}"
                    )
            telemetry.frame_decoded()
        return frame

    def read(self) -> Optional[np.ndarray]:
        return self.retrieve() if self.grab() else None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._nat is not None:
            self._nat.close()
        elif self._cap is not None:
            self._cap.release()
        # salvage-decode bookkeeping: the stream ENDED (not a sampler
        # stopping early) short of its declared frame count — a
        # truncated/corrupt tail. The prefix already flowed to the
        # caller; the note becomes a partial_decode manifest warning.
        # Declared counts are allowed a little slack (containers
        # estimate), so only a >5% shortfall counts as truncation.
        if (
            self._eof
            and self.frame_count > 0
            and self._grabs < self.frame_count
            and (self.frame_count - self._grabs) > max(1, self.frame_count // 20)
        ):
            _note(
                "partial_decode",
                f"partial decode: {self._grabs} of {self.frame_count} "
                f"declared frames decodable: {self._path}",
                decoded=self._grabs,
                declared=self.frame_count,
            )
        telemetry.end(self._span)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@dataclasses.dataclass(frozen=True)
class VideoMeta:
    fps: float
    frame_count: int
    width: int
    height: int

    @property
    def duration_s(self) -> float:
        return self.frame_count / self.fps if self.fps else 0.0


def probe(path: str, decoder: Optional[str] = None) -> VideoMeta:
    clip = _cached_clip(path, decoder)
    if clip is not None:
        return VideoMeta(
            fps=clip.fps, frame_count=clip.frame_count,
            width=clip.width, height=clip.height,
        )
    with _Reader(path, decoder) as r:
        return VideoMeta(
            fps=r.fps, frame_count=r.frame_count, width=r.width, height=r.height
        )


def read_frames_at_indices(
    path: str, indices, decoder: Optional[str] = None, allow_seek: bool = False
) -> dict:
    """Decode returning {index: rgb_uint8_hwc} for the wanted frame
    indices; indices past the decodable end are simply absent.

    ``allow_seek=True`` (opt-in): when the wanted set is sparse relative
    to its span, seeks via ``CAP_PROP_POS_FRAMES`` instead of decoding
    every frame up to ``max(indices)`` — the analog of the reference's
    ``mmcv VideoReader.get_frame`` random access (ref
    extract_i3d.py:246-248). The default is the always-frame-exact
    sequential decode: POS_FRAMES seeks can land off-by-frames on
    open-GOP/B-frame streams while still passing the position-readback
    guard below, so no feature path enables seeking (VERDICT r02 #5) —
    it remains available for callers whose accuracy needs are looser
    than the sampled-feature contract."""
    need = sorted(set(int(i) for i in indices))
    if not need:
        return {}
    clip = _cached_clip(path, decoder)
    if clip is not None:
        # the cached list is the sequential decode's output: indices
        # past its end are absent, exactly like a grab() miss below
        return {i: clip.frames[i] for i in need if i < len(clip.frames)}
    span = need[-1] + 1

    # crossover measured on the bench host: a seek costs ~13 sequential
    # frame decodes (GOP re-decode), so random access pays off only below
    # ~1-in-16 density (uni_12 over a 2-minute clip stays sequential; a
    # low --extraction_fps over a long video seeks)
    if allow_seek and len(need) * 16 < span:
        # sparse: random-access each wanted frame (cv2-only: pts->index
        # mapping for av_seek_frame is container-dependent, so the native
        # loader stays sequential). Same semantics (and the same
        # codec-dependent accuracy caveats) as the reference's mmcv
        # VideoReader.get_frame, which also seeks via CAP_PROP_POS_FRAMES.
        # Guard: if the backend doesn't honor a seek (POS_FRAMES readback
        # mismatch), fall through to the always-exact sequential decode
        # rather than silently returning wrong frames.
        got = {}
        cap = cv2.VideoCapture(str(path))
        try:
            seek_ok = True
            for i in need:
                cap.set(cv2.CAP_PROP_POS_FRAMES, i)
                if int(cap.get(cv2.CAP_PROP_POS_FRAMES)) != i:
                    seek_ok = False
                    break
                ok, frame = cap.read()
                if ok:
                    got[i] = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        finally:
            cap.release()
        if seek_ok:
            return got

    got = {}
    wanted = set(need)
    with _Reader(path, decoder) as r:
        for i in range(span):
            if not r.grab():
                break
            if i in wanted:
                frame = r.retrieve()
                if frame is not None:
                    got[i] = frame
    return got


def _fps_or_default(r: "_Reader") -> float:
    """The 25.0 fallback for absent fps metadata — recorded, not silent:
    the note surfaces as a per-video manifest warning (extract/base.py
    drains :func:`pop_decode_warnings`) so downstream timestamp
    consumers know the clock is a guess."""
    if r.fps:
        return r.fps
    _note(
        "fps_defaulted",
        f"fps metadata absent or ~zero; timestamps assume 25.0 fps: {r._path}",
    )
    return 25.0


def _stream_from_reader(
    r: "_Reader", extraction_fps: Optional[float]
) -> Iterator[Tuple[np.ndarray, float]]:
    """The sequential frame-selection loop over an already-open reader
    (shared by :func:`stream_frames` and :func:`read_all_frames`, which
    used to pay a second container open just to learn the fps)."""
    src_fps = _fps_or_default(r)
    if extraction_fps is None:
        i = 0
        while True:
            frame = r.read()
            if frame is None:
                break
            yield frame, i * 1000.0 / src_fps
            i += 1
    else:
        # Select source frames nearest the target fps grid while
        # decoding sequentially. Works without a (reliable) frame
        # count: output frame k maps to source index
        # round(k * src_fps / dst_fps); duplicates when upsampling,
        # drops when downsampling.
        out_k = 0
        src_i = -1
        frame = None
        while True:
            target = int(round(out_k * src_fps / extraction_fps))
            fresh = False
            while src_i < target:
                if not r.grab():
                    return
                fresh = True
                src_i += 1
            if fresh:
                frame = r.retrieve()
                if frame is None:
                    return
            yield frame, out_k * 1000.0 / extraction_fps
            out_k += 1


def stream_frames(
    path: str,
    extraction_fps: Optional[float] = None,
    decoder: Optional[str] = None,
) -> Iterator[Tuple[np.ndarray, float]]:
    """Yield (rgb_uint8_hwc, timestamp_ms) frames sequentially.

    With ``extraction_fps`` set, frames are selected on the target fps grid
    while still decoding sequentially (no random seeks — mp4 seeking is
    keyframe-inaccurate); skipped grid frames are grabbed, never converted.
    """
    clip = _cached_clip(path, decoder)
    if clip is not None:
        yield from _stream_from_cached(clip, extraction_fps, str(path))
        return
    with _Reader(path, decoder) as r:
        yield from _stream_from_reader(r, extraction_fps)


def read_all_frames(
    path: str,
    extraction_fps: Optional[float] = None,
    decoder: Optional[str] = None,
) -> Tuple[List[np.ndarray], float, List[float]]:
    """Whole-clip decode -> (rgb frames, effective fps, timestamps_ms).

    One reader serves both the fps lookup and the stream (this used to
    open the container twice: once via :func:`probe`, once via
    :func:`stream_frames`)."""
    frames, fps, stamps, _ = read_all_frames_with_meta(
        path, extraction_fps, decoder
    )
    return frames, fps, stamps


def read_all_frames_with_meta(
    path: str,
    extraction_fps: Optional[float] = None,
    decoder: Optional[str] = None,
) -> Tuple[List[np.ndarray], float, List[float], int]:
    """:func:`read_all_frames` plus the container's DECLARED frame count
    (0 when unknown/insane) — the number :func:`require_window` failures
    report against, so a truncated stream fails with 'N of M declared
    frames decoded' instead of a bare N."""
    frames, stamps = [], []
    clip = _cached_clip(path, decoder)
    if clip is not None:
        fps = extraction_fps or clip.fps or 25.0
        for frame, ts in _stream_from_cached(clip, extraction_fps, str(path)):
            frames.append(frame)
            stamps.append(ts)
        return frames, fps, stamps, clip.frame_count
    with _Reader(path, decoder) as r:
        declared = r.frame_count
        fps = extraction_fps or r.fps or 25.0
        for frame, ts in _stream_from_reader(r, extraction_fps):
            frames.append(frame)
            stamps.append(ts)
    return frames, fps, stamps, declared


def extract_frames(
    path: str,
    method: str,
    decoder: Optional[str] = None,
) -> Tuple[List[np.ndarray], float, List[float]]:
    """``fix_<fps>`` / ``uni_<N>`` samplers, mirroring ref utils/utils.py:297-333.

    Both sample indices as ``linspace(1, frame_cnt - 2, n)`` ("ignore some
    frames to avoid strange bugs" — i.e. skip first/last, which are
    decode-fragile). Returns (rgb frames, source fps, timestamps_ms).
    """
    ext, *params = method.split("_")
    meta = probe(path, decoder)
    frame_cnt = meta.frame_count
    if meta.fps:
        fps = meta.fps
    else:
        _note(
            "fps_defaulted",
            f"fps metadata absent or ~zero; timestamps assume 25.0 fps: {path}",
        )
        fps = 25.0
    if frame_cnt < 3:
        raise CorruptVideoError(
            f"video too short for sampling: {frame_cnt} of {frame_cnt} "
            f"declared frames, sampler needs 3: {path}"
        )
    mspf = 1000.0 / fps

    if ext == "fix":
        samples_num = int(frame_cnt / fps * int(params[0]))
    elif ext == "uni":
        samples_num = int(params[0])
    else:
        raise NotImplementedError(f"extract method {ext!r} is not supported")
    samples_num = max(samples_num, 1)
    samples_ix = np.linspace(1, frame_cnt - 2, samples_num).astype(int)

    # allow_seek=False: the reference's samplers decode sequentially up to
    # max(index) (ref utils/utils.py:297-333) — always frame-exact. Seek
    # accuracy can't be verified deeply enough (open-GOP / B-frame
    # reordering passes the POS_FRAMES readback guard) to risk the
    # sampled-feature contract on it.
    got = read_frames_at_indices(path, samples_ix, decoder, allow_seek=False)
    if not got:
        # the decodable prefix cannot fill even one sample window:
        # permanent, with decoded/declared counts for the manifest
        raise CorruptVideoError(
            f"no frames decoded (0 of {frame_cnt} declared frames): {path}"
        )
    # duplicate indices in linspace (short videos) resolve to the same frame
    last_seen = None
    frames = []
    for ix in samples_ix:
        if ix in got:
            last_seen = got[ix]
        frames.append(last_seen if last_seen is not None else next(iter(got.values())))
    timestamps_ms = [float(ix) * mspf for ix in samples_ix]
    return frames, fps, timestamps_ms


def require_window(frames, needed: int, path: str, declared: int = 0) -> None:
    """The salvage-decode boundary for windowed extractors: a decodable
    prefix that fills ≥1 model window proceeds (with the reader's
    ``partial_decode`` warning already noted); one that cannot is a
    permanent input failure recorded with decoded/declared counts."""
    if len(frames) < max(int(needed), 1):
        raise CorruptVideoError(
            f"decodable prefix too short for one model window: "
            f"{len(frames)} of {declared or 'unknown'} declared frames "
            f"decoded, window needs {needed}: {path}"
        )
