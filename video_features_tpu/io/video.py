"""Video decode + frame sampling on the host CPU.

The reference uses four decode backends (mmcv, cv2 streaming, torchvision
read_video, ffmpeg re-encode — SURVEY.md §1 L3). Here there is ONE reader
abstraction with two interchangeable backends:

- ``cv2`` — OpenCV's ``VideoCapture`` (decodes BGR; flipped to RGB once
  per retrieved frame),
- ``native`` — the framework's own C++ decode loader
  (native/decoder.cpp: libavformat/libavcodec/libswscale via ctypes),
  which converts straight to RGB24 — no BGR round trip.

``--decoder`` picks: 'auto' (default) uses the native loader when its
library builds, 'cv2'/'native' force one. Both decode the same bitstream
through libavcodec, so frames are bit-identical (tests/test_native.py).
Samplers drop frames with ``grab()`` (decode, no color conversion) and
pay ``retrieve()`` only for frames they keep.

Wrapped in:

- :func:`stream_frames` — a generator for frame-wise extractors (the
  cv2 streaming loop of ref models/resnet/extract_resnet.py:121-156),
- :func:`read_all_frames` — whole-clip decode for stack-wise extractors
  (ref models/r21d/extract_r21d.py:102, models/i3d/extract_i3d.py:239-259),
- :func:`extract_frames` — the ``fix_N`` / ``uni_N`` samplers
  (ref utils/utils.py:297-333).

fps re-targeting is done in-process by nearest-timestamp frame selection
instead of an ffmpeg re-encode subprocess (ref utils/utils.py:222-244);
if an ffmpeg binary exists it can still be used via io.ffmpeg.

Note: the reference computes ``mspf = 0.001 / fps`` (ref
utils/utils.py:312) which is a unit bug; the correct milliseconds-per-frame
``1000 / fps`` is used here (matching upstream v-iashin/video_features).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Iterator, List, Optional, Tuple

import cv2
import numpy as np

from video_features_tpu.runtime import faults
from video_features_tpu.runtime import telemetry
from video_features_tpu.runtime.faults import CorruptVideoError, DecodeTimeout

_DECODER = "auto"  # 'auto' | 'cv2' | 'native'; set once from the config
_DECODE_TIMEOUT: Optional[float] = None  # seconds per reader; set from the config
# BaseExtractor.__init__ sets the timeout, and the serve daemon builds
# extractors from its dispatcher thread — rebinds must hold this lock
_CONFIG_LOCK = threading.Lock()


def set_decoder(name: str) -> None:
    """Select the decode backend (called from config sanity_check /
    BaseExtractor; 'native' raises at open time if the library can't
    build, 'auto' silently falls back to cv2)."""
    global _DECODER
    if name not in ("auto", "cv2", "native"):
        raise ValueError(f"unknown decoder backend: {name!r}")
    _DECODER = name


def set_decode_timeout(seconds: Optional[float]) -> None:
    """Wall-clock budget per reader lifetime (``--decode_timeout``); a
    reader open longer than this raises :class:`DecodeTimeout` from its
    next ``grab()``. None disables. Module-global like the decoder
    choice: the readers are constructed deep inside samplers that don't
    thread config through."""
    global _DECODE_TIMEOUT
    with _CONFIG_LOCK:
        _DECODE_TIMEOUT = float(seconds) if seconds else None


def _resolve(decoder: Optional[str]) -> str:
    d = decoder or _DECODER
    if d not in ("auto", "cv2", "native"):
        raise ValueError(f"unknown decoder backend: {d!r}")
    return d


class _Reader:
    """grab/retrieve reader over either backend, always yielding RGB.

    ``grab()`` advances one frame without color conversion;
    ``retrieve()`` converts the held frame. Dropping a frame costs decode
    only — the sampler pattern both backends support.

    ``decoder`` is per-reader (extractors pass their config's choice);
    None uses the module default set by :func:`set_decoder`. 'auto' falls
    back to cv2 PER FILE — the native loader refuses files it cannot
    handle faithfully (unsupported codec, rotation metadata), not just
    hosts where its library fails to build.
    """

    def __init__(self, path: str, decoder: Optional[str] = None) -> None:
        # one 'decode' span per reader lifetime (open -> close), via the
        # module-level hook so samplers need no telemetry plumbing; the
        # token is None when telemetry is absent/disabled
        self._span = telemetry.begin("decode", video=str(path))
        d = _resolve(decoder)
        self._nat = None
        self._cap = None
        if d != "cv2":
            from video_features_tpu import native

            if native.decoder_available():
                try:
                    self._nat = native.NativeVideoReader(path)
                except IOError as e:
                    if d == "native":
                        # forced native: an unopenable container is bad
                        # bytes, not a flake — fail fast, don't retry
                        raise CorruptVideoError(str(e)) from e
            elif d == "native":
                raise RuntimeError(
                    f"--decoder native requested but the decode library is "
                    f"unavailable: {native.decoder_build_error()}"
                )
        if self._nat is not None:
            self.fps = self._nat.fps or 0.0
            self.frame_count = int(self._nat.frame_count or 0)
            self.width, self.height = self._nat.width, self._nat.height
        else:
            self._cap = cv2.VideoCapture(str(path))
            if not self._cap.isOpened():
                raise CorruptVideoError(f"cannot open video: {path}")
            self.fps = self._cap.get(cv2.CAP_PROP_FPS) or 0.0
            self.frame_count = int(self._cap.get(cv2.CAP_PROP_FRAME_COUNT))
            self.width = int(self._cap.get(cv2.CAP_PROP_FRAME_WIDTH))
            self.height = int(self._cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
        self._path = str(path)
        self._deadline = (
            time.monotonic() + _DECODE_TIMEOUT if _DECODE_TIMEOUT else None
        )
        # injected 'decode' faults land here, after open: a hang eats
        # into this reader's deadline exactly like a stalled demuxer
        faults.fire("decode")

    def grab(self) -> bool:
        if self._deadline is not None and time.monotonic() > self._deadline:
            raise DecodeTimeout(
                f"decode exceeded --decode_timeout {_DECODE_TIMEOUT:g}s: {self._path}"
            )
        if self._nat is not None:
            return self._nat.grab() >= 0
        return self._cap.grab()

    def retrieve(self) -> Optional[np.ndarray]:
        if self._nat is not None:
            frame = self._nat.retrieve()
        else:
            ok, frame = self._cap.retrieve()
            frame = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB) if ok else None
        if frame is not None:
            telemetry.frame_decoded()
        return frame

    def read(self) -> Optional[np.ndarray]:
        return self.retrieve() if self.grab() else None

    def close(self) -> None:
        if self._nat is not None:
            self._nat.close()
        elif self._cap is not None:
            self._cap.release()
        telemetry.end(self._span)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@dataclasses.dataclass(frozen=True)
class VideoMeta:
    fps: float
    frame_count: int
    width: int
    height: int

    @property
    def duration_s(self) -> float:
        return self.frame_count / self.fps if self.fps else 0.0


def probe(path: str, decoder: Optional[str] = None) -> VideoMeta:
    with _Reader(path, decoder) as r:
        return VideoMeta(
            fps=r.fps, frame_count=r.frame_count, width=r.width, height=r.height
        )


def read_frames_at_indices(
    path: str, indices, decoder: Optional[str] = None, allow_seek: bool = False
) -> dict:
    """Decode returning {index: rgb_uint8_hwc} for the wanted frame
    indices; indices past the decodable end are simply absent.

    ``allow_seek=True`` (opt-in): when the wanted set is sparse relative
    to its span, seeks via ``CAP_PROP_POS_FRAMES`` instead of decoding
    every frame up to ``max(indices)`` — the analog of the reference's
    ``mmcv VideoReader.get_frame`` random access (ref
    extract_i3d.py:246-248). The default is the always-frame-exact
    sequential decode: POS_FRAMES seeks can land off-by-frames on
    open-GOP/B-frame streams while still passing the position-readback
    guard below, so no feature path enables seeking (VERDICT r02 #5) —
    it remains available for callers whose accuracy needs are looser
    than the sampled-feature contract."""
    need = sorted(set(int(i) for i in indices))
    if not need:
        return {}
    span = need[-1] + 1

    # crossover measured on the bench host: a seek costs ~13 sequential
    # frame decodes (GOP re-decode), so random access pays off only below
    # ~1-in-16 density (uni_12 over a 2-minute clip stays sequential; a
    # low --extraction_fps over a long video seeks)
    if allow_seek and len(need) * 16 < span:
        # sparse: random-access each wanted frame (cv2-only: pts->index
        # mapping for av_seek_frame is container-dependent, so the native
        # loader stays sequential). Same semantics (and the same
        # codec-dependent accuracy caveats) as the reference's mmcv
        # VideoReader.get_frame, which also seeks via CAP_PROP_POS_FRAMES.
        # Guard: if the backend doesn't honor a seek (POS_FRAMES readback
        # mismatch), fall through to the always-exact sequential decode
        # rather than silently returning wrong frames.
        got = {}
        cap = cv2.VideoCapture(str(path))
        try:
            seek_ok = True
            for i in need:
                cap.set(cv2.CAP_PROP_POS_FRAMES, i)
                if int(cap.get(cv2.CAP_PROP_POS_FRAMES)) != i:
                    seek_ok = False
                    break
                ok, frame = cap.read()
                if ok:
                    got[i] = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        finally:
            cap.release()
        if seek_ok:
            return got

    got = {}
    wanted = set(need)
    with _Reader(path, decoder) as r:
        for i in range(span):
            if not r.grab():
                break
            if i in wanted:
                frame = r.retrieve()
                if frame is not None:
                    got[i] = frame
    return got


def stream_frames(
    path: str,
    extraction_fps: Optional[float] = None,
    decoder: Optional[str] = None,
) -> Iterator[Tuple[np.ndarray, float]]:
    """Yield (rgb_uint8_hwc, timestamp_ms) frames sequentially.

    With ``extraction_fps`` set, frames are selected on the target fps grid
    while still decoding sequentially (no random seeks — mp4 seeking is
    keyframe-inaccurate); skipped grid frames are grabbed, never converted.
    """
    with _Reader(path, decoder) as r:
        src_fps = r.fps or 25.0
        if extraction_fps is None:
            i = 0
            while True:
                frame = r.read()
                if frame is None:
                    break
                yield frame, i * 1000.0 / src_fps
                i += 1
        else:
            # Select source frames nearest the target fps grid while
            # decoding sequentially. Works without a (reliable) frame
            # count: output frame k maps to source index
            # round(k * src_fps / dst_fps); duplicates when upsampling,
            # drops when downsampling.
            out_k = 0
            src_i = -1
            frame = None
            while True:
                target = int(round(out_k * src_fps / extraction_fps))
                fresh = False
                while src_i < target:
                    if not r.grab():
                        return
                    fresh = True
                    src_i += 1
                if fresh:
                    frame = r.retrieve()
                    if frame is None:
                        return
                yield frame, out_k * 1000.0 / extraction_fps
                out_k += 1


def read_all_frames(
    path: str,
    extraction_fps: Optional[float] = None,
    decoder: Optional[str] = None,
) -> Tuple[List[np.ndarray], float, List[float]]:
    """Whole-clip decode -> (rgb frames, effective fps, timestamps_ms)."""
    meta = probe(path, decoder)
    fps = extraction_fps or meta.fps or 25.0
    frames, stamps = [], []
    for frame, ts in stream_frames(path, extraction_fps, decoder):
        frames.append(frame)
        stamps.append(ts)
    return frames, fps, stamps


def extract_frames(
    path: str,
    method: str,
    decoder: Optional[str] = None,
) -> Tuple[List[np.ndarray], float, List[float]]:
    """``fix_<fps>`` / ``uni_<N>`` samplers, mirroring ref utils/utils.py:297-333.

    Both sample indices as ``linspace(1, frame_cnt - 2, n)`` ("ignore some
    frames to avoid strange bugs" — i.e. skip first/last, which are
    decode-fragile). Returns (rgb frames, source fps, timestamps_ms).
    """
    ext, *params = method.split("_")
    meta = probe(path, decoder)
    fps, frame_cnt = meta.fps or 25.0, meta.frame_count
    if frame_cnt < 3:
        raise CorruptVideoError(
            f"video too short for sampling ({frame_cnt} frames): {path}"
        )
    mspf = 1000.0 / fps

    if ext == "fix":
        samples_num = int(frame_cnt / fps * int(params[0]))
    elif ext == "uni":
        samples_num = int(params[0])
    else:
        raise NotImplementedError(f"extract method {ext!r} is not supported")
    samples_num = max(samples_num, 1)
    samples_ix = np.linspace(1, frame_cnt - 2, samples_num).astype(int)

    # allow_seek=False: the reference's samplers decode sequentially up to
    # max(index) (ref utils/utils.py:297-333) — always frame-exact. Seek
    # accuracy can't be verified deeply enough (open-GOP / B-frame
    # reordering passes the POS_FRAMES readback guard) to risk the
    # sampled-feature contract on it.
    got = read_frames_at_indices(path, samples_ix, decoder, allow_seek=False)
    if not got:
        raise CorruptVideoError(f"no frames decoded from {path}")
    # duplicate indices in linspace (short videos) resolve to the same frame
    last_seen = None
    frames = []
    for ix in samples_ix:
        if ix in got:
            last_seen = got[ix]
        frames.append(last_seen if last_seen is not None else next(iter(got.values())))
    timestamps_ms = [float(ix) * mspf for ix in samples_ix]
    return frames, fps, timestamps_ms
