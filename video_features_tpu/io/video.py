"""Video decode + frame sampling on the host CPU.

The reference uses four decode backends (mmcv, cv2 streaming, torchvision
read_video, ffmpeg re-encode — SURVEY.md §1 L3). Here there is ONE:
OpenCV's ``cv2.VideoCapture``, wrapped in

- :func:`stream_frames` — a generator for frame-wise extractors (the
  cv2 streaming loop of ref models/resnet/extract_resnet.py:121-156),
- :func:`read_all_frames` — whole-clip decode for stack-wise extractors
  (ref models/r21d/extract_r21d.py:102, models/i3d/extract_i3d.py:239-259),
- :func:`extract_frames` — the ``fix_N`` / ``uni_N`` samplers
  (ref utils/utils.py:297-333).

fps re-targeting is done in-process by nearest-timestamp frame selection
instead of an ffmpeg re-encode subprocess (ref utils/utils.py:222-244);
if an ffmpeg binary exists it can still be used via io.ffmpeg. Frames are
returned RGB uint8 HWC (cv2 decodes BGR; we flip here, once — extractors
needing BGR, i.e. PWC, flip back inside their preprocess).

Note: the reference computes ``mspf = 0.001 / fps`` (ref
utils/utils.py:312) which is a unit bug; the correct milliseconds-per-frame
``1000 / fps`` is used here (matching upstream v-iashin/video_features).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

import cv2
import numpy as np


@dataclasses.dataclass(frozen=True)
class VideoMeta:
    fps: float
    frame_count: int
    width: int
    height: int

    @property
    def duration_s(self) -> float:
        return self.frame_count / self.fps if self.fps else 0.0


def probe(path: str) -> VideoMeta:
    cap = cv2.VideoCapture(str(path))
    if not cap.isOpened():
        raise IOError(f"cannot open video: {path}")
    meta = VideoMeta(
        fps=cap.get(cv2.CAP_PROP_FPS),
        frame_count=int(cap.get(cv2.CAP_PROP_FRAME_COUNT)),
        width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
        height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
    )
    cap.release()
    return meta


def read_frames_at_indices(path: str, indices) -> dict:
    """Decode returning {index: rgb_uint8_hwc} for the wanted frame
    indices; indices past the decodable end are simply absent.

    When the wanted set is sparse relative to its span (e.g. I3D with a
    low ``--extraction_fps`` over a long video), seeks via
    ``CAP_PROP_POS_FRAMES`` instead of decoding every frame up to
    ``max(indices)`` — the analog of the reference's ``mmcv
    VideoReader.get_frame`` random access (ref extract_i3d.py:246-248).
    Dense sets keep the sequential decode (seek + keyframe re-decode
    would be slower, and sequential reads are always frame-exact)."""
    need = sorted(set(int(i) for i in indices))
    if not need:
        return {}
    span = need[-1] + 1

    # crossover measured on the bench host: a seek costs ~13 sequential
    # frame decodes (GOP re-decode), so random access pays off only below
    # ~1-in-16 density (uni_12 over a 2-minute clip stays sequential; a
    # low --extraction_fps over a long video seeks)
    if len(need) * 16 < span:
        # sparse: random-access each wanted frame. Same semantics (and the
        # same codec-dependent accuracy caveats) as the reference's mmcv
        # VideoReader.get_frame, which also seeks via CAP_PROP_POS_FRAMES.
        # Guard: if the backend doesn't honor a seek (POS_FRAMES readback
        # mismatch), fall through to the always-exact sequential decode
        # rather than silently returning wrong frames.
        got = {}
        cap = cv2.VideoCapture(str(path))
        try:
            seek_ok = True
            for i in need:
                cap.set(cv2.CAP_PROP_POS_FRAMES, i)
                if int(cap.get(cv2.CAP_PROP_POS_FRAMES)) != i:
                    seek_ok = False
                    break
                ok, frame = cap.read()
                if ok:
                    got[i] = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        finally:
            cap.release()
        if seek_ok:
            return got

    got = {}
    wanted = set(need)
    cap = cv2.VideoCapture(str(path))
    try:
        i = 0
        while i < span:
            ok, frame = cap.read()
            if not ok:
                break
            if i in wanted:
                got[i] = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
            i += 1
    finally:
        cap.release()
    return got


def stream_frames(
    path: str,
    extraction_fps: Optional[float] = None,
) -> Iterator[Tuple[np.ndarray, float]]:
    """Yield (rgb_uint8_hwc, timestamp_ms) frames sequentially.

    With ``extraction_fps`` set, frames are selected on the target fps grid
    while still decoding sequentially (no random seeks — mp4 seeking in
    cv2 is keyframe-inaccurate).
    """
    cap = cv2.VideoCapture(str(path))
    if not cap.isOpened():
        raise IOError(f"cannot open video: {path}")
    src_fps = cap.get(cv2.CAP_PROP_FPS) or 25.0
    frame_count = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))

    try:
        if extraction_fps is None:
            i = 0
            while True:
                ok, frame = cap.read()
                if not ok:
                    break
                yield cv2.cvtColor(frame, cv2.COLOR_BGR2RGB), i * 1000.0 / src_fps
                i += 1
        else:
            # Select source frames nearest the target fps grid while decoding
            # sequentially. Works without a (reliable) frame count: output
            # frame k maps to source index round(k * src_fps / dst_fps);
            # duplicates when upsampling, drops when downsampling.
            out_k = 0
            src_i = -1
            frame = None
            while True:
                target = int(round(out_k * src_fps / extraction_fps))
                while src_i < target:
                    ok, nxt = cap.read()
                    if not ok:
                        return
                    frame = nxt
                    src_i += 1
                yield (
                    cv2.cvtColor(frame, cv2.COLOR_BGR2RGB),
                    out_k * 1000.0 / extraction_fps,
                )
                out_k += 1
    finally:
        cap.release()


def read_all_frames(
    path: str,
    extraction_fps: Optional[float] = None,
) -> Tuple[List[np.ndarray], float, List[float]]:
    """Whole-clip decode -> (rgb frames, effective fps, timestamps_ms)."""
    meta = probe(path)
    fps = extraction_fps or meta.fps or 25.0
    frames, stamps = [], []
    for frame, ts in stream_frames(path, extraction_fps):
        frames.append(frame)
        stamps.append(ts)
    return frames, fps, stamps


def extract_frames(
    path: str,
    method: str,
) -> Tuple[List[np.ndarray], float, List[float]]:
    """``fix_<fps>`` / ``uni_<N>`` samplers, mirroring ref utils/utils.py:297-333.

    Both sample indices as ``linspace(1, frame_cnt - 2, n)`` ("ignore some
    frames to avoid strange bugs" — i.e. skip first/last, which are
    decode-fragile). Returns (rgb frames, source fps, timestamps_ms).
    """
    ext, *params = method.split("_")
    meta = probe(path)
    fps, frame_cnt = meta.fps or 25.0, meta.frame_count
    if frame_cnt < 3:
        raise IOError(f"video too short for sampling ({frame_cnt} frames): {path}")
    mspf = 1000.0 / fps

    if ext == "fix":
        samples_num = int(frame_cnt / fps * int(params[0]))
    elif ext == "uni":
        samples_num = int(params[0])
    else:
        raise NotImplementedError(f"extract method {ext!r} is not supported")
    samples_num = max(samples_num, 1)
    samples_ix = np.linspace(1, frame_cnt - 2, samples_num).astype(int)

    wanted = set(samples_ix.tolist())
    got = {}
    cap = cv2.VideoCapture(str(path))
    try:
        i = 0
        last = max(wanted)
        while i <= last:
            ok, frame = cap.read()
            if not ok:
                break
            if i in wanted:
                got[i] = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
            i += 1
    finally:
        cap.release()
    if not got:
        raise IOError(f"no frames decoded from {path}")
    # duplicate indices in linspace (short videos) resolve to the same frame
    last_seen = None
    frames = []
    for ix in samples_ix:
        if ix in got:
            last_seen = got[ix]
        frames.append(last_seen if last_seen is not None else next(iter(got.values())))
    timestamps_ms = [float(ix) * mspf for ix in samples_ix]
    return frames, fps, timestamps_ms
