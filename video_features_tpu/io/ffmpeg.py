"""Optional ffmpeg boundary (gated: the binary may be absent).

The reference shells out to ffmpeg for fps re-encoding (ref
utils/utils.py:222-244) and the mp4 -> aac -> wav audio rip (ref
utils/utils.py:247-276). This framework does fps re-targeting in-process
(io.video._resample_indices) and reads wav directly, so ffmpeg is only
*required* for audio extraction from containers — and these helpers raise
a clear error when the binary is missing instead of failing mid-pipeline.
"""

from __future__ import annotations

import os
import pathlib
import shutil
import subprocess
from typing import Optional, Tuple

from video_features_tpu.runtime.faults import DecodeTimeout


def which_ffmpeg() -> str:
    """Path to ffmpeg, or '' when not installed (ref utils/utils.py:207-219)."""
    return shutil.which("ffmpeg") or ""


def require_ffmpeg() -> str:
    path = which_ffmpeg()
    if not path:
        raise RuntimeError(
            "ffmpeg binary not found. Audio extraction from video containers "
            "requires ffmpeg; pass a .wav file directly instead, or install ffmpeg."
        )
    return path


def reencode_video_with_diff_fps(
    video_path: str,
    tmp_path: str,
    extraction_fps: float,
    timeout_s: Optional[float] = None,
) -> str:
    """Re-encode to target fps into tmp_path (ref utils/utils.py:222-244).

    The output name carries a hash of the absolute source path: the
    reference's bare ``{stem}_new_fps.mp4`` collides when two path-list
    entries share a basename (a/clip.mp4 + b/clip.mp4), and concurrent
    prepare() workers would race ffmpeg's ``-y`` overwrite against the
    other video's decode — silently wrong features. The file is written
    to a unique temp name and atomically renamed, so a concurrent reader
    of the SAME source can never observe a truncated file."""
    import hashlib

    ffmpeg = require_ffmpeg()
    os.makedirs(tmp_path, exist_ok=True)
    tag = hashlib.sha1(os.path.abspath(video_path).encode()).hexdigest()[:10]
    stem = pathlib.Path(video_path).stem
    new_path = os.path.join(tmp_path, f"{stem}_{tag}_new_fps_{extraction_fps:g}.mp4")
    part = new_path + f".part{os.getpid()}.mp4"
    _run([ffmpeg, "-hide_banner", "-loglevel", "error", "-y", "-i", video_path,
          "-filter:v", f"fps=fps={extraction_fps}", part], timeout_s=timeout_s)
    os.replace(part, new_path)
    return new_path


def _run(cmd, timeout_s: Optional[float] = None) -> None:
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        # subprocess.run already killed the child; surface the same
        # transient deadline error class as an in-process decode stall
        raise DecodeTimeout(
            f"ffmpeg exceeded --decode_timeout {timeout_s:g}s: {' '.join(cmd)}"
        ) from e
    if proc.returncode != 0:
        raise RuntimeError(
            f"ffmpeg failed (exit {proc.returncode}): {' '.join(cmd)}\n{proc.stderr.strip()}"
        )


def extract_wav_from_video(video_path: str, tmp_path: str) -> Tuple[str, str]:
    """Container -> .aac -> .wav two-stage rip (ref utils/utils.py:247-276)."""
    ffmpeg = require_ffmpeg()
    os.makedirs(tmp_path, exist_ok=True)
    stem = pathlib.Path(video_path).stem
    aac_path = os.path.join(tmp_path, f"{stem}.aac")
    wav_path = os.path.join(tmp_path, f"{stem}.wav")
    _run([ffmpeg, "-hide_banner", "-loglevel", "error", "-y",
          "-i", video_path, "-acodec", "copy", aac_path])
    _run([ffmpeg, "-hide_banner", "-loglevel", "error", "-y",
          "-i", aac_path, wav_path])
    return wav_path, aac_path
