from video_features_tpu.io.paths import form_list_from_user_input, form_slices  # noqa: F401
from video_features_tpu.io.sink import action_on_extraction  # noqa: F401
from video_features_tpu.io.video import (  # noqa: F401
    VideoMeta,
    extract_frames,
    read_all_frames,
    stream_frames,
)
