"""Output sink: what happens to an extracted feature dict.

Mirrors ref utils/utils.py:50-114 (``action_on_extraction``): features are
printed with max/mean/min stats, or saved as ``<stem>_<key>.npy`` /
``<stem>_<key>.pkl`` (``<stem>.npy`` when ``output_direct``); meta keys
``fps`` and ``timestamps_ms`` are never saved. The reference's vestigial
``save_jpg`` flow branch (buggy at ref utils/utils.py:105 — iterating an
int) is implemented correctly here for 2-channel flow features.
"""

from __future__ import annotations

import json
import os
import pathlib
import pickle
import shutil
import threading
import uuid
from typing import Any, Dict, List, Union

import numpy as np

from video_features_tpu.runtime import faults

META_KEYS = ("fps", "timestamps_ms")
_SUFFIX = {"save_numpy": "npy", "save_pickle": "pkl"}


def atomic_copy(src: str, dest: str) -> None:
    """Copy ``src`` to ``dest`` through a uniquely-named tmp file +
    ``os.replace`` — the same commit protocol as the feature saver
    below, shared with the content-addressed cache (extract/cache.py)
    so a kill mid-materialize can never leave a truncated output that
    ``--resume`` (or a cache lookup) would then trust as complete."""
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    tmp = (
        f"{dest}.{os.getpid()}-{threading.get_ident()}"
        f"-{uuid.uuid4().hex[:8]}.tmp"
    )
    try:
        shutil.copyfile(src, tmp)
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(
    path: str,
    doc: Any,
    *,
    indent: Union[int, None] = None,
    sort_keys: bool = False,
) -> str:
    """Publish ``doc`` as JSON at ``path`` with the commit protocol every
    durable root in the tree uses (graftcheck GC601): stage to a
    uniquely-named same-directory ``.tmp`` sibling, then one
    ``os.replace``. Readers either see the old complete file or the new
    complete file — never a torn one — and concurrent writers can't
    clobber each other's staging file. Returns ``path``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = (
        f"{path}.{os.getpid()}-{threading.get_ident()}"
        f"-{uuid.uuid4().hex[:8]}.tmp"
    )
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=indent, sort_keys=sort_keys)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def output_file_name(name: str, key: str, on_extraction: str, output_direct: bool) -> str:
    """The single source of the ``<stem>_<key>.<ext>`` naming rule, shared
    by the saver and the ``--resume`` probe so they can never drift.
    Feature types may contain '/' (CLIP-ViT-B/32); sanitized so the file
    name stays flat and '<stem>_<key>' stays greppable."""
    suffix = _SUFFIX[on_extraction]
    if output_direct:
        return f"{name}.{suffix}"
    return f"{name}_{key.replace('/', '-')}.{suffix}"


def expected_output_files(
    feature_keys,
    video_path: Union[str, List[str]],
    output_path: str,
    on_extraction: str,
    output_direct: bool = False,
) -> List[str]:
    """The files a successful save would produce — the skip-if-done probe
    for ``--resume`` (the reference always recomputes and overwrites,
    ref utils/utils.py:92-95). Empty for non-file sinks AND for save_jpg
    (per-frame jpg dirs have no cheap completeness probe), so those modes
    always recompute — safe, never wrong."""
    if on_extraction not in _SUFFIX:
        return []
    if isinstance(video_path, (list, tuple)):
        video_path = video_path[0]
    name = pathlib.Path(video_path).stem
    # dict.fromkeys: output_direct collapses every key to one file
    return list(
        dict.fromkeys(
            os.path.join(
                output_path, output_file_name(name, key, on_extraction, output_direct)
            )
            for key in feature_keys
        )
    )


def action_on_extraction(
    feats_dict: Dict[str, np.ndarray],
    video_path: Union[str, List[str]],
    output_path: str,
    on_extraction: str,
    output_direct: bool = False,
) -> List[str]:
    """Returns warnings (currently: empty-feature values) so the caller
    can record them in the run manifest; ``--strict`` fails the run on
    them (docs/robustness.md)."""
    if isinstance(video_path, (list, tuple)):
        video_path = video_path[0]
    name = pathlib.Path(video_path).stem
    warnings: List[str] = []

    for key, value in feats_dict.items():
        if key in META_KEYS:
            continue
        value = np.asarray(value)
        if on_extraction == "print":
            print(key)
            print(value)
            print(f"max: {value.max():.8f}; mean: {value.mean():.8f}; min: {value.min():.8f}")
            print()
        elif on_extraction in ("save_numpy", "save_pickle"):
            fpath = os.path.join(
                output_path, output_file_name(name, key, on_extraction, output_direct)
            )
            os.makedirs(os.path.dirname(fpath), exist_ok=True)
            if len(value) == 0:
                msg = f"the value is empty for {key} @ {fpath}"
                print(f"Warning: {msg}")
                warnings.append(msg)
            # write tmp + rename: a run killed mid-save must not leave a
            # truncated file that --resume would then trust as complete.
            # The tmp name carries thread id + uuid, not just pid: two
            # worker THREADS re-running a requeued video share a pid and
            # would clobber (then os.replace) each other's half-written
            # tmp file.
            tmp = (
                f"{fpath}.{os.getpid()}-{threading.get_ident()}"
                f"-{uuid.uuid4().hex[:8]}.tmp"
            )
            try:
                with open(tmp, "wb") as f:
                    if on_extraction == "save_numpy":
                        np.save(f, value)
                    else:
                        pickle.dump(value, f)
                # injected sink faults land between write and rename: the
                # worst moment — bytes on disk, nothing committed
                faults.fire("sink")
                os.replace(tmp, fpath)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        elif on_extraction == "save_jpg":
            # flow (T, 2, H, W) float -> per-pair flow_x_/flow_y_ grayscale
            # jpgs holding the uint8-quantized flow (clamp ±20, 128+255/40·f
            # — the I3D flow quantization, ref transforms.py:33-51).
            # Divergences from the reference's vestigial branch (ref
            # utils/utils.py:98-110): its `for f_num in value.shape[0]`
            # iterates an int (crash), it writes raw float arrays (junk
            # pixels), and its `<n>_x.jpg` names don't match what its own
            # flow reader globs for — files here are named
            # flow_x_<n>.jpg/flow_y_<n>.jpg so `--flow_type flow
            # --flow_dir` can consume them directly (round-trip closed).
            if value.ndim != 4 or value.shape[1] != 2:
                raise ValueError(
                    f"save_jpg needs (T, 2, H, W) flow, got {value.shape} "
                    f"for key {key!r} (use raft/pwc features)"
                )
            from PIL import Image

            from video_features_tpu.ops.preprocess import flow_quantize_uint8_np

            quant = flow_quantize_uint8_np(value)
            vdir = os.path.join(output_path, name)
            os.makedirs(vdir, exist_ok=True)
            for f_num in range(quant.shape[0]):
                for ch, axis in enumerate("xy"):
                    Image.fromarray(quant[f_num, ch], mode="L").save(
                        os.path.join(vdir, f"flow_{axis}_{f_num:0>5d}.jpg"),
                        quality=95,
                    )
        else:
            raise NotImplementedError(f"on_extraction: {on_extraction} is not implemented")
    return warnings
