"""Preflight media probe: the vouching stage in front of every decode.

The serve subsystem accepts arbitrary media by HTTP and spool, and the
batch CLI accepts whatever a manifest lists — but the decode boundary
historically trusted container metadata: a lying 8K-resolution header
could OOM the host before a single frame was rejected, and a corrupt
upload burned retries (or worse, breaker budget) discovering what one
cheap open would have told us. :func:`preflight` answers three questions
without real decode work:

- does the container open at all, and does it carry a stream of the
  kind the consumer needs (``need='video'`` or ``'audio'``)?
- is the declared metadata sane (dimensions, fps, frame count), and
  does it fit inside the declared resource caps (``--max_pixels``,
  ``--max_duration_s``, ``--max_decode_bytes``)?
- does ONE frame actually decode (the cheapest possible proof that the
  bitstream is not pure garbage behind a healthy-looking header)?

and folds the answers into a structured :class:`MediaReport` with a
three-way verdict: ``ok`` (admit), ``caution`` (admit, but record the
warnings — absent fps, insane declared frame count), or ``reject``
(permanent: HTTP 422 at serve admission, a manifest ``failed`` record
with zero retries at batch ingest).

Deliberately NOT built on io/video.py's ``_Reader``: the probe must not
open telemetry decode spans or advance ``--fault_inject decode:*``
counters (existing fault tests pin injection cadence against one reader
open per attempt), and it must stay importable without dragging the
decode-timeout machinery in. It opens cv2 directly, reads header
properties, optionally grabs one frame, and releases. Declared-metadata
caps here are the first line; io/video.py enforces the same caps again
as a running budget over ACTUAL decode, so a metadata lie that slips
past the probe still cannot blow host RAM.

No jax imports — the probe runs on HTTP handler threads and decode
workers.
"""

from __future__ import annotations

import dataclasses
import math
import os
import struct
from typing import Any, Dict, List, Optional, Tuple

from video_features_tpu.runtime.faults import MediaRejected, ResourceCapExceeded

# extensions the probe knows how to open; anything else (directories of
# pre-extracted flow frames, exotic containers) skips probing with a
# warning rather than rejecting what the decoder might still handle
VIDEO_EXTENSIONS = (
    ".mp4", ".avi", ".mkv", ".mov", ".webm", ".m4v",
    ".mpg", ".mpeg", ".wmv", ".flv", ".3gp",
)
AUDIO_EXTENSIONS = (".wav",)

# below this, declared fps is treated as ABSENT (hostile AVIs can declare
# dwScale ~2^32 -> fps ~1e-10; near-zero must trip the same recorded
# 25.0-default warning as exactly zero); above MAX_SANE_FPS it is a lie
MIN_SANE_FPS = 1e-3
MAX_SANE_FPS = 1000.0
# a declared frame count past this is header garbage, not a long video
MAX_SANE_FRAMES = 10 ** 9


@dataclasses.dataclass(frozen=True)
class ResourceCaps:
    """The three input resource caps, all optional (None = uncapped).

    ``max_pixels`` bounds one frame's width*height; ``max_duration_s``
    bounds the clip length; ``max_decode_bytes`` bounds the total RGB
    bytes a single reader may materialize (frames * w * h * 3)."""

    max_pixels: Optional[int] = None
    max_duration_s: Optional[float] = None
    max_decode_bytes: Optional[int] = None

    @classmethod
    def from_config(cls, cfg: Any) -> "ResourceCaps":
        return cls(
            max_pixels=getattr(cfg, "max_pixels", None),
            max_duration_s=getattr(cfg, "max_duration_s", None),
            max_decode_bytes=getattr(cfg, "max_decode_bytes", None),
        )

    def enabled(self) -> bool:
        return any(
            v is not None
            for v in (self.max_pixels, self.max_duration_s, self.max_decode_bytes)
        )


NO_CAPS = ResourceCaps()


@dataclasses.dataclass
class MediaReport:
    """One probed input, classified. ``verdict`` is 'ok' | 'caution' |
    'reject'; ``reason`` is set only on reject; ``warnings`` carry the
    caution findings (recorded in the manifest, never fatal).
    ``cap_exceeded`` distinguishes a resource-cap reject (raises
    :class:`ResourceCapExceeded`) from a bad-media reject (raises
    :class:`MediaRejected`)."""

    path: str
    need: str = "video"
    verdict: str = "ok"
    reason: Optional[str] = None
    warnings: List[str] = dataclasses.field(default_factory=list)
    container: Optional[str] = None  # 'video' | 'wav' | None (unprobed)
    width: int = 0
    height: int = 0
    fps: float = 0.0
    frame_count: int = 0
    duration_s: Optional[float] = None
    size_bytes: int = 0
    first_frame_ok: Optional[bool] = None  # None = check not performed
    cap_exceeded: bool = False

    def _reject(self, reason: str, cap: bool = False) -> "MediaReport":
        self.verdict = "reject"
        self.reason = reason
        self.cap_exceeded = cap
        return self

    def _finish(self) -> "MediaReport":
        if self.verdict != "reject":
            self.verdict = "caution" if self.warnings else "ok"
        return self

    def to_error(self) -> Exception:
        """The taxonomy exception for a reject verdict (permanent,
        input-classified either way); raises nothing itself."""
        cls = ResourceCapExceeded if self.cap_exceeded else MediaRejected
        exc = cls(f"preflight rejected {self.path}: {self.reason}")
        exc.stage = "preflight"
        return exc

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _sniff_riff_wave(path: str) -> bool:
    """True when the file's magic says RIFF/WAVE — an audio container no
    matter what its extension claims (.avi is RIFF too, but tags 'AVI ')."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(12)
    except OSError:
        return False
    return len(head) == 12 and head[:4] == b"RIFF" and head[8:12] == b"WAVE"


def _probe_wav(report: MediaReport, caps: ResourceCaps) -> MediaReport:
    """Walk the RIFF chunks of a wav: fmt gives sample rate/byte rate,
    data gives payload size — enough for duration and byte caps without
    reading the samples (scipy's reader would load everything)."""
    report.container = "wav"
    sample_rate = byte_rate = data_bytes = 0
    try:
        with open(report.path, "rb") as fh:
            fh.seek(12)  # past RIFF<size>WAVE
            while True:
                hdr = fh.read(8)
                if len(hdr) < 8:
                    break
                tag, size = hdr[:4], struct.unpack("<I", hdr[4:])[0]
                if tag == b"fmt " and size >= 16:
                    fmt = fh.read(size)
                    _, channels, sample_rate, byte_rate = struct.unpack(
                        "<HHII", fmt[:12]
                    )
                elif tag == b"data":
                    data_bytes = size
                    break
                else:
                    fh.seek(size + (size & 1), os.SEEK_CUR)
    except (OSError, struct.error) as exc:
        return report._reject(f"unparseable wav header ({exc})")
    if sample_rate <= 0 or data_bytes <= 0:
        return report._reject(
            f"wav has no decodable audio (sample_rate={sample_rate}, "
            f"data_bytes={data_bytes})"
        )
    report.fps = float(sample_rate)
    if byte_rate > 0:
        report.duration_s = data_bytes / byte_rate
    if caps.max_duration_s is not None and report.duration_s is not None \
            and report.duration_s > caps.max_duration_s:
        return report._reject(
            f"declared audio duration {report.duration_s:.1f}s exceeds "
            f"--max_duration_s {caps.max_duration_s:g}", cap=True,
        )
    if caps.max_decode_bytes is not None and data_bytes > caps.max_decode_bytes:
        return report._reject(
            f"declared audio payload {data_bytes} bytes exceeds "
            f"--max_decode_bytes {caps.max_decode_bytes}", cap=True,
        )
    report.first_frame_ok = True
    return report._finish()


def _read_video_header(path: str) -> Tuple[Any, Dict[str, float]]:
    import cv2

    cap = cv2.VideoCapture(str(path))
    if not cap.isOpened():
        cap.release()
        return None, {}
    meta = {
        "fps": cap.get(cv2.CAP_PROP_FPS) or 0.0,
        "frame_count": cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0.0,
        "width": cap.get(cv2.CAP_PROP_FRAME_WIDTH) or 0.0,
        "height": cap.get(cv2.CAP_PROP_FRAME_HEIGHT) or 0.0,
    }
    return cap, meta


def _probe_video(
    report: MediaReport, caps: ResourceCaps, first_frame: bool
) -> MediaReport:
    cap, meta = _read_video_header(report.path)
    if cap is None:
        return report._reject("container does not open (no decodable video stream)")
    try:
        report.container = "video"
        fps = float(meta["fps"])
        if not math.isfinite(fps) or fps < MIN_SANE_FPS:
            fps = 0.0
        report.width = int(meta["width"])
        report.height = int(meta["height"])
        raw_count = meta["frame_count"]
        if not math.isfinite(raw_count) or not (0 <= raw_count <= MAX_SANE_FRAMES):
            report.warnings.append(
                f"declared frame count is insane ({raw_count:g}); treating as unknown"
            )
            report.frame_count = 0
        else:
            report.frame_count = int(raw_count)
        if fps == 0.0:
            report.warnings.append(
                "fps metadata absent or ~zero; decode will assume 25.0"
            )
        elif fps > MAX_SANE_FPS:
            report.warnings.append(f"declared fps is insane ({fps:g})")
        report.fps = fps
        if report.width <= 0 or report.height <= 0:
            report.warnings.append("declared frame dimensions missing from header")
        eff_fps = fps if 0.0 < fps <= MAX_SANE_FPS else 25.0
        if report.frame_count > 0:
            report.duration_s = report.frame_count / eff_fps

        # declared-metadata caps: the cheap half of the resource guard
        # (io/video.py re-enforces over actual decode)
        pixels = report.width * report.height
        if caps.max_pixels is not None and pixels > caps.max_pixels:
            return report._reject(
                f"declared frame size {report.width}x{report.height} "
                f"({pixels} pixels) exceeds --max_pixels {caps.max_pixels}",
                cap=True,
            )
        if caps.max_duration_s is not None and report.duration_s is not None \
                and report.duration_s > caps.max_duration_s:
            return report._reject(
                f"declared duration {report.duration_s:.1f}s "
                f"({report.frame_count} frames at {eff_fps:g} fps) exceeds "
                f"--max_duration_s {caps.max_duration_s:g}", cap=True,
            )
        if caps.max_decode_bytes is not None and report.frame_count > 0 and pixels > 0:
            declared_bytes = report.frame_count * pixels * 3
            if declared_bytes > caps.max_decode_bytes:
                return report._reject(
                    f"declared decode size {declared_bytes} bytes "
                    f"({report.frame_count} frames x {report.width}x"
                    f"{report.height}x3) exceeds --max_decode_bytes "
                    f"{caps.max_decode_bytes}", cap=True,
                )

        if first_frame:
            ok = bool(cap.grab())
            report.first_frame_ok = ok
            if not ok:
                return report._reject(
                    "no decodable frames (first frame does not decode)"
                )
    finally:
        cap.release()
    return report._finish()


def preflight(
    path: str,
    need: str = "video",
    caps: Optional[ResourceCaps] = None,
    first_frame: bool = True,
) -> MediaReport:
    """Probe one input and classify it. Never raises for bad media —
    the verdict IS the answer (use :func:`preflight_or_raise` for the
    exception-shaped form the extract pipeline wants)."""
    caps = caps or NO_CAPS
    report = MediaReport(path=str(path), need=need)
    if not os.path.exists(path):
        return report._reject("file does not exist")
    if os.path.isdir(path):
        # pre-extracted flow-frame directories and the like: nothing to
        # probe, and rejecting them would break legitimate inputs
        report.warnings.append("directory input; media preflight skipped")
        return report._finish()
    report.size_bytes = os.path.getsize(path)
    if report.size_bytes == 0:
        return report._reject("empty file (0 bytes)")

    ext = os.path.splitext(path)[1].lower()
    is_wave = ext in AUDIO_EXTENSIONS or _sniff_riff_wave(path)
    if need == "audio":
        if is_wave:
            return _probe_wav(report, caps)
        # a video container bound for the audio path: the container must
        # at least open; audio-stream presence is only provable with an
        # ffmpeg probe, so decode-time classification (io/audio.py)
        # carries that part of the contract
        report.warnings.append(
            "audio stream presence not verifiable without decode; "
            "container checked as video only"
        )
        return _probe_video(report, caps, first_frame)
    if is_wave:
        return report._reject("audio-only container (RIFF/WAVE): no video stream")
    if ext not in VIDEO_EXTENSIONS:
        report.warnings.append(
            f"unrecognized extension {ext or '(none)'}; media preflight skipped"
        )
        return report._finish()
    return _probe_video(report, caps, first_frame)


def preflight_or_raise(
    path: str,
    need: str = "video",
    caps: Optional[ResourceCaps] = None,
    first_frame: bool = True,
) -> MediaReport:
    """:func:`preflight`, raising the taxonomy exception on reject —
    :class:`ResourceCapExceeded` for cap busts, :class:`MediaRejected`
    otherwise (both permanent, both input-classified; the manifest gets
    the probe's precise reason and zero retries are burned)."""
    report = preflight(path, need=need, caps=caps, first_frame=first_frame)
    if report.verdict == "reject":
        raise report.to_error()
    return report
