"""Audio input: wav reading + resampling on the host.

The reference reads wav via soundfile, normalizes int16 by 32768, mixes to
mono, and resamples to 16 kHz with resampy (ref
models/vggish/vggish_src/vggish_input.py:74-87 and :57-60). Neither
soundfile nor resampy is assumed here: wav decode uses scipy.io.wavfile
and resampling uses a polyphase filter (scipy.signal.resample_poly), which
is the same class of kaiser-windowed sinc resampler resampy implements.

For videos, the wav is ripped via io.ffmpeg when an ffmpeg binary exists;
``.wav`` inputs are consumed directly either way.
"""

from __future__ import annotations

import math
import os
from typing import Tuple

import numpy as np
from scipy.io import wavfile
from scipy.signal import resample_poly


def read_wav(path: str) -> Tuple[np.ndarray, int]:
    """-> (float32 samples in [-1, 1], shape (n,) or (n, ch); sample rate)."""
    sr, data = wavfile.read(path)
    if data.dtype == np.int16:
        data = data / 32768.0
    elif data.dtype == np.int32:
        data = data / 2147483648.0
    elif data.dtype == np.uint8:
        data = (data.astype(np.float32) - 128.0) / 128.0
    data = np.asarray(data, dtype=np.float32)
    return data, int(sr)


def to_mono(data: np.ndarray) -> np.ndarray:
    return data.mean(axis=1) if data.ndim > 1 else data


def resample(data: np.ndarray, src_sr: int, dst_sr: int) -> np.ndarray:
    """Polyphase resampling src_sr -> dst_sr along axis 0."""
    if src_sr == dst_sr:
        return data
    g = math.gcd(int(src_sr), int(dst_sr))
    return resample_poly(data, dst_sr // g, src_sr // g, axis=0).astype(np.float32)


def load_audio_for_model(
    path: str,
    target_sr: int,
    tmp_path: str = "./tmp",
    keep_tmp_files: bool = False,
) -> np.ndarray:
    """Full audio front door: wav/video path -> mono float32 at target_sr.

    Video containers are ripped to wav via ffmpeg into ``tmp_path``; the
    temp wav/aac are deleted afterwards unless ``keep_tmp_files`` (the
    reference's --keep_tmp_files contract, ref main.py:108-109).
    """
    tmp_files = []
    if not path.lower().endswith(".wav"):
        from video_features_tpu.io.ffmpeg import extract_wav_from_video

        path, aac = extract_wav_from_video(path, tmp_path)
        tmp_files = [path, aac]
    try:
        data, sr = read_wav(path)
    finally:
        if not keep_tmp_files:
            for f in tmp_files:
                try:
                    os.remove(f)
                except OSError:
                    pass
    return resample(to_mono(data), sr, target_sr)
