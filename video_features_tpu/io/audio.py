"""Audio input: wav reading + resampling on the host.

The reference reads wav via soundfile, normalizes int16 by 32768, mixes to
mono, and resamples to 16 kHz with resampy's ``kaiser_best`` windowed
sinc (ref models/vggish/vggish_src/vggish_input.py:74-87 and :48).
Neither soundfile nor resampy is assumed here: wav decode uses
scipy.io.wavfile, and the resampler is a NATIVE implementation of the
same published kaiser_best algorithm (Smith's windowed-sinc
interpolation with resampy 0.2.x's exact filter parameters), vectorized
as a phase-decomposed polyphase matmul. The r4 advisor-era scipy
``resample_poly`` substitute measured a 2.6e-3 relative-L2 drift on
final VGGish embeddings — past the framework's 1e-3 budget — so the
reference's resampler is reproduced exactly instead
(tests/test_vggish.py pins parity against an independent per-sample
re-derivation of the algorithm).

For videos, the wav is ripped via io.ffmpeg when an ffmpeg binary exists;
``.wav`` inputs are consumed directly either way.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, Tuple

import numpy as np
from scipy.io import wavfile

from video_features_tpu.runtime.faults import AudioDecodeError, MissingStreamError

# resampy.filters.sinc_window('kaiser_best') parameters: 64 zero
# crossings sampled at 2**9 points each, Kaiser beta tuned for ~-96 dB
# stopband, cutoff rolled off to 0.9476 of Nyquist
_NUM_ZEROS = 64
_PRECISION = 9
_ROLLOFF = 0.9475937167399596
_BETA = 14.769656459379492

# ffmpeg stderr fragments that mean "this container has no audio track"
# — the one rip failure that deserves its own precise reason instead of
# the generic corrupt-audio classification
_NO_AUDIO_MARKERS = (
    "does not contain any stream",
    "Stream map 'a' matches no streams",
    "matches no streams",
)


def read_wav(path: str) -> Tuple[np.ndarray, int]:
    """-> (float32 samples in [-1, 1], shape (n,) or (n, ch); sample rate).

    Parse failures raise :class:`AudioDecodeError` (permanent,
    input-classified) rather than letting scipy's bare ValueError escape
    into the retry machinery as a maybe-transient unknown."""
    try:
        sr, data = wavfile.read(path)
    except (ValueError, EOFError) as exc:
        # scipy raises bare ValueError for bad bytes; OSErrors (missing
        # file, I/O flake) pass through and stay transient-classifiable
        raise AudioDecodeError(
            f"unparseable wav ({type(exc).__name__}: {exc}): {path}"
        ) from exc
    if data.dtype == np.int16:
        data = data / 32768.0
    elif data.dtype == np.int32:
        data = data / 2147483648.0
    elif data.dtype == np.uint8:
        data = (data.astype(np.float32) - 128.0) / 128.0
    data = np.asarray(data, dtype=np.float32)
    return data, int(sr)


def to_mono(data: np.ndarray) -> np.ndarray:
    return data.mean(axis=1) if data.ndim > 1 else data


def _sinc_window() -> np.ndarray:
    """Right half of the kaiser_best sinc table (resampy.filters)."""
    num_bits = 2 ** _PRECISION
    n = num_bits * _NUM_ZEROS
    taps = np.arange(n + 1) / num_bits  # 0 .. num_zeros inclusive
    sinc = _ROLLOFF * np.sinc(_ROLLOFF * taps)
    window = np.kaiser(2 * n + 1, _BETA)[n:]
    return sinc * window


# (src_sr, dst_sr) -> (per-phase weight matrix rows, left extents, window len)
# VGGish prepare runs on --decode_workers threads, so the cache insert is
# lock-guarded; a racing miss at worst recomputes the same taps.
_PHASE_CACHE: Dict[Tuple[int, int], tuple] = {}
_PHASE_LOCK = threading.Lock()


def _phase_filters(src_sr: int, dst_sr: int):
    """Precompute kaiser_best tap weights per output phase.

    With rational ratio L/M (L = dst/g, M = src/g) the fractional
    position of output sample t against the input grid repeats every L
    outputs, so the interpolated-table weights resampy computes per
    sample (resampy.interpn) collapse to L fixed FIR vectors — the
    windowed-sinc equivalent of a polyphase bank. Output t (phase
    p = t mod L, block j = t // L) reads the contiguous input window
    ``x[n - left_p : n - left_p + width_p]`` with ``n = (p*M)//L + j*M``;
    each phase's outputs are then one strided-gather matmul.
    """
    key = (int(src_sr), int(dst_sr))
    if key in _PHASE_CACHE:
        return _PHASE_CACHE[key]
    g = math.gcd(*key)
    L, M = key[1] // g, key[0] // g
    ratio = L / M
    win = _sinc_window()
    if ratio < 1:
        win = win * ratio
    delta = np.diff(win, append=0.0)
    num_bits = 2 ** _PRECISION
    scale = min(1.0, ratio)
    index_step = int(scale * num_bits)

    weights = []  # per phase: (left_taps_reversed ++ right_taps)
    lefts = []
    for p in range(L):
        time = p * M / L
        n = (p * M) // L
        # left wing: taps for x[n], x[n-1], ...
        frac = scale * (time - n)
        index_frac = frac * num_bits
        offset = int(index_frac)
        eta = index_frac - offset
        i_max = (len(win) - offset) // index_step
        idx = offset + index_step * np.arange(i_max)
        w_left = win[idx] + eta * delta[idx]
        # right wing: taps for x[n+1], x[n+2], ...
        frac = scale - frac
        index_frac = frac * num_bits
        offset = int(index_frac)
        eta = index_frac - offset
        k_max = (len(win) - offset) // index_step
        idx = offset + index_step * np.arange(k_max)
        w_right = win[idx] + eta * delta[idx]
        weights.append(np.concatenate([w_left[::-1], w_right]))
        lefts.append(i_max - 1)  # window starts at x[n - (i_max-1)]

    width = max(len(w) for w in weights)
    wmat = np.zeros((L, width))
    for p, w in enumerate(weights):
        wmat[p, : len(w)] = w
    out = (wmat, np.asarray(lefts), L, M)
    with _PHASE_LOCK:
        _PHASE_CACHE[key] = out
    return out


def resample(data: np.ndarray, src_sr: int, dst_sr: int) -> np.ndarray:
    """resampy-kaiser_best-exact resampling along axis 0 (1-D or (n, ch)).

    Boundary truncation matches resampy: taps that fall outside the
    signal contribute zero (the zero-padded gather reproduces interpn's
    wing clipping exactly).
    """
    if src_sr == dst_sr:
        return data
    x = np.asarray(data, dtype=np.float64)
    squeeze = x.ndim == 1
    if squeeze:
        x = x[:, None]
    wmat, lefts, L, M = _phase_filters(src_sr, dst_sr)
    n_in = x.shape[0]
    # resampy 0.2.x sizes the output as int(n * sample_ratio) — i.e.
    # FLOOR, not ceil (resampy.core.resample); one extra trailing sample
    # would shift VGGish's 0.96 s frame count on boundary-length clips.
    # Integer arithmetic = the exact floor, immune to float rounding.
    n_out = (n_in * int(dst_sr)) // int(src_sr)
    width = wmat.shape[1]
    pad_lo = int(lefts.max())
    xp = np.pad(x, ((pad_lo, width + M), (0, 0)))

    out = np.empty((n_out, x.shape[1]), dtype=np.float64)
    # one matmul per phase: rows are the strided windows of x this
    # phase's outputs read; all windows share the phase's FIR vector.
    # Window starts advance by exactly M per output within a phase, so
    # windows[base::M] is a strided VIEW (no per-row gather copy) and
    # the einsum runs straight off it.
    windows = np.lib.stride_tricks.sliding_window_view(xp, width, axis=0)
    for p in range(L):
        count = len(range(p, n_out, L))
        if not count:
            continue
        base = (p * M) // L - lefts[p] + pad_lo
        # sliding_window_view appends the window axis last: (t, ch, w)
        out[p::L] = np.einsum(
            "tsw,w->ts", windows[base::M][:count], wmat[p]
        )
    out = out.astype(np.float32)
    return out[:, 0] if squeeze else out


def load_audio_for_model(
    path: str,
    target_sr: int,
    tmp_path: str = "./tmp",
    keep_tmp_files: bool = False,
) -> np.ndarray:
    """Full audio front door: wav/video path -> mono float32 at target_sr.

    Video containers are ripped to wav via ffmpeg into ``tmp_path``; the
    temp wav/aac are deleted afterwards unless ``keep_tmp_files`` (the
    reference's --keep_tmp_files contract, ref main.py:108-109).
    """
    tmp_files = []
    if not path.lower().endswith(".wav"):
        from video_features_tpu.io.ffmpeg import extract_wav_from_video

        src = path
        try:
            path, aac = extract_wav_from_video(path, tmp_path)
        except RuntimeError as exc:
            msg = str(exc)
            if "ffmpeg binary" in msg or "binary not found" in msg:
                raise  # missing tool is an environment problem, not bad media
            # the rip subprocess died on the bitstream: classify it
            if any(m in msg for m in _NO_AUDIO_MARKERS):
                raise MissingStreamError(
                    f"no audio stream in container: {src}"
                ) from exc
            raise AudioDecodeError(
                f"audio rip failed on the bitstream: {src}: {msg[:300]}"
            ) from exc
        tmp_files = [path, aac]
    try:
        data, sr = read_wav(path)
    finally:
        if not keep_tmp_files:
            for f in tmp_files:
                try:
                    os.remove(f)
                except OSError:
                    pass
    return resample(to_mono(data), sr, target_sr)
