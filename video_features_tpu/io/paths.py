"""Path-list forming and sliding-window slice math.

Behavior mirrors ref utils/utils.py:117-126 (form_slices) and :153-204
(form_list_from_user_input): the video list IS the dataset; a path entry is
either a video path or a ``(video_path, flow_dir_for_video)`` pair when
pre-extracted flow is consumed.
"""

from __future__ import annotations

import os
import pathlib
from typing import List, Tuple, Union

PathEntry = Union[str, Tuple[str, str]]


def form_slices(size: int, stack_size: int, step_size: int) -> List[Tuple[int, int]]:
    """(start, end) index windows over ``size`` frames; drops the ragged tail,
    exactly like ref utils/utils.py:117-126."""
    slices = []
    full_stack_num = (size - stack_size) // step_size + 1
    for i in range(full_stack_num):
        start = i * step_size
        slices.append((start, start + stack_size))
    return slices


def form_list_from_user_input(cfg) -> List[PathEntry]:
    """Resolve the user's input selection into a list of path entries.

    Precedence and pairing rules follow ref utils/utils.py:153-204:
    file-with-paths > video_dir (zipped with flow_dir by sorted stem) >
    explicit video_paths (zipped with flow_paths by stem).
    """
    if cfg.file_with_video_paths is not None:
        with open(cfg.file_with_video_paths) as rfile:
            path_list: List[PathEntry] = [
                line.strip() for line in rfile.readlines() if line.strip()
            ]
    elif cfg.video_dir is not None:
        if cfg.flow_dir is None:
            path_list = sorted(str(p) for p in pathlib.Path(cfg.video_dir).glob("*"))
        else:
            v_list = sorted(pathlib.Path(cfg.video_dir).glob("*"), key=lambda x: x.stem)
            f_list = sorted(pathlib.Path(cfg.flow_dir).glob("*"), key=lambda x: x.stem)
            path_list = [
                (str(v), str(f))
                for v, f in zip(v_list, f_list)
                if v.stem == f.stem
            ]
    elif cfg.video_paths is not None:
        if cfg.flow_paths is None:
            path_list = list(cfg.video_paths)
        else:
            path_list = [
                (v, f)
                for v, f in zip(cfg.video_paths, cfg.flow_paths)
                if pathlib.Path(v).stem == pathlib.Path(f).stem
            ]
    else:
        raise ValueError("no video provided")

    for entry in path_list:
        paths = entry if isinstance(entry, tuple) else (entry,)
        for p in paths:
            if not os.path.exists(p):
                raise ValueError(f"path does not exist: {p}")

    return path_list


def video_path_of(entry: PathEntry) -> str:
    return entry[0] if isinstance(entry, (tuple, list)) else entry
