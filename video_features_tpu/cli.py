"""CLI entry point: ``video-features-tpu --feature_type <X> ...``
(or ``python main.py ...`` via the repo-root shim).

Drop-in surface for the reference CLI (ref main.py:94-149): same flags,
same feature types, same output contract. ``--device_ids`` indexes
``jax.devices()`` (TPU chips under TPU runtimes); ``--cpu`` forces the CPU
backend. Dispatch goes through one code path — the dynamic work-queue
scheduler — for both single- and multi-device runs.
"""

import sys

from video_features_tpu.config import enable_compile_cache, parse_args
from video_features_tpu.extract.registry import build_extractor
from video_features_tpu.parallel.devices import resolve_devices
from video_features_tpu.parallel.scheduler import (
    mesh_feature_extraction,
    parallel_feature_extraction,
)


def main(argv=None) -> None:
    import os

    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # the long-lived daemon (video_features_tpu/serve/): loads models
        # once, keeps executables warm, serves requests over HTTP and/or
        # a spool dir. `serve warmup ...` runs the preflight and exits.
        from video_features_tpu.serve.daemon import serve_main

        return serve_main(argv[1:])
    cfg = parse_args(argv)
    # before any device/compile touch, so every executable (including the
    # --preprocess device bucket grid) can hit/populate the on-disk cache
    enable_compile_cache(cfg)

    # Multi-host slices: when a launcher provides a coordinator (e.g.
    # JAX_COORDINATOR_ADDRESS on a TPU pod), join the distributed runtime
    # before touching devices — jax.devices() then spans hosts and a
    # --sharding mesh rides ICI for collectives, DCN for dispatch. After
    # arg validation (a --help/typo run must not block on the barrier),
    # never for --cpu, and only once per process (initialize is once-only).
    if os.environ.get("JAX_COORDINATOR_ADDRESS") and not cfg.cpu:
        import jax

        if not getattr(main, "_distributed_initialized", False):
            jax.distributed.initialize()
            main._distributed_initialized = True
    if cfg.on_extraction in ("save_numpy", "save_pickle"):
        print(f"Saving features to {cfg.output_path}")
    if cfg.keep_tmp_files:
        print(f"Keeping temp files in {cfg.tmp_path}")

    extractor = build_extractor(cfg)
    devices = resolve_devices(cfg)
    try:
        if cfg.sharding == "mesh":
            mesh_feature_extraction(extractor, devices)
        else:
            parallel_feature_extraction(extractor, devices)
    finally:
        # merge every process's JSONL events into _manifest/summary.json
        # and print the one-line outcome — even when the scheduler raised,
        # so a crashed run still leaves a machine-readable record of what
        # completed (docs/robustness.md). Gated on this run actually
        # recording (print-mode ad-hoc runs have no manifest dir).
        summary = None
        # final telemetry drain BEFORE the merge so the summary's
        # metrics/throughput block (and the digest line below) reflect
        # the whole run — including a run the scheduler aborted
        extractor.telemetry.close()
        if getattr(extractor.manifest, "path", None) is not None:
            from video_features_tpu.runtime.faults import finalize_run, format_summary

            summary = finalize_run(cfg.output_path)
            if summary is not None:
                print(format_summary(summary))
    if cfg.strict and summary is not None:
        from video_features_tpu.runtime.faults import strict_failures

        problems = strict_failures(summary)
        if problems:
            raise SystemExit(
                "--strict: run completed with "
                + f"{len(problems)} problem(s):\n  "
                + "\n  ".join(problems)
            )


if __name__ == "__main__":
    main(sys.argv[1:])
