"""CLI entry point: ``video-features-tpu --feature_type <X> ...``
(or ``python main.py ...`` via the repo-root shim).

Drop-in surface for the reference CLI (ref main.py:94-149): same flags,
same feature types, same output contract. ``--device_ids`` indexes
``jax.devices()`` (TPU chips under TPU runtimes); ``--cpu`` forces the CPU
backend. Dispatch goes through one code path — the dynamic work-queue
scheduler — for both single- and multi-device runs.
"""

import sys

from video_features_tpu.config import (
    enable_compile_cache,
    parse_batch_args,
    sanity_check,
)
from video_features_tpu.extract.registry import build_extractor
from video_features_tpu.parallel.devices import resolve_devices
from video_features_tpu.parallel.scheduler import (
    mesh_feature_extraction,
    parallel_feature_extraction,
)


def main(argv=None) -> None:
    import os

    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # the long-lived daemon (video_features_tpu/serve/): loads models
        # once, keeps executables warm, serves requests over HTTP and/or
        # a spool dir. `serve warmup ...` runs the preflight and exits.
        from video_features_tpu.serve.daemon import serve_main

        return serve_main(argv[1:])
    cfg, feature_types = parse_batch_args(argv)
    # before any device/compile touch, so every executable (including the
    # --preprocess device bucket grid) can hit/populate the on-disk cache
    enable_compile_cache(cfg)

    # Multi-host slices: when a launcher provides a coordinator (e.g.
    # JAX_COORDINATOR_ADDRESS on a TPU pod), join the distributed runtime
    # before touching devices — jax.devices() then spans hosts and a
    # --sharding mesh rides ICI for collectives, DCN for dispatch. After
    # arg validation (a --help/typo run must not block on the barrier),
    # never for --cpu, and only once per process (initialize is once-only).
    if os.environ.get("JAX_COORDINATOR_ADDRESS") and not cfg.cpu:
        import jax

        if not getattr(main, "_distributed_initialized", False):
            jax.distributed.initialize()
            main._distributed_initialized = True
    if cfg.on_extraction in ("save_numpy", "save_pickle"):
        print(f"Saving features to {cfg.output_path}")
    if cfg.keep_tmp_files:
        print(f"Keeping temp files in {cfg.tmp_path}")

    # multi-model runs (--feature_types A B ...) install the shared-decode
    # frame cache for the whole loop: model A's pass decodes each clip
    # once, every later model replays the cached frames (extract/plan.py)
    from video_features_tpu.extract.plan import shared_frame_cache

    summary = None
    wrote_manifest = False
    try:
        with shared_frame_cache(cfg, feature_types):
            for ft in feature_types:
                fcfg = (
                    cfg
                    if ft == cfg.feature_type
                    else sanity_check(cfg.replace(feature_type=ft))
                )
                extractor = build_extractor(fcfg)
                devices = resolve_devices(fcfg)
                try:
                    if fcfg.sharding == "mesh":
                        mesh_feature_extraction(extractor, devices)
                    else:
                        parallel_feature_extraction(extractor, devices)
                finally:
                    # final telemetry drain BEFORE the manifest merge so
                    # the summary's metrics/throughput block reflects the
                    # whole run — including a run the scheduler aborted
                    extractor.telemetry.close()
                    wrote_manifest |= (
                        getattr(extractor.manifest, "path", None) is not None
                    )
    finally:
        # merge every process's JSONL events into _manifest/summary.json
        # and print the one-line outcome — even when the scheduler raised,
        # so a crashed run still leaves a machine-readable record of what
        # completed (docs/robustness.md). One <output>/_manifest covers
        # the whole multi-feature tree, so ONE merge at the end covers
        # every model's pass. Gated on this run actually recording
        # (print-mode ad-hoc runs have no manifest dir).
        if wrote_manifest:
            from video_features_tpu.runtime.faults import finalize_run, format_summary

            summary = finalize_run(cfg.output_path)
            if summary is not None:
                print(format_summary(summary))
    if cfg.strict and summary is not None:
        from video_features_tpu.runtime.faults import strict_failures

        problems = strict_failures(summary)
        if problems:
            raise SystemExit(
                "--strict: run completed with "
                + f"{len(problems)} problem(s):\n  "
                + "\n  ".join(problems)
            )


if __name__ == "__main__":
    main(sys.argv[1:])
