"""Request lifecycle: the manifest-backed record every serve request gets.

A batch run's unit of record is the video (runtime/faults.py manifest);
the daemon's unit of record is the *request* — same video, different
identity: two users asking for the same clip are two requests, and each
one must end in a queryable terminal state. States:

    queued -> dispatched -> done | failed
    queued -> rejected                      (backpressure / bad input)

Every transition is appended to a :class:`~video_features_tpu.runtime.
faults.RunManifest` rooted at ``<output>/_requests`` (so the extraction
manifest under ``<output>/_manifest`` stays purely per-video), and the
terminal state is additionally written as ``<output>/_requests/<id>.json``
— the durable per-request result record the status endpoint serves after
the in-memory map forgets (daemon restart). Failure records reuse the
``classify_error`` taxonomy from runtime/faults.py, so a request that
died of a transient decode flake reads exactly like the batch manifest
would read it.

No jax imports; everything here runs on source/HTTP threads.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from video_features_tpu.runtime.faults import RunManifest

REQUESTS_DIRNAME = "_requests"

# queued/dispatched are transitional; done/failed/rejected are terminal
# (merge_manifest treats all three as terminal when folding the request
# manifest, so a restart never resurrects a rejected request as live).
REQUEST_STATES = ("queued", "dispatched", "done", "failed", "rejected")
TERMINAL_STATES = ("done", "failed", "rejected")

# request ids become result filenames: constrain them so a hostile id
# can never traverse out of _requests/ (the HTTP source accepts ids)
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,99}$")

# the admission key's catch-all bucket for requests that do not declare
# one: they still coalesce with each other (the extractor's own agg_key
# keeps truly mixed shapes out of one fused dispatch)
DEFAULT_BUCKET = "~"


class BadRequest(ValueError):
    """Malformed request payload (unknown feature type, missing path,
    unsafe id). Permanent by nature: re-sending the same bytes fails
    the same way."""


@dataclasses.dataclass
class ExtractionRequest:
    """One admitted unit of work. ``bucket`` is the client's spatial-
    bucket hint — the coalescing half of the admission key; the fused
    dispatch itself is still guarded by the extractor's ``agg_key``, so
    a wrong hint costs batching efficiency, never correctness."""

    feature_type: str
    video_path: str
    id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:12])
    bucket: str = DEFAULT_BUCKET
    source: str = "local"  # http | spool | warmup | local
    received_ts: float = dataclasses.field(default_factory=time.time)

    def key(self) -> Tuple[str, str]:
        """The admission-control key: same-(feature_type, bucket)
        requests may coalesce into one fused --video_batch group."""
        return (self.feature_type, self.bucket)


def parse_request(payload: Dict[str, Any], source: str) -> ExtractionRequest:
    """Validate one request dict (HTTP body or spool file) into an
    :class:`ExtractionRequest`; raises :class:`BadRequest` naming the
    problem (the sources turn that into 400 / a rejected record)."""
    if not isinstance(payload, dict):
        raise BadRequest(f"request body must be a JSON object, got {type(payload).__name__}")
    ft = payload.get("feature_type")
    if not ft or not isinstance(ft, str):
        raise BadRequest("missing 'feature_type'")
    video = payload.get("video_path")
    if not video or not isinstance(video, str):
        raise BadRequest("missing 'video_path'")
    kw: Dict[str, Any] = {"feature_type": ft, "video_path": video, "source": source}
    rid = payload.get("id")
    if rid is not None:
        if not isinstance(rid, str) or not _ID_RE.match(rid):
            raise BadRequest(
                "bad 'id': need 1-100 chars of [A-Za-z0-9._-] starting alphanumeric"
            )
        kw["id"] = rid
    bucket = payload.get("bucket")
    if bucket is not None:
        if not isinstance(bucket, str) or len(bucket) > 32:
            raise BadRequest("bad 'bucket': expected a short string like '640x480'")
        kw["bucket"] = bucket
    return ExtractionRequest(**kw)


def requests_root(output_root: str) -> str:
    return os.path.join(output_root, REQUESTS_DIRNAME)


class RequestTracker:
    """Thread-safe request registry + the manifest/result-file writers.

    Sources admit from their own threads, the batcher's dispatcher
    transitions from its thread, and the status endpoint reads from HTTP
    handler threads — one lock covers the in-memory map; the manifest
    has its own (runtime/faults.py)."""

    def __init__(self, output_root: str, telemetry: Any = None) -> None:
        self.output_root = output_root
        self.results_dir = requests_root(output_root)
        self.manifest = RunManifest(self.results_dir)
        self.telemetry = telemetry
        self._lock = threading.Lock()
        self._records: Dict[str, Dict[str, Any]] = {}
        self._spans: Dict[str, Any] = {}  # request id -> open telemetry token

    # -- transitions ----------------------------------------------------

    def admit(self, req: ExtractionRequest) -> Dict[str, Any]:
        rec = {
            "id": req.id,
            "state": "queued",
            "feature_type": req.feature_type,
            "video_path": req.video_path,
            "bucket": req.bucket,
            "source": req.source,
            "received_ts": round(req.received_ts, 4),
        }
        with self._lock:
            if req.id in self._records:
                raise BadRequest(f"duplicate request id {req.id!r}")
            self._records[req.id] = rec
        self._count("requests_admitted")
        if self.telemetry is not None and self.telemetry.enabled:
            token = self.telemetry.begin(
                "request", video=req.video_path, request=req.id,
                feature_type=req.feature_type, bucket=req.bucket,
            )
            if token is not None:
                with self._lock:
                    self._spans[req.id] = token
        self.manifest.record(
            f"request:{req.id}", "queued",
            feature_type=req.feature_type, video_path=req.video_path,
            bucket=req.bucket, source=req.source,
        )
        return dict(rec)

    def dispatched(self, req: ExtractionRequest, group_size: int) -> None:
        with self._lock:
            rec = self._records.get(req.id)
            if rec is not None:
                rec["state"] = "dispatched"
                rec["group_size"] = int(group_size)
        self.manifest.record(
            f"request:{req.id}", "dispatched", group_size=int(group_size)
        )

    def finish(
        self,
        req: ExtractionRequest,
        status: str,
        error_class: Optional[str] = None,
        error_type: Optional[str] = None,
        message: Optional[str] = None,
        features: Optional[List[str]] = None,
    ) -> Dict[str, Any]:
        """Terminal transition (done/failed/rejected): update the map,
        append the manifest record, write the durable result JSON, and
        close the request telemetry span."""
        assert status in TERMINAL_STATES, status
        with self._lock:
            rec = self._records.get(req.id)
            if rec is None:
                rec = {"id": req.id, "video_path": req.video_path,
                       "feature_type": req.feature_type, "bucket": req.bucket}
                self._records[req.id] = rec
            rec["state"] = status
            rec["finished_ts"] = round(time.time(), 4)
            rec["wall_s"] = round(rec["finished_ts"] - rec.get("received_ts", rec["finished_ts"]), 4)
            if error_class is not None:
                rec["error_class"] = error_class
            if error_type is not None:
                rec["error_type"] = error_type
            if message is not None:
                rec["message"] = str(message)[:500]
            if features is not None:
                rec["features"] = list(features)
            out = dict(rec)
            token = self._spans.pop(req.id, None)
        if token is not None:
            token.finish(state=status)
        self._count(f"requests_{status}")
        extra = {
            k: out[k]
            for k in ("error_class", "error_type", "message", "wall_s")
            if k in out
        }
        self.manifest.record(f"request:{req.id}", status, **extra)
        self._write_result(out)
        return out

    def forget(self, req: ExtractionRequest) -> None:
        """Back out an admit that never reached the queue (spool
        backpressure): the spool file stays on disk and will be
        re-submitted later under the SAME id, so no live record may
        linger to collide with it. The append-only manifest keeps the
        'queued' line and gains a non-terminal 'deferred' one — a later
        re-admit simply re-records."""
        with self._lock:
            self._records.pop(req.id, None)
            token = self._spans.pop(req.id, None)
        if token is not None:
            token.finish(state="deferred")
        self._count("requests_deferred")
        self.manifest.record(f"request:{req.id}", "deferred")

    def reject(self, req: ExtractionRequest, reason: str) -> Dict[str, Any]:
        """Backpressure / bad-input terminal state: the request never
        reached the admission queue."""
        return self.finish(
            req, "rejected", error_class="rejected", message=reason
        )

    # -- queries --------------------------------------------------------

    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The live record, falling back to the durable result file for
        requests finished before a daemon restart."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is not None:
                return dict(rec)
        if not _ID_RE.match(request_id or ""):
            return None
        path = os.path.join(self.results_dir, f"{request_id}.json")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {s: 0 for s in REQUEST_STATES}
            for rec in self._records.values():
                s = rec.get("state")
                if s in out:
                    out[s] += 1
        return out

    # -- internals ------------------------------------------------------

    def _count(self, name: str) -> None:
        if self.telemetry is not None and self.telemetry.enabled:
            self.telemetry.metrics.inc(name)

    def _write_result(self, rec: Dict[str, Any]) -> None:
        """tmp + rename so a status reader never sees a torn record."""
        os.makedirs(self.results_dir, exist_ok=True)
        path = os.path.join(self.results_dir, f"{rec['id']}.json")
        tmp = f"{path}.{os.getpid()}.{uuid.uuid4().hex[:6]}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(rec, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
